"""Minimal counters/histograms registry with Prometheus-style labels.

The reference has logging only (SURVEY.md section 5: "Our build should
add a minimal counters/histograms registry from day one since the
north-star metric is a latency").  Exposed by the server at /metrics in
Prometheus text format.

Labels: every metric is a *family*; `family.labels(table="cpu")`
returns a child series keyed by the sorted label set, rendered as
`name{table="cpu"} value`.  The family object itself doubles as the
label-less series (back-compat: call sites that never use labels are
unchanged), but once a family has children the bare series is only
rendered if it was actually touched — a purely-labeled family must not
scrape a phantom `name 0` line.
"""

from __future__ import annotations

import bisect
import random
import threading
from typing import Optional

_DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# long-running operations (compaction rewrites, memtable flushes, cold
# object-store scans): the default buckets top out at 10 s, which
# flattens everything slower into +Inf — these extend to 10 minutes
WIDE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0, 120.0, 300.0, 600.0)


def _escape(value: object) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _label_str(labels: tuple) -> str:
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in labels) + "}"


class _Family:
    """Shared label plumbing: child creation + series naming.  A child
    is a full metric instance of the same class with `_labels` set; it
    renders series lines only (HELP/TYPE come from the family)."""

    __slots__ = ()

    def _init_family(self, labels: tuple) -> None:
        self._labels = labels
        self._children: Optional[dict] = None
        self._touched = False

    def _series(self, suffix: str = "") -> str:
        if self._labels:
            return f"{self.name}{suffix}" + _label_str(self._labels)
        return f"{self.name}{suffix}"

    def labels(self, **kv):
        """Child series for this label set (created on first use).
        Children are cached — `family.labels(table="x")` is cheap enough
        for per-call use, but hot paths should bind the child once."""
        if not kv:
            return self
        assert not self._labels, "labels() on a labeled child"
        key = tuple(sorted(kv.items()))
        with self._lock:
            if self._children is None:
                self._children = {}
            child = self._children.get(key)
            if child is None:
                child = self._new_child(key)
                self._children[key] = child
            return child

    def _snapshot_children(self) -> list:
        with self._lock:
            return [] if not self._children else list(
                self._children.values())

    def remove(self, **kv) -> bool:
        """Deregister one labeled child so it stops rendering — the
        reload discipline for label values that name config-scoped
        entities (a tenant removed from [tenants] must not serve
        phantom series on /metrics forever).  Returns whether a child
        was actually removed."""
        if not kv:
            return False
        key = tuple(sorted(kv.items()))
        with self._lock:
            if not self._children:
                return False
            return self._children.pop(key, None) is not None

    def _render_base(self) -> bool:
        """Whether the label-less series line should be emitted: always
        for a never-labeled metric (back-compat), only-if-touched once
        labeled children exist."""
        return self._children is None or self._touched

    def _header(self, kind: str) -> list:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {kind}"]

    def samples(self) -> list:
        """Family-wide scalar samples as (series_name, labels_dict,
        value) tuples — the meta-ingest scrape surface
        (metric_engine/meta.py).  Mirrors render(): the bare series
        only when it would render, then every labeled child."""
        out = []
        if self._render_base():
            out.extend(self._sample_points())
        for child in self._snapshot_children():
            out.extend(child._sample_points())
        return out


class Counter(_Family):
    __slots__ = ("name", "help", "_value", "_lock", "_labels", "_children",
                 "_touched")

    def __init__(self, name: str, help_: str = "", labels: tuple = ()):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()
        self._init_family(labels)

    def _new_child(self, key: tuple) -> "Counter":
        return Counter(self.name, self.help, labels=key)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            self._touched = True

    @property
    def value(self) -> float:
        return self._value

    @property
    def total(self) -> float:
        """Family-wide sum: the bare series plus every labeled child."""
        return self._value + sum(c._value
                                 for c in self._snapshot_children())

    def _series_lines(self) -> list:
        return [f"{self._series()} {self._value}"]

    def _sample_points(self) -> list:
        return [(self.name, dict(self._labels), self._value)]

    def render(self) -> str:
        out = self._header("counter")
        if self._render_base():
            out += self._series_lines()
        for child in self._snapshot_children():
            out += child._series_lines()
        return "\n".join(out) + "\n"


class Gauge(_Family):
    """A value that goes up and down (queue depth, active queries,
    breaker state).  Rendered with the Prometheus `gauge` type."""

    __slots__ = ("name", "help", "_value", "_lock", "_labels", "_children",
                 "_touched")

    def __init__(self, name: str, help_: str = "", labels: tuple = ()):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()
        self._init_family(labels)

    def _new_child(self, key: tuple) -> "Gauge":
        return Gauge(self.name, self.help, labels=key)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value
            self._touched = True

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            self._touched = True

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount
            self._touched = True

    @property
    def value(self) -> float:
        return self._value

    def _series_lines(self) -> list:
        return [f"{self._series()} {self._value}"]

    def _sample_points(self) -> list:
        return [(self.name, dict(self._labels), self._value)]

    def render(self) -> str:
        out = self._header("gauge")
        if self._render_base():
            out += self._series_lines()
        for child in self._snapshot_children():
            out += child._series_lines()
        return "\n".join(out) + "\n"


_RESERVOIR_SIZE = 4096


class Histogram(_Family):
    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count",
                 "_lock", "_samples", "_rng", "_labels", "_children",
                 "_touched")

    def __init__(self, name: str, help_: str = "",
                 buckets: tuple = _DEFAULT_BUCKETS, labels: tuple = ()):
        self.name = name
        self.help = help_
        self.buckets = buckets
        self._counts = [0] * (len(buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        # true reservoir sample (Vitter's algorithm R): every observation
        # has equal probability of being in the quantile sample, so
        # quantiles track steady state, not start-up
        self._samples: list[float] = []
        self._rng = random.Random(0x5EA)
        self._init_family(labels)

    def _new_child(self, key: tuple) -> "Histogram":
        # children share the family's bucket layout so the le= grid is
        # consistent across every series of the family
        return Histogram(self.name, self.help, self.buckets, labels=key)

    def observe(self, value: float) -> None:
        with self._lock:
            idx = bisect.bisect_left(self.buckets, value)
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            self._touched = True
            if len(self._samples) < _RESERVOIR_SIZE:
                self._samples.append(value)
            else:
                j = self._rng.randrange(self._count)
                if j < _RESERVOIR_SIZE:
                    self._samples[j] = value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
            return s[min(len(s) - 1, int(q * len(s)))]

    def _series_lines(self) -> list:
        out = []
        acc = 0
        base = (_label_str(self._labels)[1:-1] + ","
                if self._labels else "")
        for b, c in zip(self.buckets, self._counts):
            acc += c
            out.append(f'{self.name}_bucket{{{base}le="{b}"}} {acc}')
        out.append(f'{self.name}_bucket{{{base}le="+Inf"}} {self._count}')
        out.append(f"{self._series('_sum')} {self._sum}")
        out.append(f"{self._series('_count')} {self._count}")
        return out

    def _sample_points(self) -> list:
        # sum + count only: rates and means are derivable, and the
        # bucket grid would multiply the scraped-series cardinality
        labels = dict(self._labels)
        return [(f"{self.name}_sum", labels, self._sum),
                (f"{self.name}_count", dict(labels), self._count)]

    def render(self) -> str:
        out = self._header("histogram")
        if self._render_base():
            out += self._series_lines()
        for child in self._snapshot_children():
            out += child._series_lines()
        return "\n".join(out) + "\n"


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_)
                self._metrics[name] = m
            assert isinstance(m, Counter)
            return m

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help_)
                self._metrics[name] = m
            assert isinstance(m, Gauge)
            return m

    def histogram(self, name: str, help_: str = "",
                  buckets: tuple = _DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets)
                self._metrics[name] = m
            assert isinstance(m, Histogram)
            return m

    def family(self, name: str):
        """The registered family for `name`, or None — the typed
        factories (counter/gauge/histogram) create; this only looks
        up (label-child removal at config reload must not mint a
        family of the wrong type as a side effect)."""
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        # snapshot the metric list under the registry lock, render
        # OUTSIDE it (each metric takes its own lock) — a scrape must
        # never serialize against metric registration — and sort by
        # name so scrapes are stable/diffable
        with self._lock:
            metrics = sorted(self._metrics.items())
        return "".join(m.render() for _name, m in metrics)

    def samples(self) -> list:
        """Every family's scalar samples as (series_name, labels_dict,
        value), sorted by family name — the meta-ingest scrape
        snapshot.  Same lock discipline as render(): snapshot the
        metric list under the registry lock, sample outside it."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out = []
        for _name, m in metrics:
            out.extend(m.samples())
        return out


registry = MetricsRegistry()
