"""Force the JAX CPU backend with N virtual devices.

Single home for the tunnel-hazard recipe shared by tests/conftest.py and
__graft_entry__.dryrun_multichip: the axon sitecustomize hook registers
the TPU tunnel plugin at interpreter start and forces
jax_platforms="axon,cpu"; initializing that backend dials a single-client
relay and can wedge the process. The env var alone is too late once jax
is imported, so the jax.config itself must be overridden before the
first backend initialization — and any already-initialized backend that
is non-CPU or has too few devices must be dropped.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(n_devices: int) -> None:
    """Make `jax.devices()` return >= n_devices virtual CPU devices.

    Safe to call before OR after `import jax`, but must run before the
    backend the caller relies on is initialized (an already-initialized
    sufficient CPU backend is left untouched; insufficient or non-CPU
    backends are cleared so re-initialization picks up the new flags).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_FLAG}=(\d+)", flags)
    if m is None or int(m.group(1)) < n_devices:
        want = f"{_FLAG}={n_devices}"
        flags = flags.replace(m.group(0), want) if m else f"{flags} {want}"
        os.environ["XLA_FLAGS"] = flags.strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    from jax._src import xla_bridge as _xb

    backends = getattr(_xb, "_backends", {})
    # jax.devices() would itself initialize a backend — only probe when
    # one already exists
    if backends and (any(p != "cpu" for p in backends)
                     or len(jax.devices()) < n_devices):
        # XLA_FLAGS is parsed once per process, so a rebuilt client won't
        # see a raised device count; jax_num_cpu_devices IS re-read at
        # client creation (but may only be set while no backend exists)
        import jax.extend.backend as jeb

        jeb.clear_backends()
        jax.config.update("jax_num_cpu_devices", n_devices)
