"""Request-scoped tracing: trace IDs, tree-structured spans, a ring of
recent traces, and a slow-query log.

The reference uses field-style tracing events (tracing + EnvFilter,
SURVEY.md section 5) without spans; here spans are first-class and
request-scoped (docs/observability.md):

- every query/write through the HTTP server gets a `trace_id`
  (returned as the `X-Trace-Id` response header);
- `span(name, **fields)` records a real span (span_id/parent_id/
  status/fields) into the ambient trace when one is active — and keeps
  its original behavior (enter/exit logs + a latency histogram) either
  way, so background loops (compaction, manifest merge) stay observable
  without a trace;
- `trace_add(name, n)` attributes counted work (object-store GETs and
  bytes, cache tier hits, per-stage wall time) to the active trace;
- the trace context propagates across regions via the `X-Trace-Id`
  request header, and a downstream region exports its recorded spans
  back on the `X-Trace-Export` response header, so a scatter-gathered
  query yields ONE stitched distributed trace on the coordinator;
- completed traces land in a bounded ring (`GET /debug/traces`,
  `/debug/traces/{id}`), and traces over the slow threshold — or ones
  that died on their deadline — hit the slow-query log plus the
  `slow_queries_total` counter.

Context propagates through asyncio tasks natively and into the named
worker pools via `common.runtimes` (which copies the contextvars
context onto the pool thread), so stage attribution recorded inside
parquet decode / merge workers still lands on the right trace.

Env: HORAEDB_TRACE=1 promotes span logs from DEBUG to INFO.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import random
import threading
import time
from collections import OrderedDict
from typing import Iterator, Optional

from horaedb_tpu.utils.metrics import registry

logger = logging.getLogger("horaedb_tpu.trace")
slow_logger = logging.getLogger("horaedb_tpu.trace.slow")

TRACE_HEADER = "X-Trace-Id"
EXPORT_HEADER = "X-Trace-Export"

# aiohttp caps a header line at 8190 bytes; exports stay safely under
EXPORT_LIMIT = 7000

_SLOW_QUERIES = registry.counter(
    "slow_queries_total",
    "traced requests over the slow threshold (or deadline-exceeded)")
# ops get their OWN slow counter: an 11-minute compaction is slow, but
# it is not a slow QUERY — alerts on slow_queries_total must not fire
# during routine maintenance
_SLOW_OPS = registry.counter(
    "slow_ops_total",
    "background-op traces over their per-op slow threshold")
_TRACES_RECORDED = registry.counter(
    "traces_recorded_total", "traces completed into the trace ring")

_current_span: contextvars.ContextVar[str] = contextvars.ContextVar(
    "horaedb_span", default="")
_current_trace: contextvars.ContextVar[Optional["Trace"]] = \
    contextvars.ContextVar("horaedb_trace", default=None)
_current_span_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "horaedb_span_id", default="")

_LEVEL = logging.INFO if os.environ.get("HORAEDB_TRACE") == "1" else logging.DEBUG

# ids only need uniqueness, not secrecy; one process-wide PRNG seeded
# from urandom, guarded for thread use
_id_rng = random.Random(int.from_bytes(os.urandom(8), "big"))
_id_lock = threading.Lock()


def new_trace_id() -> str:
    with _id_lock:
        return f"{_id_rng.getrandbits(64):016x}"


def _new_span_id() -> str:
    with _id_lock:
        return f"{_id_rng.getrandbits(32):08x}"


def current_span() -> str:
    """Dotted path of the active span ("" outside any span)."""
    return _current_span.get()


def active_trace() -> Optional["Trace"]:
    """The ambient trace, or None outside a traced request."""
    return _current_trace.get()


def current_trace_id() -> str:
    trace = _current_trace.get()
    return trace.trace_id if trace is not None else ""


class Trace:
    """One request's (or background operation's) span buffer +
    counters.  Thread-safe: spans and counts arrive from the event loop
    AND worker-pool threads.  After `finish()` the trace is immutable —
    late adds (a straggler task outliving its request) are dropped, so
    work done after the query ended is attributed to nothing.

    `kind` separates the two trace populations: "query" (HTTP
    query/write requests, the PR-5 surface) and "op" (background
    operations — compaction, flush, WAL commit rounds, rollup passes,
    scrub, health rounds; docs/observability.md, background plane).
    Op traces carry the op name in `op` and may override the recorder's
    slow threshold per-op via `slow_threshold_s`."""

    __slots__ = ("trace_id", "name", "kind", "op", "slow_threshold_s",
                 "root_fields", "root_span_id", "start_ms", "_t0",
                 "spans", "counters", "finished", "_lock")

    def __init__(self, trace_id: str, name: str, kind: str = "query",
                 op: str = "", slow_threshold_s: Optional[float] = None,
                 root_fields: Optional[dict] = None):
        self.trace_id = trace_id
        self.name = name
        self.kind = kind
        self.op = op
        self.slow_threshold_s = slow_threshold_s
        self.root_fields = dict(root_fields or {})
        self.root_span_id = _new_span_id()
        self.start_ms = time.time() * 1e3
        self._t0 = time.perf_counter()
        self.spans: list[dict] = []
        self.counters: dict[str, float] = {}
        self.finished = False
        self._lock = threading.Lock()

    def record(self, span_dict: dict) -> None:
        with self._lock:
            if not self.finished:
                self.spans.append(span_dict)

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            if not self.finished:
                self.counters[name] = self.counters.get(name, 0) + value

    # stitching bounds: a trace must stay ring-sized and exportable no
    # matter what its downstream peers send
    _IMPORT_MAX_SPANS = 512
    _IMPORT_MAX_COUNTERS = 256

    def import_remote(self, payload: dict, parent_id: str) -> None:
        """Stitch a downstream region's exported spans under
        `parent_id` (the RPC span that fetched them): remote roots —
        spans whose parent is not in the export — are reparented, and
        the remote's counters fold into ours.  Defensive by contract:
        entries that aren't span-shaped are skipped and both spans and
        counters are bounded — a peer on another version (or anything
        else answering that port) must never be able to blow up or
        bloat the coordinator's trace."""
        spans = payload.get("spans")
        if not isinstance(spans, list):
            spans = []
        spans = [s for s in spans if isinstance(s, dict)]
        ids = {s.get("span_id") for s in spans}
        with self._lock:
            if self.finished:
                return
            budget = self._IMPORT_MAX_SPANS - len(self.spans)
            for s in spans[:max(0, budget)]:
                if s.get("parent_id") not in ids:
                    s = dict(s, parent_id=parent_id)
                self.spans.append(s)
            counters = payload.get("counters")
            for k, v in (counters.items()
                         if isinstance(counters, dict) else ()):
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool):
                    continue
                if (k not in self.counters
                        and len(self.counters) >= self._IMPORT_MAX_COUNTERS):
                    continue
                self.counters[k] = self.counters.get(k, 0) + v

    def finish(self, status: str = "ok") -> dict:
        with self._lock:
            if self.finished:  # idempotent: first finish wins
                return self.to_dict_locked()
            duration_ms = (time.perf_counter() - self._t0) * 1e3
            self.spans.append({
                "span_id": self.root_span_id, "parent_id": "",
                "name": self.name, "start_ms": round(self.start_ms, 3),
                "duration_ms": round(duration_ms, 3), "status": status,
                "fields": {k: _field(v)
                           for k, v in self.root_fields.items()},
            })
            self.finished = True
            return self.to_dict_locked()

    def to_dict_locked(self) -> dict:
        root = self.spans[-1] if self.finished else None
        return {
            "trace_id": self.trace_id,
            "root": self.name,
            "kind": self.kind,
            "op": self.op,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": (root["duration_ms"] if root else None),
            "status": (root["status"] if root else "active"),
            "counters": dict(self.counters),
            "spans": list(self.spans),
        }


def span_tree(trace_dict: dict) -> dict:
    """Nest a completed trace's flat span list into the JSON tree the
    debug endpoint serves: each node carries its span plus `children`
    sorted by start time.  Orphans (a parent pruned by an export cap)
    attach to the root."""
    spans = sorted(trace_dict.get("spans", []),
                   key=lambda s: s.get("start_ms") or 0)
    nodes = {s["span_id"]: dict(s, children=[]) for s in spans}
    # the trace's own root is the parentless span named after the
    # trace; any other parentless span (stitching leftovers) attaches
    # under it like an orphan
    roots = [nodes[s["span_id"]] for s in spans if not s.get("parent_id")]
    root = next((n for n in roots
                 if n.get("name") == trace_dict.get("root")),
                roots[0] if roots else {"span_id": "", "name":
                                        trace_dict.get("root", ""),
                                        "children": []})
    for s in spans:
        node = nodes[s["span_id"]]
        if node is root:
            continue
        parent = nodes.get(s.get("parent_id") or "")
        (parent["children"] if parent is not None and parent is not node
         else root["children"]).append(node)
    out = {k: v for k, v in trace_dict.items() if k != "spans"}
    out["tree"] = root
    return out


def summarize(trace_dict: dict, top: int = 4) -> str:
    """Compact per-stage summary for the response header / slow log:
    total plus the longest direct children of the root, aggregated by
    span name."""
    spans = trace_dict.get("spans", [])
    roots = {s["span_id"] for s in spans if not s.get("parent_id")}
    by_name: dict[str, float] = {}
    for s in spans:
        if s.get("parent_id") in roots:
            by_name[s["name"]] = (by_name.get(s["name"], 0.0)
                                  + (s.get("duration_ms") or 0.0))
    parts = [f"total={trace_dict.get('duration_ms', 0):.1f}ms"]
    for name, ms in sorted(by_name.items(), key=lambda kv: -kv[1])[:top]:
        parts.append(f"{name}={ms:.1f}ms")
    return ";".join(parts)


def export_payload(trace_dict: dict, limit: int = EXPORT_LIMIT) -> str:
    """Serialize a completed trace for the X-Trace-Export response
    header.  Header lines are size-capped, so over the limit the
    export degrades: span fields are dropped first, then the deepest
    spans (roots survive — the coordinator keeps the region's shape,
    losing only leaf detail), and an oversized counter bag is trimmed
    to its largest entries; `dropped_spans` / `dropped_counters`
    record the cuts.  Guaranteed to terminate and to return a blob
    within `limit` (the floor payload is constant-size)."""
    spans = trace_dict.get("spans", [])
    counters = trace_dict.get("counters", {})
    payload = {"spans": spans, "counters": counters}
    blob = json.dumps(payload, separators=(",", ":"))
    if len(blob) <= limit:
        return blob
    # counters first: a runaway bag (e.g. folded in from many
    # downstream hops) must not eat the whole span budget
    cblob = json.dumps(counters, separators=(",", ":"))
    if len(cblob) > limit // 2:
        kept: dict = {}
        size = 2
        for k, v in sorted(counters.items(),
                           key=lambda kv: -abs(kv[1])):
            entry = len(json.dumps({str(k): v},
                                   separators=(",", ":")))
            if size + entry > limit // 2:
                break
            kept[k] = v
            size += entry
        counters = dict(kept, dropped_counters=len(trace_dict.get(
            "counters", {})) - len(kept))
    slim = [dict(s, fields={}) for s in spans]
    by_id = {s["span_id"]: s for s in slim}

    def depth_of(s: dict) -> int:
        d, seen = 0, set()
        cur = s
        while cur.get("parent_id") in by_id and cur["span_id"] not in seen:
            seen.add(cur["span_id"])
            cur = by_id[cur["parent_id"]]
            d += 1
        return d

    depth = {s["span_id"]: depth_of(s) for s in slim}
    slim.sort(key=lambda s: depth[s["span_id"]])
    while slim:
        payload = {"spans": slim, "counters": counters,
                   "dropped_spans": len(spans) - len(slim)}
        blob = json.dumps(payload, separators=(",", ":"))
        if len(blob) <= limit:
            return blob
        # strictly-shrinking tail cut: empties on the last span rather
        # than spinning on an irreducible payload
        del slim[(len(slim) * 3) // 4:]
    return json.dumps({"spans": [], "counters": {},
                       "dropped_spans": len(spans)},
                      separators=(",", ":"))


def ingest_export(header_value: Optional[str]) -> None:
    """Fold a peer's X-Trace-Export header into the active trace,
    parented under the current span (the RPC span).  Malformed exports
    are dropped — stitching is best-effort observability, never a
    query failure."""
    if not header_value:
        return
    trace = _current_trace.get()
    if trace is None or trace.finished:
        return
    try:
        payload = json.loads(header_value)
        if isinstance(payload, dict):
            trace.import_remote(payload, _current_span_id.get())
    except Exception:  # noqa: BLE001 — observability must not fail RPCs
        logger.warning("dropping malformed trace export (%d bytes)",
                       len(header_value))


class TraceRecorder:
    """Process-wide trace sink: sampling decisions, the bounded ring of
    completed traces, and the slow-query log ([trace] config)."""

    def __init__(self) -> None:
        self.enabled = True
        self.ring_size = 256
        self.slow_threshold_s = 1.0
        self.sample_rate = 1.0
        # op traces get their OWN ring and knobs: a hot background op
        # (a WAL commit round per write group) must never evict query
        # traces, and background ops have very different "slow" scales
        self.op_ring_size = 256
        self.op_slow_threshold_s = 30.0
        self.op_sample_rate = 1.0
        self._ring: "OrderedDict[str, dict]" = OrderedDict()
        self._op_ring: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()
        self._rng = random.Random(0xACE)

    def configure(self, enabled: Optional[bool] = None,
                  ring_size: Optional[int] = None,
                  slow_threshold_s: Optional[float] = None,
                  sample_rate: Optional[float] = None,
                  op_ring_size: Optional[int] = None,
                  op_slow_threshold_s: Optional[float] = None,
                  op_sample_rate: Optional[float] = None) -> None:
        if enabled is not None:
            self.enabled = enabled
        if ring_size is not None:
            self.ring_size = max(1, ring_size)
        if slow_threshold_s is not None:
            self.slow_threshold_s = slow_threshold_s
        if sample_rate is not None:
            self.sample_rate = min(1.0, max(0.0, sample_rate))
        if op_ring_size is not None:
            self.op_ring_size = max(1, op_ring_size)
        if op_slow_threshold_s is not None:
            self.op_slow_threshold_s = op_slow_threshold_s
        if op_sample_rate is not None:
            self.op_sample_rate = min(1.0, max(0.0, op_sample_rate))

    def start(self, name: str, trace_id: Optional[str] = None,
              forced: bool = False, kind: str = "query", op: str = "",
              slow_threshold_s: Optional[float] = None,
              root_fields: Optional[dict] = None) -> Optional[Trace]:
        """A new active trace, or None when tracing is off / this
        request lost the sampling draw.  `forced` (an upstream
        coordinator already traced this request) bypasses sampling —
        a stitched trace must not lose limbs to a local coin flip.
        Op traces (kind="op") draw against `op_sample_rate`."""
        if not self.enabled:
            return None
        rate = self.op_sample_rate if kind == "op" else self.sample_rate
        if not forced and rate < 1.0:
            with self._lock:
                if self._rng.random() >= rate:
                    return None
        return Trace(trace_id or new_trace_id(), name, kind=kind, op=op,
                     slow_threshold_s=slow_threshold_s,
                     root_fields=root_fields)

    def finish(self, trace: Trace, status: str = "ok") -> dict:
        """Complete a trace into its ring; fires the slow log on
        threshold breach or a deadline-exceeded outcome.  Ops use
        their per-op threshold when one was set at start, else the
        recorder's op default."""
        d = trace.finish(status)
        if trace.slow_threshold_s is not None:
            thr = trace.slow_threshold_s
        elif trace.kind == "op":
            thr = self.op_slow_threshold_s
        else:
            thr = self.slow_threshold_s
        slow = (status == "timeout"
                or (d["duration_ms"] or 0) >= thr * 1e3)
        d["slow"] = slow
        ring, size = ((self._op_ring, self.op_ring_size)
                      if trace.kind == "op"
                      else (self._ring, self.ring_size))
        with self._lock:
            ring[trace.trace_id] = d
            ring.move_to_end(trace.trace_id)
            while len(ring) > size:
                ring.popitem(last=False)
        _TRACES_RECORDED.inc()
        if slow:
            (_SLOW_OPS if trace.kind == "op" else _SLOW_QUERIES).inc()
            what = (f"op {trace.op or d['root']}"
                    if trace.kind == "op" else "query")
            slow_logger.warning(
                "[trace] slow %s trace_id=%s root=%s status=%s %s "
                "counters=%s", what, trace.trace_id, d["root"], status,
                summarize(d), json.dumps(d["counters"], sort_keys=True))
        return d

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            d = self._ring.get(trace_id)
            return d if d is not None else self._op_ring.get(trace_id)

    def list(self, limit: int = 50, kind: str = "query",
             op: Optional[str] = None) -> list[dict]:
        """Newest-first summaries for GET /debug/traces.  `kind` picks
        the population: "query" (default — the PR-5 contract), "op",
        or "all" (both rings merged by start time); `op` filters to
        one op name (implies kind="op")."""
        if op is not None:
            kind = "op"
        with self._lock:
            items = []
            if kind in ("all", "query"):
                items += list(self._ring.values())
            if kind in ("all", "op"):
                items += [d for d in self._op_ring.values()
                          if op is None or d.get("op") == op]
        items.sort(key=lambda d: d.get("start_ms") or 0)
        out = []
        for d in reversed(items[-max(0, limit):] if limit else items):
            out.append({"trace_id": d["trace_id"], "root": d["root"],
                        "kind": d.get("kind", "query"),
                        "op": d.get("op", ""),
                        "start_ms": d["start_ms"],
                        "duration_ms": d["duration_ms"],
                        "status": d["status"], "slow": d.get("slow"),
                        "spans": len(d["spans"])})
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._op_ring.clear()


recorder = TraceRecorder()


@contextlib.contextmanager
def trace_scope(trace: Optional[Trace]) -> Iterator[Optional[Trace]]:
    """Bind `trace` as the ambient trace (None = explicit no-trace
    scope).  Spans and trace_add() calls inside — including those in
    tasks and pool work spawned inside — attribute to it."""
    tok = _current_trace.set(trace)
    tok_span = _current_span_id.set(
        trace.root_span_id if trace is not None else "")
    try:
        yield trace
    finally:
        _current_trace.reset(tok)
        _current_span_id.reset(tok_span)


def trace_add(name: str, value: float = 1.0) -> None:
    """Attribute counted work to the active trace (no-op outside)."""
    trace = _current_trace.get()
    if trace is not None:
        trace.add(name, value)


@contextlib.contextmanager
def span(name: str, buckets: Optional[tuple] = None, **fields) -> Iterator[None]:
    """Traced operation: logs enter/exit, observes a latency histogram
    (`buckets` overrides the default layout — pass
    metrics.WIDE_BUCKETS for long-running ops so compaction/flush
    don't flatten into +Inf), and records a tree span into the active
    trace when one is bound."""
    parent_path = _current_span.get()
    full = f"{parent_path}/{name}" if parent_path else name
    token = _current_span.set(full)
    trace = _current_trace.get()
    span_id = parent_id = ""
    tok_sid = None
    if trace is not None and not trace.finished:
        span_id = _new_span_id()
        parent_id = _current_span_id.get() or trace.root_span_id
        tok_sid = _current_span_id.set(span_id)
    t0 = time.perf_counter()
    wall_ms = time.time() * 1e3
    if logger.isEnabledFor(_LEVEL):
        logger.log(_LEVEL, "-> %s %s", full,
                   " ".join(f"{k}={v}" for k, v in fields.items()))
    ok = False
    try:
        yield
        ok = True
    finally:
        _current_span.reset(token)
        if tok_sid is not None:
            _current_span_id.reset(tok_sid)
        elapsed = time.perf_counter() - t0
        if logger.isEnabledFor(_LEVEL):
            if ok:
                logger.log(_LEVEL, "<- %s %.1fms", full, elapsed * 1e3)
            else:
                logger.log(_LEVEL, "<- %s FAILED after %.1fms", full,
                           elapsed * 1e3)
        # failures are observed too — failure-path tail latency matters
        hist_kwargs = {} if buckets is None else {"buckets": buckets}
        registry.histogram(f"span_{name.replace('.', '_')}_seconds",
                           f"span {name} duration",
                           **hist_kwargs).observe(elapsed)
        if span_id:
            trace.record({
                "span_id": span_id, "parent_id": parent_id, "name": name,
                "start_ms": round(wall_ms, 3),
                "duration_ms": round(elapsed * 1e3, 3),
                "status": "ok" if ok else "error",
                "fields": {k: _field(v) for k, v in fields.items()},
            })


def _field(v):
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


@contextlib.contextmanager
def op_trace(op: str, slow_s: Optional[float] = None,
             **fields) -> Iterator[Optional[Trace]]:
    """Trace one background operation (compaction execute, memtable
    flush, WAL group-commit round, rollup roll pass, scrub pass,
    health-monitor round) as its own kind="op" trace tree in the
    recorder's op ring — with the same objstore/cache/rows/bytes
    attribution queries get, because every trace_add()/span() inside
    (including pool work, which inherits the contextvars) lands on the
    ambient trace this binds.

    If a trace is ALREADY ambient — a query-triggered flush inside the
    aggregate pushdown's pre-flush, a synchronous roll under a traced
    admin request — the operation records as a span of that trace
    instead of stealing the scope: the work is attributed to whoever
    caused it.

    `slow_s` overrides the recorder's op slow threshold for this op
    (a compaction's "slow" is minutes; a WAL fsync round's is
    seconds)."""
    if _current_trace.get() is not None:
        with span(op, **fields):
            yield None
        return
    trace = recorder.start(op, kind="op", op=op, slow_threshold_s=slow_s,
                           root_fields=fields)
    if trace is None:
        yield None
        return
    status = "ok"
    with trace_scope(trace):
        try:
            yield trace
        except BaseException:
            status = "error"
            raise
        finally:
            recorder.finish(trace, status=status)
