"""Structured tracing spans.

The reference uses field-style tracing events (tracing + EnvFilter,
SURVEY.md section 5) without spans; here spans are first-class: a
context manager that logs enter/exit with duration and fields, nests via
a contextvar, and feeds the metrics registry so every traced operation
gets a latency histogram for free.

    with span("compaction.execute", inputs=len(task.inputs)):
        ...

Env: HORAEDB_TRACE=1 promotes span logs from DEBUG to INFO.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import time
from typing import Iterator

from horaedb_tpu.utils.metrics import registry

logger = logging.getLogger("horaedb_tpu.trace")

_current_span: contextvars.ContextVar[str] = contextvars.ContextVar(
    "horaedb_span", default="")

_LEVEL = logging.INFO if os.environ.get("HORAEDB_TRACE") == "1" else logging.DEBUG


def current_span() -> str:
    """Dotted path of the active span ("" outside any span)."""
    return _current_span.get()


@contextlib.contextmanager
def span(name: str, **fields) -> Iterator[None]:
    parent = _current_span.get()
    full = f"{parent}/{name}" if parent else name
    token = _current_span.set(full)
    t0 = time.perf_counter()
    if logger.isEnabledFor(_LEVEL):
        logger.log(_LEVEL, "-> %s %s", full,
                   " ".join(f"{k}={v}" for k, v in fields.items()))
    ok = False
    try:
        yield
        ok = True
    finally:
        _current_span.reset(token)
        elapsed = time.perf_counter() - t0
        if logger.isEnabledFor(_LEVEL):
            if ok:
                logger.log(_LEVEL, "<- %s %.1fms", full, elapsed * 1e3)
            else:
                logger.log(_LEVEL, "<- %s FAILED after %.1fms", full,
                           elapsed * 1e3)
        # failures are observed too — failure-path tail latency matters
        registry.histogram(f"span_{name.replace('.', '_')}_seconds",
                           f"span {name} duration").observe(elapsed)
