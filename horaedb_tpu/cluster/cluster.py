"""Cluster facade: routed writes + scatter-gather queries over regions.

Each region is a full MetricEngine (its own tables, manifest, compaction)
under `{root}/region_{id}`.  Series are partitioned by routing_key, so in
a steady-state layout each series lives in one region and gather is a
plain concatenation.  During a split's TTL window the SAME series can
have pre-split rows in the old region and post-split rows in the new one
— rows for one tsid may then arrive from two regions (still no duplicate
(series, timestamp) points, since each write went to exactly one region);
consumers must not assume per-region series disjointness until the old
rule ages out.  (The reference's legacy system forwards via HoraeMeta +
gRPC the same way, SURVEY.md P6.)
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Optional

import pyarrow as pa

from horaedb_tpu.common import deadline as deadline_mod
from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.loops import loops
from horaedb_tpu.common.time_ext import now_ms
from horaedb_tpu.cluster.breaker import (CLOSED as BREAKER_CLOSED,
                                         BreakerConfig, CircuitBreaker)
from horaedb_tpu.cluster.router import RoutingTable, routing_key
from horaedb_tpu.metric_engine import MetricEngine, Sample
from horaedb_tpu.objstore import ObjectStore
from horaedb_tpu.storage.config import StorageConfig
from horaedb_tpu.storage.types import TimeRange
from horaedb_tpu.utils import op_trace, registry, span

logger = logging.getLogger(__name__)

_GATHER_PARTIAL = registry.counter(
    "cluster_gather_partial_total",
    "scatter-gather queries answered with one or more regions missing")
_RPC_TIMEOUTS = registry.counter(
    "cluster_region_rpc_timeouts_total",
    "remote region RPC attempts that hit their timeout budget")
_RPC_RETRIES = registry.counter(
    "cluster_region_rpc_retries_total",
    "bounded single-retry attempts against remote regions")
_HEDGES = registry.counter(
    "cluster_hedged_rpcs_total",
    "hedge requests fired after the hedge delay elapsed")
_HEDGE_WINS = registry.counter(
    "cluster_hedge_wins_total",
    "hedged requests that beat the primary attempt")
_HEALTH_ERRORS = registry.counter(
    "health_monitor_errors_total",
    "heartbeat-round exceptions, by region (region=\"_round\" for "
    "whole-round failures that would otherwise be swallowed)")
_STALE_OWNER_RETRIES = registry.counter(
    "cluster_stale_owner_retries_total",
    "routed retries after a 409 stale-owner answer mid-failover")

# sentinel: a stale-owner retry that produced no result (the region
# stays in the partial answer's missing set)
_GATHER_MISS = object()


@dataclass
class GatherMeta:
    """Outcome marker for a degraded scatter-gather: which routed
    regions contributed nothing and why.  `partial` is the wire-level
    `partial: true` flag the server surfaces on /query* responses."""

    partial: bool = False
    missing_regions: list[int] = field(default_factory=list)
    errors: dict[int, str] = field(default_factory=dict)


class Cluster:
    def __init__(self, regions: dict[int, MetricEngine],
                 routing: RoutingTable, root_path: str, store: ObjectStore,
                 segment_ms: int, config: Optional[StorageConfig]):
        self.regions = regions
        self.routing = routing
        self._root_path = root_path
        self._store = store
        self._segment_ms = segment_ms
        self._config = config
        # heartbeat state (start_health_monitor): remote regions marked
        # dead after consecutive failed pings fail queries fast
        self._health_task: Optional[asyncio.Task] = None
        self._health_fails: dict[int, int] = {}
        # last heartbeat exception per region, surfaced via the health
        # loop's /debug/tasks backlog instead of vanishing into a bare
        # except (the pre-PR-7 behavior)
        self._health_errors: dict[int, dict] = {}
        self.dead_regions: set[int] = set()
        # per-remote-region circuit breakers (docs/robustness.md):
        # consecutive failures open the circuit; the health monitor's
        # pings drive open -> half-open recovery
        self.breakers: dict[int, CircuitBreaker] = {}
        self._breaker_config = BreakerConfig()
        # last load survey (survey_load): region stats + the hot-shard
        # split/rebalance plan, refreshed by the health monitor every
        # _SURVEY_EVERY rounds and surfaced on /debug/tasks
        self.rebalance_survey: Optional[dict] = None
        self._health_rounds = 0
        # ownership re-resolution hook (cluster/replication.py): when a
        # region answers 409 stale-owner mid-failover, _gather calls
        # `await owner_resolver(rid, exc)` for a fresh backend to
        # repoint at and retries ONE hop.  None = no resolver: the 409
        # degrades to a partial answer like any other region failure.
        self.owner_resolver = None

    @property
    def breaker_config(self) -> BreakerConfig:
        return self._breaker_config

    @breaker_config.setter
    def breaker_config(self, cfg: BreakerConfig) -> None:
        """Re-point EXISTING breakers too: a server that applies its
        [breaker] section after remote regions were attached must not
        leave them on the defaults (order-independent configuration)."""
        self._breaker_config = cfg
        for br in self.breakers.values():
            br.config = cfg

    @classmethod
    async def open(cls, root_path: str, store: ObjectStore,
                   num_regions: int = 2,
                   segment_ms: int = 2 * 3600 * 1000,
                   config: Optional[StorageConfig] = None,
                   routing: Optional[RoutingTable] = None,
                   serve: Optional[set] = None) -> "Cluster":
        """`serve` limits which regions get LOCAL engines (default: all
        in the routing table).  A node joining an existing cluster must
        pass the set it owns — opening a region another node is serving
        would race its manifest merger."""
        from horaedb_tpu.objstore import NotFoundError

        if routing is None:
            # the persisted routing table (the cluster's "root table"
            # state) wins over a fresh uniform layout
            try:
                routing = RoutingTable.from_json(
                    (await store.get(f"{root_path}/routing.json")).decode())
            except NotFoundError:
                routing = RoutingTable.uniform(list(range(num_regions)))
        regions = {}
        for rid in routing.region_ids():
            if serve is not None and rid not in serve:
                continue
            regions[rid] = await MetricEngine.open(
                f"{root_path}/region_{rid}", store, segment_ms=segment_ms,
                config=config)
        return cls(regions, routing, root_path, store, segment_ms, config)

    async def save_routing(self) -> None:
        """Persist the routing table (atomic object-store put)."""
        await self._store.put(f"{self._root_path}/routing.json",
                              self.routing.to_json().encode())

    async def split_region(self, region_id: int, pivot_key: int,
                           new_region_id: int, table_ttl_ms: int) -> None:
        """The full split flow, ordered so a failure at any step leaves a
        consistent cluster: (1) provision the new region, (2) build and
        PERSIST the new routing on a copy, (3) swap it live.  Writes
        route to the new region only after the durable routing exists —
        a crash mid-split can orphan an empty region directory, never
        lose a routed write."""

        await self.add_region(new_region_id)
        new_routing = RoutingTable(rules=list(self.routing.rules),
                                   strict_time_routing=self.routing
                                   .strict_time_routing)
        new_routing.split(region_id, pivot_key, new_region_id,
                          now_ms(), table_ttl_ms)
        await self._store.put(f"{self._root_path}/routing.json",
                              new_routing.to_json().encode())
        self.routing = new_routing

    async def close(self) -> None:
        await self.stop_health_monitor()
        for e in self.regions.values():
            await e.close()

    async def add_region(self, region_id: int) -> None:
        """Provision the engine for a region created by a split; layout
        parameters come from the cluster so regions can't diverge."""
        ensure(region_id not in self.regions, f"region {region_id} exists")
        self.regions[region_id] = await MetricEngine.open(
            f"{self._root_path}/region_{region_id}", self._store,
            segment_ms=self._segment_ms, config=self._config)

    def add_remote_region(self, region_id: int, backend) -> None:
        """Attach a region served by another process (e.g. a RemoteRegion
        speaking the server's HTTP API over DCN).  Attaching the first
        remote auto-starts the heartbeat monitor — dead peers must be
        discovered by the monitor, not by the first query that fans out
        to them."""
        ensure(region_id not in self.regions, f"region {region_id} exists")
        self.regions[region_id] = backend
        self._clear_dead_mark(region_id)  # fresh backend, fresh health
        self.breakers[region_id] = CircuitBreaker(str(region_id),
                                                  self.breaker_config)
        if (self._health_task is None
                and getattr(backend, "ping", None) is not None):
            try:
                self.start_health_monitor()
            except RuntimeError:
                # no running event loop (sync caller building a cluster
                # before serving): the operator starts it explicitly
                pass

    def repoint_region(self, region_id: int, backend) -> None:
        """Swap a routed region's backend in place (failover repoint:
        the old owner answered 409, the resolver found the new one).
        Health/breaker state resets — the new backend's record starts
        clean.  The OLD backend is not closed here: mid-gather its
        coroutines may still be unwinding; the caller owns its
        lifecycle."""
        ensure(region_id in self.regions,
               f"region {region_id} not attached")
        self.regions[region_id] = backend
        self._clear_dead_mark(region_id)
        if not isinstance(backend, MetricEngine):
            self.breakers[region_id] = CircuitBreaker(
                str(region_id), self.breaker_config)

    def _clear_dead_mark(self, region_id: int) -> None:
        """A region whose backend changed (adopted locally, re-attached
        remote) must not inherit a stale dead mark, failure count, or
        breaker state."""
        self.dead_regions.discard(region_id)
        self._health_fails.pop(region_id, None)
        self._health_errors.pop(region_id, None)
        self.breakers.pop(region_id, None)

    def enable_lease_routing(self, cache_ttl_ms: int = 1000,
                             backend_factory=None):
        """Wire [replication] into routing: `owner_resolver` answers
        409 stale-owner retries from the region's LIVE lease record in
        this cluster's own store/root — the same record the new
        primary's fence commits against — instead of a stubbed
        callable.  `backend_factory(record)` builds the backend for a
        resolved record; default follows the record's advertised URL
        with a RemoteRegion.  Returns the resolver (its TTL'd cache is
        inspectable in tests)."""
        from horaedb_tpu.cluster.placement import LeaseOwnerResolver
        from horaedb_tpu.cluster.replication import LeaseManager

        manager = LeaseManager(self._store, self._root_path)
        self.owner_resolver = LeaseOwnerResolver(
            manager, backend_factory, cache_ttl_ms=cache_ttl_ms)
        return self.owner_resolver

    # ---- region movement --------------------------------------------------

    async def detach_region(self, region_id: int) -> None:
        """Stop serving a region locally so another node can adopt it.

        The region's data lives in the SHARED object store, so moving a
        region is an ownership handoff, not a data copy: the source
        closes its engine (flushing manifests), the new owner opens one
        over the same paths.  Routing is unchanged; operations routed
        here fail loudly until a backend is re-attached
        (add_remote_region pointing at the new owner, or adopt_region
        to take it back)."""
        ensure(region_id in self.regions, f"region {region_id} not served")
        engine = self.regions.pop(region_id)
        self._clear_dead_mark(region_id)
        close = getattr(engine, "close", None)
        if close is not None:
            await close()

    async def adopt_region(self, region_id: int) -> None:
        """Take over serving a region from the shared object store —
        the destination half of a region move.  Replaces a remote proxy
        if one was attached (closing it); recovery (manifest snapshot +
        delta fold) happens in MetricEngine.open, so an owner that
        crashed without detaching cleanly is still adoptable."""
        old = self.regions.get(region_id)
        ensure(not isinstance(old, MetricEngine),
               f"region {region_id} is already served locally")
        # open FIRST: a failed open must leave any existing proxy
        # attached rather than the region backend-less
        self.regions.pop(region_id, None)
        try:
            await self.add_region(region_id)
        except BaseException:
            if old is not None:
                self.regions[region_id] = old
            raise
        # the data is served locally now; a stale dead mark (from the
        # remote proxy this replaces) must not keep failing queries
        self._clear_dead_mark(region_id)
        if old is not None:
            close = getattr(old, "close", None)
            if close is not None:
                await close()

    def region_loads(self) -> dict[int, int]:
        """Routing-rule share per served region — the cheap signal.
        `region_stats()` is the REAL load signal (rows/bytes actually
        stored); use this only when manifests are unreachable."""
        loads: dict[int, int] = {rid: 0 for rid in self.regions}
        for rule in self.routing.rules:
            if rule.region_id in loads:
                loads[rule.region_id] += 1
        return loads

    async def region_stats(self) -> dict[int, dict]:
        """Per-region data volume: {rid: {rows, bytes, rules, remote}}.
        Local regions read their manifests; remote regions are asked via
        /stats (a dead remote reports rows/bytes -1 rather than failing
        the whole survey)."""
        rules = self.region_loads()

        async def one(rid: int, backend) -> tuple[int, dict]:
            remote = not isinstance(backend, MetricEngine)
            try:
                s = await backend.stats()
                return rid, {"rows": int(s["rows"]),
                             "bytes": int(s["bytes"]),
                             "rules": rules.get(rid, 0), "remote": remote}
            except Exception:
                return rid, {"rows": -1, "bytes": -1,
                             "rules": rules.get(rid, 0), "remote": remote}

        # concurrent: the survey is bounded by ONE slow peer's timeout,
        # not the sum over unreachable peers
        results = await asyncio.gather(*(one(rid, b) for rid, b
                                         in self.regions.items()))
        return dict(results)

    # ---- health -----------------------------------------------------------

    _HEALTH_FAILS = 2

    def start_health_monitor(self, interval_s: float = 5.0) -> None:
        """Heartbeat remote regions so a dead peer is discovered by the
        monitor, not by the first query that fans out to it.  After
        _HEALTH_FAILS consecutive failed pings a region is marked dead
        and routed queries fail IMMEDIATELY with an actionable error;
        a successful ping clears the mark."""
        ensure(self._health_task is None, "health monitor already running")
        self._health_task = loops.spawn(
            lambda hb: self._health_loop(hb, interval_s),
            name="health-monitor", owner="cluster",
            period_s=interval_s, backlog=self._health_backlog)

    def _health_backlog(self) -> dict:
        """/debug/tasks hint: which peers are failing and the last
        heartbeat error per region (with its timestamp)."""
        out = {
            "dead_regions": sorted(self.dead_regions),
            "consecutive_fails": {str(r): n for r, n
                                  in self._health_fails.items() if n},
            "last_errors": {str(r): dict(e) for r, e
                            in self._health_errors.items()},
        }
        if self.rebalance_survey is not None:
            # the hot-shard signal rides the same surface: an operator
            # watching /debug/tasks sees the split/rebalance plan next
            # to the liveness it derives from
            out["rebalance"] = {
                "at_ms": self.rebalance_survey["at_ms"],
                "plan": self.rebalance_survey["plan"],
            }
        return out

    async def stop_health_monitor(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None

    async def check_health_once(self) -> dict[int, bool]:
        """One heartbeat round (the monitor's body; callable directly in
        tests/ops tooling).  Returns {rid: alive} for remote regions.
        Pings run CONCURRENTLY so a round is bounded by one ping
        timeout, not the sum over sick peers."""
        targets = [(rid, ping) for rid, backend in self.regions.items()
                   if (ping := getattr(backend, "ping", None)) is not None]
        # return_exceptions: one ping RAISING (vs. returning False) used
        # to kill the whole round — and the loop's bare except then
        # swallowed it, so a buggy backend was indistinguishable from a
        # healthy idle monitor.  Now it counts, is surfaced, and marks
        # only ITS region failed.
        results = await asyncio.gather(*(p() for _rid, p in targets),
                                       return_exceptions=True)
        alive: dict[int, bool] = {}
        for (rid, _p), res in zip(targets, results):
            if isinstance(res, asyncio.CancelledError):
                raise res
            if isinstance(res, BaseException):
                _HEALTH_ERRORS.labels(region=str(rid)).inc()
                self._health_errors[rid] = {
                    "error": str(res) or type(res).__name__,
                    "at_ms": now_ms()}
                logger.warning("health ping for region %s raised: %s",
                               rid, res)
                ok = False
            else:
                ok = bool(res)
            alive[rid] = ok
            br = self.breakers.get(rid)
            if ok:
                self._health_fails[rid] = 0
                self.dead_regions.discard(rid)
                if br is not None:
                    # open circuits move to half-open on a good ping:
                    # the next real query is the recovery probe
                    br.on_ping_ok()
            else:
                self._health_fails[rid] = self._health_fails.get(rid, 0) + 1
                if self._health_fails[rid] >= self._HEALTH_FAILS:
                    self.dead_regions.add(rid)
                if br is not None:
                    # a dead peer opens its circuit even without query
                    # traffic, so the first query after an outage skips
                    # it instead of paying a connect timeout
                    br.record_failure()
        return alive

    # load surveys (region_stats RPCs to every peer) are heavier than
    # pings: refresh the rebalance plan every Nth health round
    _SURVEY_EVERY = 6

    async def _health_loop(self, hb, interval_s: float) -> None:
        while True:
            hb.beat()
            try:
                # each round is an op trace: ping RPC spans + failure
                # attribution land in /debug/traces?kind=op
                with op_trace("health_round", slow_s=max(interval_s,
                                                         5.0)):
                    await self.check_health_once()
                    self._health_rounds += 1
                    if self._health_rounds % self._SURVEY_EVERY == 0:
                        # per-region load -> hot-shard split/rebalance
                        # recommendation (cached; /debug/tasks +
                        # /admin/rebalance read it)
                        await self.survey_load()
                hb.ok()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — a crash must not
                # kill the loop, but it must not vanish either: count
                # it and surface it on /debug/tasks (last_error)
                hb.error(exc)
                _HEALTH_ERRORS.labels(region="_round").inc()
                logger.exception("health-monitor round failed")
            await asyncio.sleep(interval_s)

    # ---- rebalancing ------------------------------------------------------

    async def propose_rebalance(self, skew_ratio: float = 2.0
                                ) -> list[dict]:
        """Propose region moves from the REAL load signal: regions whose
        stored bytes exceed `skew_ratio` x the mean are flagged with the
        detach/adopt recipe (ownership handoff over the shared store —
        no data copy) plus a split recipe when the hot region serves
        several routing rules (a hot SHARD is relieved by splitting its
        key range, not by moving the whole thing to an equally-sized
        victim).  Returns [] when balanced.  The operator (or an
        external controller loop) executes the moves; this node cannot
        know its peers' capacities."""
        return self._rebalance_from_stats(await self.region_stats(),
                                          skew_ratio)

    def split_pivot(self, region_id: int) -> Optional[int]:
        """Machine-executable split point for a hot region: the
        midpoint of its WIDEST live routing rule (without per-key load
        stats, halving the largest key share is the best static
        guess).  None when the region has no splittable rule."""
        best = None
        for rule in self.routing.rules:
            if rule.region_id != region_id:
                continue
            if rule.end_key - rule.start_key < 2:
                continue
            if best is None or (rule.end_key - rule.start_key
                                > best.end_key - best.start_key):
                best = rule
        if best is None:
            return None
        return best.start_key + (best.end_key - best.start_key) // 2

    def _rebalance_from_stats(self, stats: dict[int, dict],
                              skew_ratio: float) -> list[dict]:
        sized = {rid: s["bytes"] for rid, s in stats.items()
                 if s["bytes"] >= 0}
        if len(sized) < 2:
            return []
        mean = sum(sized.values()) / len(sized)
        if mean <= 0:
            return []
        next_rid = max(list(sized) + [r.region_id
                       for r in self.routing.rules]) + 1
        plan = []
        for rid, b in sorted(sized.items(), key=lambda kv: -kv[1]):
            if b > skew_ratio * mean:
                rules = stats[rid].get("rules", 0)
                entry = {
                    "region": rid,
                    "kind": "move",
                    "bytes": b,
                    "mean_bytes": round(mean),
                    "rules": rules,
                    "reason": f"stores {b / mean:.1f}x the mean",
                    "proposal": ("detach_region({rid}) here; "
                                 "adopt_region({rid}) on a lighter node"
                                 .format(rid=rid)),
                }
                pivot = self.split_pivot(rid) if rules >= 1 else None
                if pivot is not None:
                    # hot shard: halve its key share in place; the new
                    # region can then move independently.  pivot_key +
                    # new_region_id make the entry machine-executable
                    # (cluster/replication.py RebalanceExecutor) —
                    # split_region(region, pivot_key, new_region_id,
                    # table_ttl_ms) runs it verbatim.
                    entry["kind"] = "split"
                    entry["pivot_key"] = pivot
                    entry["new_region_id"] = next_rid
                    entry["split_proposal"] = (
                        f"split_region({rid}, pivot_key={pivot}, "
                        f"new_region_id={next_rid}, "
                        "table_ttl_ms=<table TTL>)")
                    next_rid += 1
                plan.append(entry)
        return plan

    async def survey_load(self, skew_ratio: float = 2.0) -> dict:
        """One load survey: per-region rows/bytes plus the rebalance/
        split plan, cached for /debug/tasks (the health monitor runs
        this periodically) and served by POST /admin/rebalance."""
        stats = await self.region_stats()
        out = {
            "at_ms": now_ms(),
            "skew_ratio": skew_ratio,
            "region_stats": {str(r): s for r, s in sorted(stats.items())},
            "plan": self._rebalance_from_stats(stats, skew_ratio),
        }
        self.rebalance_survey = out
        return out

    # ---- write ------------------------------------------------------------

    async def write(self, samples: list[Sample]) -> None:
        now = now_ms()
        by_region: dict[int, list[Sample]] = {}
        for s in samples:
            rid = self.routing.route_write(
                routing_key(s.name, s.labels), now)
            by_region.setdefault(rid, []).append(s)
        # validate every target BEFORE writing anything: a region created
        # by split() must be provisioned via add_region() first, and a
        # partial multi-region write would be hard to unwind
        missing = [rid for rid in by_region if rid not in self.regions]
        ensure(not missing,
               f"routing targets unprovisioned regions {missing}; call "
               "add_region() after split()")
        dead = [rid for rid in by_region if rid in self.dead_regions]
        ensure(not dead,
               f"write routes to DEAD remote regions {dead} (heartbeat "
               "failing) — failing BEFORE any region commits so a retry "
               "cannot duplicate rows; restore the peer or move the "
               "region (adopt_region / add_remote_region)")
        await asyncio.gather(*(
            self.regions[rid].write(batch)
            for rid, batch in by_region.items()))

    # ---- read (scatter-gather) --------------------------------------------

    def _query_regions(self, metric: str, filters: list[tuple[str, str]],
                       time_range: TimeRange) -> list[int]:
        # a query pins to one key only if the filters form a full series
        # key, which we can't know without the schema — so fan out to all
        # rules alive for the window (RFC accepts full-region scatter).
        # Every routed region must have an attached backend: silently
        # skipping one (e.g. detached mid-move) would return PARTIAL
        # data with no indication.
        rids = self.routing.route_query(None, int(time_range.start),
                                        int(time_range.end))
        missing = [rid for rid in rids if rid not in self.regions]
        ensure(not missing,
               f"query routes to regions {missing} with no attached "
               "backend (moved/detached?); attach via add_remote_region "
               "or adopt_region")
        dead = [rid for rid in rids if rid in self.dead_regions]
        ensure(not dead,
               f"query routes to DEAD remote regions {dead} (heartbeat "
               "failing); restore the peer, or move the region here with "
               "adopt_region / to another node with add_remote_region")
        return rids

    async def query(self, metric: str, filters: list[tuple[str, str]],
                    time_range: TimeRange, field: str = "value") -> pa.Table:
        rids = self._query_regions(metric, filters, time_range)
        tables = await asyncio.gather(*(
            self.regions[rid].query(metric, filters, time_range, field=field)
            for rid in rids))
        # all regions share one result schema, so concat handles the
        # empty case too — no refetch needed
        return pa.concat_tables(tables)

    async def query_downsample(self, metric: str,
                               filters: list[tuple[str, str]],
                               time_range: TimeRange, bucket_ms: int,
                               field: str = "value") -> dict:
        """Scatter-gather downsample: per-region grids merged by tsid.
        Regions are series-disjoint in steady state; during a split's TTL
        window an overlapping tsid combines additively (sum/count/min/
        max; avg recomputed; `last` takes the later region's value)."""
        rids = self._query_regions(metric, filters, time_range)
        results = await asyncio.gather(*(
            self.regions[rid].query_downsample(metric, filters, time_range,
                                               bucket_ms, field=field)
            for rid in rids))
        return _merge_downsample(results, time_range, bucket_ms)

    async def label_values(self, metric: str, tag_key: str,
                           time_range: TimeRange) -> list[str]:
        rids = self._query_regions(metric, [], time_range)
        results = await asyncio.gather(*(
            self.regions[rid].label_values(metric, tag_key, time_range)
            for rid in rids))
        out: set[str] = set()
        for r in results:
            out.update(r)
        return sorted(out)

    # ---- degraded read (resilient scatter-gather) -------------------------
    #
    # The strict methods above fail the whole query when any routed
    # region is unreachable — correct for consistency-sensitive
    # callers, wrong for a serving path where one slow or dead region
    # must not take down every dashboard.  The *_gather variants
    # return the SURVIVING regions' data plus a GatherMeta marker
    # (partial / missing_regions) instead:
    #
    #   * dead regions (heartbeat) and open-circuit regions are
    #     skipped up front — no connect attempt, no timeout wait;
    #   * every remote attempt is bounded by
    #     min(breaker.rpc_timeout, ambient deadline remaining);
    #   * failures and timeouts get ONE bounded retry (reads are
    #     idempotent), breaker bookkeeping on every outcome;
    #   * optional hedged reads: after hedge_delay with no response a
    #     second identical request races the first.

    def breaker_states(self) -> dict[int, str]:
        """Per-region breaker state (ops/debug surface)."""
        return {rid: br.state for rid, br in self.breakers.items()}

    def _gather_targets(self, time_range: TimeRange
                        ) -> tuple[list[int], dict[int, str]]:
        """Split routed regions into live targets and skipped ones
        (with reasons).  Unlike _query_regions, nothing raises."""
        rids = self.routing.route_query(None, int(time_range.start),
                                        int(time_range.end))
        live: list[int] = []
        skipped: dict[int, str] = {}
        for rid in rids:
            if rid not in self.regions:
                skipped[rid] = "no attached backend (moved/detached?)"
            elif rid in self.dead_regions:
                skipped[rid] = "dead (heartbeat failing)"
            else:
                br = self.breakers.get(rid)
                if br is not None and not br.allow():
                    skipped[rid] = "circuit open"
                else:
                    live.append(rid)
        return live, skipped

    async def _call_region(self, rid: int, factory):
        """One region's read RPC under the resilience policy.  `factory`
        builds a fresh coroutine per attempt (retries and hedges need
        independent coroutines)."""
        backend = self.regions[rid]
        br = self.breakers.get(rid)
        if isinstance(backend, MetricEngine):
            # local engines are bounded by the deadline checkpoints in
            # the storage read path, not by an RPC timeout.  The span
            # keeps gather traces region-attributed either way (a
            # remote backend's RPC span nests under this one).
            with span("region_call", region=rid, local=True):
                return await factory()
        cfg = self.breaker_config
        cap = cfg.rpc_timeout.seconds or None
        attempts = 1 + max(0, cfg.retries)
        try:
            with span("region_call", region=rid, local=False):
                return await self._call_region_attempts(rid, factory, br,
                                                        cap, attempts)
        except (asyncio.CancelledError, deadline_mod.DeadlineExceeded):
            # exits that record NO outcome must still release a
            # half-open probe slot this call may have claimed, or the
            # breaker wedges rejecting until the next good ping
            if br is not None:
                br.abort_probe()
            raise

    async def _call_region_attempts(self, rid, factory, br,
                                    cap: Optional[float], attempts: int):
        last_exc: Optional[BaseException] = None
        for attempt in range(attempts):
            budget = deadline_mod.remaining_budget(cap)
            if budget is not None and budget <= 0.001:
                # the REQUEST ran out of time — not the region's fault,
                # so no breaker failure is recorded
                raise deadline_mod.DeadlineExceeded(
                    f"region {rid}: no deadline budget left")
            # when the deadline (not rpc_timeout) is what bounds this
            # attempt, a timeout is the requester's deadline expiring —
            # charging it to the region would open circuits on healthy
            # peers whenever clients send tight deadlines
            deadline_limited = (budget is not None
                                and (cap is None or budget < cap))
            if attempt:
                _RPC_RETRIES.inc()
            try:
                result = await self._hedged_attempt(factory, budget)
                if br is not None:
                    br.record_success()
                return result
            except asyncio.CancelledError:
                raise
            except deadline_mod.DeadlineExceeded:
                raise  # requester's deadline: no breaker bookkeeping
            except asyncio.TimeoutError:
                if deadline_limited:
                    raise deadline_mod.DeadlineExceeded(
                        f"region {rid}: request deadline expired "
                        "mid-RPC")
                _RPC_TIMEOUTS.inc()
                if br is not None:
                    br.record_failure()
                shown = "unbounded" if budget is None else f"{budget:.3f}s"
                last_exc = Error(
                    f"region {rid} RPC timed out (budget {shown})")
            except Exception as exc:
                if br is not None:
                    br.record_failure()
                last_exc = exc
            # the failure may have opened (or re-opened) the circuit:
            # retrying into an open breaker is exactly the load
            # multiplication it exists to prevent.  state (pure read)
            # rather than allow(): allow() on a cooled-down breaker
            # would CLAIM the half-open probe slot we are not about to
            # use
            if br is not None and br.state != BREAKER_CLOSED:
                break
        assert last_exc is not None
        raise last_exc

    async def _hedged_attempt(self, factory, budget: Optional[float]):
        """One policy attempt, optionally hedged: if the primary has
        not answered within hedge_delay, fire a second identical
        request and take whichever SUCCEEDS first.  Reads only —
        callers guarantee idempotency."""
        delay = self.breaker_config.hedge_delay.seconds
        if delay <= 0 or (budget is not None and delay >= budget):
            return await asyncio.wait_for(factory(), budget)
        primary = asyncio.ensure_future(factory())
        tasks = [primary]
        try:
            done, _ = await asyncio.wait({primary}, timeout=delay)
            if done:
                return primary.result()  # raises the primary's error
            _HEDGES.inc()
            hedge = asyncio.ensure_future(factory())
            tasks.append(hedge)
            end = (None if budget is None
                   else time.monotonic() + (budget - delay))
            pending = set(tasks)
            last_exc: Optional[BaseException] = None
            while pending:
                step = (None if end is None
                        else max(0.0, end - time.monotonic()))
                done, pending = await asyncio.wait(
                    pending, timeout=step,
                    return_when=asyncio.FIRST_COMPLETED)
                if not done:
                    raise asyncio.TimeoutError()
                for t in done:
                    if t.exception() is None:
                        if t is not primary:
                            _HEDGE_WINS.inc()
                        return t.result()
                    last_exc = t.exception()
            assert last_exc is not None
            raise last_exc
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
                elif not t.cancelled():
                    # retrieve a loser's error so asyncio never logs
                    # "Task exception was never retrieved" for the
                    # attempt that lost the race
                    t.exception()

    async def _retry_stale_owner(self, rid: int, exc, factory_for):
        """One routed retry after a 409 stale-owner answer: ask the
        resolver for the region's new backend, repoint, re-issue the
        region call.  Any failure — no resolver, resolver error, no
        backend, retry failure — returns _GATHER_MISS and the region
        degrades to a partial answer (X-Missing-Regions), never a hard
        error to the client."""
        if self.owner_resolver is None:
            return _GATHER_MISS
        try:
            backend = await self.owner_resolver(rid, exc)
        except Exception as res_exc:  # noqa: BLE001 — degrade, not fail
            logger.warning("gather: owner re-resolution for region %s "
                           "failed: %s", rid, res_exc)
            return _GATHER_MISS
        if backend is None:
            return _GATHER_MISS
        _STALE_OWNER_RETRIES.inc()
        self.repoint_region(rid, backend)
        try:
            return await self._call_region(rid, factory_for(rid))
        except asyncio.CancelledError:
            raise
        except Exception as retry_exc:  # noqa: BLE001 — one hop only
            logger.warning("gather: stale-owner retry for region %s "
                           "failed: %s", rid, retry_exc)
            return _GATHER_MISS

    async def _gather(self, time_range: TimeRange, factory_for
                      ) -> tuple[dict[int, object], GatherMeta]:
        """Degraded scatter-gather core: returns {rid: result} for the
        regions that answered plus the GatherMeta marker.  Raises only
        when EVERY routed region failed or was skipped — a query that
        can return no region's data at all has nothing to degrade to."""
        from horaedb_tpu.cluster.replication import StaleOwnerError

        live, skipped = self._gather_targets(time_range)
        outcomes = await asyncio.gather(
            *(self._call_region(rid, factory_for(rid)) for rid in live),
            return_exceptions=True)
        results: dict[int, object] = {}
        errors: dict[int, str] = dict(skipped)
        stale: dict[int, StaleOwnerError] = {}
        for rid, out in zip(live, outcomes):
            if isinstance(out, asyncio.CancelledError):
                raise out
            if isinstance(out, StaleOwnerError):
                # mid-failover 409: never a hard error — try ONE
                # routed retry against the re-resolved owner below,
                # else degrade to a partial answer
                stale[rid] = out
                errors[rid] = str(out) or "stale owner"
            elif isinstance(out, BaseException):
                logger.warning("gather: region %s failed: %s", rid, out)
                errors[rid] = str(out) or type(out).__name__
            else:
                results[rid] = out
        for rid, exc in stale.items():
            retried = await self._retry_stale_owner(rid, exc, factory_for)
            if retried is not _GATHER_MISS:
                results[rid] = retried
                errors.pop(rid, None)
        missing = sorted(set(errors))
        if not results:
            dl = deadline_mod.current_deadline()
            if dl is not None and dl.expired:
                # every region "failed" because the request ran out of
                # time — that is a deadline outcome (HTTP 504), not a
                # region failure (400)
                raise deadline_mod.DeadlineExceeded(
                    "query deadline expired before any region answered: "
                    f"{errors}")
            raise Error(f"query failed in every routed region: {errors}")
        if missing:
            _GATHER_PARTIAL.inc()
        meta = GatherMeta(partial=bool(missing), missing_regions=missing,
                          errors=errors)
        return results, meta

    async def query_gather(self, metric: str,
                           filters: list[tuple[str, str]],
                           time_range: TimeRange, field: str = "value"
                           ) -> tuple[pa.Table, GatherMeta]:
        """Degraded row scatter-gather: surviving regions' rows plus
        the partial/missing_regions marker."""
        results, meta = await self._gather(
            time_range,
            lambda rid: lambda: self.regions[rid].query(
                metric, filters, time_range, field=field))
        return pa.concat_tables(list(results.values())), meta

    async def query_downsample_gather(self, metric: str,
                                      filters: list[tuple[str, str]],
                                      time_range: TimeRange,
                                      bucket_ms: int,
                                      field: str = "value"
                                      ) -> tuple[dict, GatherMeta]:
        """Degraded downsample scatter-gather (same per-tsid merge as
        the strict path)."""
        results, meta = await self._gather(
            time_range,
            lambda rid: lambda: self.regions[rid].query_downsample(
                metric, filters, time_range, bucket_ms, field=field))
        return (_merge_downsample(list(results.values()), time_range,
                                  bucket_ms), meta)

    async def label_values_gather(self, metric: str, tag_key: str,
                                  time_range: TimeRange
                                  ) -> tuple[list[str], GatherMeta]:
        """Degraded label-value union across surviving regions."""
        results, meta = await self._gather(
            time_range,
            lambda rid: lambda: self.regions[rid].label_values(
                metric, tag_key, time_range))
        out: set[str] = set()
        for vals in results.values():
            out.update(vals)
        return sorted(out), meta


def _merge_downsample(results: list[dict], time_range: TimeRange,
                      bucket_ms: int) -> dict:
    """Merge per-region downsample grids by tsid (shared by the strict
    and degraded gather paths).  Delegates to the combine module's
    cross-region merge, which allocates only the aggregates the regions
    actually returned — a subset query no longer pays six full
    groups x buckets grids at the coordinator."""
    from horaedb_tpu.storage.combine import merge_downsample_results

    num_buckets = -(-(int(time_range.end) - int(time_range.start))
                    // bucket_ms)
    return merge_downsample_results(results, num_buckets)
