"""Replication plane: WAL shipping, lease-fenced ownership, failover,
and auto-executed rebalance (PAPERS.md: Taurus — separate durability
from serving, ship the log, share the pages, fence with epochs).

The design leans entirely on invariants earlier PRs already proved:

  * SSTs live in the SHARED object store and every flush commits
    through the manifest, so a follower never re-flushes — it adopts
    the primary's SSTs by opening the same region paths.  Only the
    acked-but-unflushed tail (WAL frames -> memtables) needs shipping.
  * WAL frames carry the write seq end to end (PR 3), and replay dedups
    via `__seq__` last-value.  A follower therefore MIRRORS the
    primary's raw CRC-framed segment bytes into a local directory; on
    promotion, `MetricEngine.open` with the mirror as its WAL dir
    replays the tail with seqs preserved — the promoted grids are
    byte-identical with what the primary would have served, and a
    frame shipped twice is exactly-once after the merge.
  * Ownership is a lease record in the shared store with a MONOTONIC
    epoch.  Every flush on a replicated region revalidates the lease
    at the commit point (`IngestStorage.fence`, wal/ingest.py) — a
    primary whose lease was stolen gets StaleEpochError BEFORE the SST
    + manifest commit, so split-brain cannot commit.

Shipping runs over the existing aiohttp plane (`/repl/wal/*`,
`X-Deadline-Ms` / `X-Trace-Id` riding along) or in-process through
`LocalWalSource` (tests, chaos, single-process failover drills).
"""

from __future__ import annotations

import asyncio
import json
import math
import os
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

import logging

from horaedb_tpu.common import deadline as deadline_mod
from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.loops import loops
from horaedb_tpu.common.time_ext import ReadableDuration, now_ms
from horaedb_tpu.objstore import NotFoundError, ObjectStore
from horaedb_tpu.utils import registry, tracing
from horaedb_tpu.wal.log import mirror_watermarks, verify_frames

logger = logging.getLogger(__name__)

# ---- metrics (label + zeroing discipline: per-region gauge children
# are REMOVED when their owner closes, so a departed region's series
# stops being scraped instead of flatlining at its last value) --------

_LAG = registry.gauge(
    "replication_lag_seqs",
    "primary WAL high-watermark minus follower shipped seq, by region")
_SHIPPED_BYTES = registry.counter(
    "replication_shipped_bytes_total",
    "WAL bytes durably mirrored by followers")
_LEASE_EPOCH = registry.gauge(
    "lease_epoch", "current lease epoch, by region (0 = released)")
_FAILOVERS = registry.counter(
    "failovers_total", "lease takeovers, by reason")
_REBALANCE_MOVES = registry.counter(
    "rebalance_moves_total",
    "auto-rebalance plan entries processed, by kind and outcome")
_ELECTIONS = registry.counter(
    "standby_elections_total",
    "standby self-promotion election attempts, by outcome")


class ReplicationError(Error):
    """A replication-plane operation failed."""


class StaleEpochError(ReplicationError):
    """The fencing refusal: this holder's lease epoch is no longer the
    region's current epoch (or its lease expired un-renewed).  Raised
    at the flush commit point — the write was NOT committed."""


class StaleOwnerError(ReplicationError):
    """The wire-level 409: the peer answered 'I no longer own this
    region'.  Carries the new owner's URL when the peer knows it, so
    the coordinator can re-resolve and retry once."""

    def __init__(self, message: str, region: Optional[int] = None,
                 owner: Optional[str] = None):
        super().__init__(message)
        self.region = region
        self.owner = owner


# ---- configuration ----------------------------------------------------------


@dataclass
class ReplicationConfig:
    """[replication]: WAL shipping + lease-fenced ownership.

    A node is a PRIMARY for its engine's regions (it serves the
    shipping endpoints and, when `region` >= 0, holds that region's
    lease and fences every flush on it).  Setting `primary_url` makes
    it ALSO a follower: it tails that peer's WAL into `mirror_dir`,
    ready to promote.
    """

    enabled: bool = False
    # lease-fenced region this node claims at startup (-1 = serve +
    # ship only, no lease)
    region: int = -1
    # lease holder identity; empty derives "server:<port>"
    holder: str = ""
    lease_ttl: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(10))
    renew_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(2))
    # follower mode: tail this peer's WAL into mirror_dir
    primary_url: str = ""
    mirror_dir: str = ""
    poll_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_millis(500))
    # per-read-RPC byte cap for tail shipping (a transient wire chunk,
    # not a resident budget)
    max_batch_bytes: int = 4 << 20
    rpc_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(10))
    # follower liveness horizon for RETENTION: a follower silent (no
    # poll, no ack) longer than this stops pinning sealed segments — a
    # follower that died for good must not grow primary disk without
    # bound.  It is never deregistered: its next poll refreshes
    # liveness and it resyncs anything truncated meanwhile from the
    # shared SSTs (flushed_seqs) + a fresh listing.
    follower_ttl: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(60))


@dataclass
class RebalanceConfig:
    """[rebalance]: the safety envelope under which the health
    monitor's split/detach recommendations (survey_load) execute
    automatically.  Defaults are conservative: disabled, and dry-run
    even when enabled — an operator must opt in twice before the
    executor changes the routing table on its own."""

    enabled: bool = False
    # record what WOULD run without executing it
    dry_run: bool = True
    max_concurrent_moves: int = 1
    # per-region minimum gap between executed moves
    cooldown: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(300))
    # refuse to move/split a region whose replica is lagging (vacuously
    # healthy when no replica-health probe is wired)
    require_replica_healthy: bool = True
    max_replica_lag_seqs: int = 0
    skew_ratio: float = 2.0
    interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(30))
    # TTL applied to the pre-split rule (how long the old region keeps
    # answering queries for the split range)
    table_ttl_ms: int = 7 * 24 * 3600 * 1000


@dataclass
class FailoverConfig:
    """[failover]: standby self-promotion.  A follower with this on
    runs a StandbyMonitor that watches the primary's lease record and,
    once the lease sits expired past a jittered grace window, races
    `promote()` against sibling standbys — the lease's monotonic-epoch
    acquire IS the election.  Disabled by default: failover stays an
    operator/placement-controller decision unless opted in."""

    enabled: bool = False
    # how long an EXPIRED lease must stay unclaimed before this
    # standby runs an election.  The grace window absorbs a primary
    # that is slow to renew (store hiccup, GC pause) without flapping;
    # config validation refuses a grace shorter than one renewal.
    grace: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(5))
    # per-election random extra wait, as a fraction of `grace` —
    # decorrelates sibling standbys so the freshest (which also defers
    # least, see the fitness check) usually acquires uncontested
    jitter: float = 0.5
    # lease-record poll cadence for the monitor loop
    check_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_millis(500))
    # pause between publishing our fitness record and reading the
    # siblings' — the pre-acquire "freshest mirror wins" exchange
    fitness_wait: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_millis(200))
    # flap suppression: after a LOST or failed election this standby
    # sits out at least this long before arming another grace window
    cooldown: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(5))


# ---- lease-fenced ownership -------------------------------------------------


@dataclass
class LeaseRecord:
    region: int
    holder: str
    epoch: int
    expires_at_ms: int
    # the holder's serving address — what lease-backed routing resolves
    # a region's owner to after a failover (empty for in-process
    # holders; the resolver then needs a holder->backend factory)
    url: str = ""

    def to_json(self) -> bytes:
        return json.dumps({
            "region": self.region, "holder": self.holder,
            "epoch": self.epoch, "expires_at_ms": self.expires_at_ms,
            "url": self.url,
        }).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "LeaseRecord":
        d = json.loads(blob)
        return cls(region=int(d["region"]), holder=str(d["holder"]),
                   epoch=int(d["epoch"]),
                   expires_at_ms=int(d["expires_at_ms"]),
                   url=str(d.get("url", "")))


class LeaseManager:
    """Per-region lease records under `{root}/leases/` in the SHARED
    object store — the same store every region's manifests live in, so
    whoever can commit data can also see who owns it.

    Acquire is read-bump-put with a read-back verify: the epoch is
    strictly monotonic (a new holder's epoch is always greater than
    every epoch that ever committed), and a racing acquirer that
    overwrote our record between put and read-back wins — we fail.
    The *commit-time* guarantee does not rest on acquire being atomic:
    every flush revalidates the record via `Lease.check()` at the
    fencing point, so a holder that lost the race can never commit.
    """

    def __init__(self, store: ObjectStore, root_path: str,
                 clock: Callable[[], int] = now_ms):
        self.store = store
        self.root_path = root_path
        self._clock = clock

    def _path(self, region: int) -> str:
        return f"{self.root_path}/leases/region_{region}.json"

    async def read(self, region: int) -> Optional[LeaseRecord]:
        try:
            blob = await self.store.get(self._path(region))
        except NotFoundError:
            return None
        return LeaseRecord.from_json(blob)

    async def acquire(self, region: int, holder: str,
                      ttl_ms: int, url: str = "") -> "Lease":
        """Take (or retake) the region's lease, bumping the epoch.
        Raises ReplicationError while another holder's lease is live."""
        now = self._clock()
        cur = await self.read(region)
        if (cur is not None and cur.holder != holder
                and cur.expires_at_ms > now):
            raise ReplicationError(
                f"region {region} lease held by {cur.holder!r} "
                f"(epoch {cur.epoch}, {cur.expires_at_ms - now}ms left)")
        epoch = (cur.epoch if cur is not None else 0) + 1
        rec = LeaseRecord(region=region, holder=holder, epoch=epoch,
                          expires_at_ms=now + ttl_ms, url=url)
        await self.store.put(self._path(region), rec.to_json())
        back = await self.read(region)
        if back is None or back.holder != holder or back.epoch != epoch:
            raise ReplicationError(
                f"region {region} lease acquire lost a race "
                f"(now held by {getattr(back, 'holder', None)!r})")
        _LEASE_EPOCH.labels(region=str(region)).set(epoch)
        logger.info("lease: %r acquired region %d at epoch %d",
                    holder, region, epoch)
        return Lease(self, rec)


class Lease:
    """One holder's live claim on a region — the FENCE object installed
    on the region's ingest tables (`IngestStorage.fence`): `check()`
    runs at every flush's commit point and raises StaleEpochError when
    this epoch is no longer the region's current one."""

    def __init__(self, manager: LeaseManager, record: LeaseRecord):
        self.manager = manager
        self.record = record
        self.lost = False
        self._renew_task: Optional[asyncio.Task] = None
        self._stopping = False
        self.on_lost: Optional[Callable[[BaseException], None]] = None

    @property
    def region(self) -> int:
        return self.record.region

    @property
    def epoch(self) -> int:
        return self.record.epoch

    def valid_locally(self) -> bool:
        """Cheap local view: not known-lost and not expired un-renewed.
        Conservative — expiry here refuses even if no one stole the
        lease yet (better to under-serve than double-commit)."""
        return (not self.lost
                and self.record.expires_at_ms > self.manager._clock())

    async def check(self) -> None:
        """The fencing read: the store's CURRENT record must still be
        (this holder, this epoch) and unexpired.  One store get per
        flush — flushes already pay an SST put + manifest commit, so
        the fence adds a small fraction, and it makes commit-time
        ownership a property of the SHARED store, not local belief."""
        if self.lost:
            raise StaleEpochError(
                f"region {self.region}: lease lost (epoch {self.epoch})")
        if not self.valid_locally():
            self.lost = True
            raise StaleEpochError(
                f"region {self.region}: lease expired un-renewed "
                f"(epoch {self.epoch})")
        cur = await self.manager.read(self.region)
        if (cur is None or cur.epoch != self.epoch
                or cur.holder != self.record.holder):
            self.lost = True
            got = "gone" if cur is None else (
                f"held by {cur.holder!r} at epoch {cur.epoch}")
            raise StaleEpochError(
                f"region {self.region}: fencing check failed — our "
                f"epoch {self.epoch}, record {got}")

    async def renew(self) -> None:
        """Extend the lease TTL; verifies the record is still ours
        first (a renewal must never resurrect a stolen lease)."""
        cur = await self.manager.read(self.region)
        if (cur is None or cur.epoch != self.epoch
                or cur.holder != self.record.holder):
            self.lost = True
            raise StaleEpochError(
                f"region {self.region}: lease stolen before renewal "
                f"(our epoch {self.epoch})")
        rec = LeaseRecord(
            region=self.region, holder=self.record.holder,
            epoch=self.epoch,
            expires_at_ms=self.manager._clock() + self._ttl_ms(),
            url=self.record.url)
        await self.manager.store.put(self.manager._path(self.region),
                                     rec.to_json())
        self.record = rec

    def _ttl_ms(self) -> int:
        # the original grant length, preserved across renewals
        return getattr(self, "_granted_ttl_ms", 10_000)

    def grant_ttl_ms(self, ttl_ms: int) -> None:
        self._granted_ttl_ms = ttl_ms

    def start_renewal(self, interval_s: float, ttl_ms: int) -> None:
        """Heartbeat loop (common/loops.py): renew every `interval_s`;
        a stolen lease stops the loop and fires `on_lost` so the owner
        can start answering 409 stale-owner."""
        ensure(self._renew_task is None, "lease renewal already running")
        self.grant_ttl_ms(ttl_ms)
        self._renew_task = loops.spawn(
            lambda hb: self._renew_loop(hb, interval_s),
            name=f"lease-renew:region_{self.region}", kind="lease-renew",
            owner="replication", period_s=interval_s,
            backlog=lambda: {"region": self.region, "epoch": self.epoch,
                             "lost": self.lost,
                             "expires_at_ms": self.record.expires_at_ms})

    async def _renew_loop(self, hb, interval_s: float) -> None:
        while not self._stopping:
            await asyncio.sleep(interval_s)
            if self._stopping:
                return
            hb.beat()
            try:
                await self.renew()
                hb.ok()
            except asyncio.CancelledError:
                raise
            except StaleEpochError as exc:
                hb.error(exc)
                logger.warning("lease renew: %s", exc)
                if self.on_lost is not None:
                    self.on_lost(exc)
                return
            except Exception as exc:  # noqa: BLE001 — transient store
                # failure: keep trying; the lease simply expires if the
                # store stays unreachable (the conservative outcome)
                hb.error(exc)
                logger.warning("lease renew for region %d failed: %s",
                               self.region, exc)

    async def stop_renewal(self) -> None:
        self._stopping = True
        if self._renew_task is not None:
            self._renew_task.cancel()
            try:
                await self._renew_task
            except asyncio.CancelledError:
                pass
            self._renew_task = None

    async def release(self) -> None:
        """Voluntary handoff: stop renewing and, if the record is still
        ours, replace it with an already-expired TOMBSTONE that keeps
        the epoch (holder cleared, expires_at_ms=0).  Deleting instead
        would restart the next acquire at epoch 1, breaking the strict
        monotonicity every epoch consumer is promised ('always greater
        than every epoch that ever committed') — the tombstone makes a
        release/re-acquire cycle continue the sequence.  The epoch
        gauge child is removed (zeroing discipline) — a released
        region has no current holder."""
        await self.stop_renewal()
        cur = await self.manager.read(self.region)
        if (cur is not None and cur.epoch == self.epoch
                and cur.holder == self.record.holder):
            tomb = LeaseRecord(region=self.region, holder="",
                               epoch=self.epoch, expires_at_ms=0)
            await self.manager.store.put(
                self.manager._path(self.region), tomb.to_json())
        self.lost = True
        _LEASE_EPOCH.remove(region=str(self.region))


def install_fence(engine, lease: Optional[Lease]) -> None:
    """Point every WAL-fronted table of `engine` at `lease` as its
    flush-time fence (None = unfence).  The wal/ layer never imports
    cluster/ — the fence is duck-typed (`await fence.check()`)."""
    for table in engine.tables.values():
        if getattr(table, "wal", None) is not None:
            table.fence = lease


# ---- primary side: the shipping hub ----------------------------------------


class ReplicationHub:
    """Primary-side shipping surface over one engine's per-table WALs:
    segment listings, frame-aligned tail reads, follower acks, and the
    retention hook that keeps sealed segments alive until every
    registered follower acked past them.

    With no followers registered, retention defers to the WAL's
    default (always deletable) — a single-copy node behaves
    bit-for-bit as before.  Correctness does not depend on the hook:
    a segment only becomes deletable once all its seqs are flushed,
    and flushed rows live in the SHARED SSTs a follower adopts; the
    hook is what keeps the *acked high-watermark* meaningful, so a
    promotion knows exactly how fresh its mirror is.

    Retention only honors LIVE followers: one silent past
    `follower_ttl` (no poll, no ack) stops pinning segments — it
    registered on its first poll with no deregistration path, so
    without a liveness horizon a follower dead for good would block
    WAL truncation forever.  A stale follower that comes back
    refreshes its liveness on the next poll and resyncs from the
    listing + the shared-SST floor.
    """

    def __init__(self, engine, config: Optional[ReplicationConfig] = None,
                 clock: Callable[[], int] = now_ms):
        self.engine = engine
        self.config = config or ReplicationConfig()
        self._clock = clock
        self._closed = False
        # follower -> {log -> highest acked (durably mirrored) seq}
        self._acks: dict[str, dict[str, int]] = {}
        # follower -> last poll/ack wall ms (liveness for retention)
        self._last_seen: dict[str, int] = {}
        for name, wal in self._wals().items():
            wal.retention = self._retention_for(name)

    def _wals(self) -> dict:
        return {name: t.wal for name, t in self.engine.tables.items()
                if getattr(t, "wal", None) is not None}

    def _live(self, follower_id: str) -> bool:
        ttl_ms = int(self.config.follower_ttl.seconds * 1000)
        last = self._last_seen.get(follower_id, 0)
        return self._clock() - last <= ttl_ms

    def _retention_for(self, log: str):
        def allow_delete(segment_id: int, max_seq: int) -> bool:
            del segment_id
            return all(acks.get(log, 0) >= max_seq
                       for fid, acks in self._acks.items()
                       if self._live(fid))
        return allow_delete

    def register_follower(self, follower_id: str) -> None:
        self._acks.setdefault(follower_id, {})
        self._last_seen[follower_id] = self._clock()

    def ack(self, follower_id: str, acks: dict[str, int]) -> None:
        mine = self._acks.setdefault(follower_id, {})
        self._last_seen[follower_id] = self._clock()
        for log, seq in acks.items():
            mine[log] = max(mine.get(log, 0), int(seq))

    def snapshot(self, follower_id: Optional[str] = None) -> dict:
        """One poll's worth of listing state: per-log segments + high
        watermarks.  Passing `follower_id` registers the follower (its
        first poll arms retention)."""
        if self._closed:
            # a closed hub (primary dead or demoted) must REFUSE to
            # answer, matching a dead HTTP primary: an empty listing
            # would read as "everything truncated" and a tailing
            # follower would drop its whole mirror
            raise ReplicationError("replication hub closed")
        if follower_id:
            self.register_follower(follower_id)
        wals = self._wals()
        return {
            "logs": {name: wal.segments() for name, wal in wals.items()},
            "high_watermarks": {name: wal.high_watermark
                                for name, wal in wals.items()},
            # seqs at or below these are committed to shared SSTs (and
            # may already be truncated): followers count them caught up
            # without shipping
            "flushed_seqs": {name: wal.flushed_seq
                             for name, wal in wals.items()},
        }

    async def read_tail(self, log: str, segment_id: int, offset: int,
                        max_bytes: int) -> Optional[tuple[bytes, bool]]:
        if self._closed:
            raise ReplicationError("replication hub closed")
        wal = self._wals().get(log)
        if wal is None:
            raise ReplicationError(f"unknown wal log {log!r}")
        return await wal.read_tail(segment_id, offset, max_bytes)

    def status(self) -> dict:
        """/repl/status + /debug/tasks surface.  `retention_held_by`
        names the LIVE followers currently pinning otherwise-deletable
        sealed segments (fully SST-covered, un-acked, follower not yet
        past the liveness TTL) — the stuck-retention signal an
        operator greps for when primary disk grows; `stale` followers
        no longer pin anything."""
        wals = self._wals()
        hw = {name: wal.high_watermark for name, wal in wals.items()}
        flushed = {name: wal.flushed_seq for name, wal in wals.items()}
        # per log, the newest seq in a sealed + fully-flushed segment:
        # deletable but for follower acks (flushed_seq is a contiguous
        # prefix, so max_seq <= flushed_seq covers the whole segment)
        blockable = {
            name: max((s["max_seq"] for s in wal.segments()
                       if s["sealed"]
                       and s["max_seq"] <= wal.flushed_seq), default=0)
            for name, wal in wals.items()}
        followers = {}
        held_by = []
        for fid, acks in self._acks.items():
            lag = max((hw.get(log, 0) - max(acks.get(log, 0),
                                            flushed.get(log, 0))
                       for log in hw), default=0)
            live = self._live(fid)
            followers[fid] = {"acks": dict(acks), "lag_seqs": lag,
                              "stale": not live,
                              "last_seen_ms": self._last_seen.get(fid, 0)}
            if live and any(acks.get(log, 0) < m
                            for log, m in blockable.items() if m):
                held_by.append(fid)
        return {
            "high_watermarks": hw,
            "followers": followers,
            "retention_held_by": sorted(held_by),
            "follower_ttl_ms": int(
                self.config.follower_ttl.seconds * 1000),
        }

    def close(self) -> None:
        self._closed = True
        for wal in self._wals().values():
            wal.retention = None
        self._acks = {}
        self._last_seen = {}


# ---- wal sources (the follower's view of a primary) -------------------------


class LocalWalSource:
    """In-process source over a ReplicationHub — tests, chaos drills,
    and single-process multi-region failover."""

    def __init__(self, hub: ReplicationHub, follower_id: str):
        self.hub = hub
        self.follower_id = follower_id

    async def snapshot(self) -> dict:
        return self.hub.snapshot(self.follower_id)

    async def read(self, log: str, segment_id: int, offset: int,
                   max_bytes: int) -> Optional[tuple[bytes, bool]]:
        return await self.hub.read_tail(log, segment_id, offset, max_bytes)

    async def ack(self, acks: dict[str, int]) -> None:
        self.hub.ack(self.follower_id, acks)

    async def close(self) -> None:
        pass


class HttpWalSource:
    """Shipping over the existing aiohttp plane (`/repl/wal/*`).  Every
    RPC carries an explicit timeout plus the ambient deadline/trace
    headers, exactly like the cluster's region RPCs."""

    def __init__(self, base_url: str, follower_id: str,
                 timeout_s: float = 10.0, session=None):
        self.base_url = base_url.rstrip("/")
        self.follower_id = follower_id
        self.timeout_s = timeout_s
        self._session = session
        self._own_session = session is None

    async def _ensure_session(self):
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    def _budget(self):
        import aiohttp

        dl = deadline_mod.current_deadline()
        if dl is not None:
            dl.check()
        budget = deadline_mod.remaining_budget(self.timeout_s)
        headers = {}
        if dl is not None and dl.deadline_at is not None:
            headers["X-Deadline-Ms"] = str(
                max(1, math.floor((budget or 0.0) * 1000)))
        trace = tracing.active_trace()
        if trace is not None and not trace.finished:
            headers[tracing.TRACE_HEADER] = trace.trace_id
        return aiohttp.ClientTimeout(total=budget), headers

    async def snapshot(self) -> dict:
        session = await self._ensure_session()
        timeout, headers = self._budget()
        async with session.get(
                self.base_url + "/repl/wal/segments",
                params={"follower": self.follower_id},
                timeout=timeout, headers=headers) as resp:
            if resp.status != 200:
                text = await resp.text()
                raise ReplicationError(
                    f"{self.base_url}/repl/wal/segments returned "
                    f"{resp.status}: {text[:200]}")
            return json.loads(await resp.read())

    async def read(self, log: str, segment_id: int, offset: int,
                   max_bytes: int) -> Optional[tuple[bytes, bool]]:
        session = await self._ensure_session()
        timeout, headers = self._budget()
        async with session.get(
                self.base_url + "/repl/wal/read",
                params={"log": log, "segment": str(segment_id),
                        "offset": str(offset),
                        "max_bytes": str(max_bytes)},
                timeout=timeout, headers=headers) as resp:
            if resp.status != 200:
                text = await resp.text()
                raise ReplicationError(
                    f"{self.base_url}/repl/wal/read returned "
                    f"{resp.status}: {text[:200]}")
            if resp.headers.get("X-Wal-Gone") == "1":
                return None
            sealed = resp.headers.get("X-Wal-Sealed") == "1"
            return await resp.read(), sealed

    async def ack(self, acks: dict[str, int]) -> None:
        session = await self._ensure_session()
        timeout, headers = self._budget()
        async with session.post(
                self.base_url + "/repl/wal/ack",
                json={"follower": self.follower_id, "acks": acks},
                timeout=timeout, headers=headers) as resp:
            if resp.status != 200:
                text = await resp.text()
                raise ReplicationError(
                    f"{self.base_url}/repl/wal/ack returned "
                    f"{resp.status}: {text[:200]}")

    async def close(self) -> None:
        if self._own_session and self._session is not None:
            await self._session.close()
            self._session = None


# ---- follower: mirror the primary's WAL bytes -------------------------------


class WalFollower:
    """Tails a primary's per-table WALs into a local mirror directory,
    byte-for-byte and frame-verified.

    Mirror layout is EXACTLY the engine's WAL layout
    (`{mirror_dir}/{table}/{id:020d}.wal`), so promotion is simply
    `MetricEngine.open(..., wal dir = mirror_dir)`: PR 3's replay
    rebuilds the memtables with seqs preserved and no new replay
    machinery exists to diverge.  Each appended chunk is truncated to
    the longest verified-frame prefix (`verify_frames`) and fsynced
    before it is acked, so the primary's retention watermark only ever
    reflects DURABLY mirrored frames.
    """

    def __init__(self, source, mirror_dir: str,
                 config: Optional[ReplicationConfig] = None,
                 region: Optional[int] = None):
        self.source = source
        self.mirror_dir = mirror_dir
        self.config = config or ReplicationConfig()
        self.region = region
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        # log -> {segment_id -> durably mirrored bytes}
        self._progress: dict[str, dict[int, int]] = {}
        # log -> highest seq durably mirrored
        self.shipped_seqs: dict[str, int] = {}
        self._hw: dict[str, int] = {}
        # log -> primary's SST-committed floor: seqs below it live in
        # the shared store and never need shipping
        self._flushed: dict[str, int] = {}
        self._lag_child = _LAG.labels(
            region=str(region if region is not None else "_"))
        self._lag_child.set(0)

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> None:
        ensure(self._task is None, "wal follower already started")
        interval = self.config.poll_interval.seconds
        self._task = loops.spawn(
            lambda hb: self._ship_loop(hb, interval),
            name=f"wal-ship:{self.mirror_dir}", kind="wal-ship",
            owner="replication", period_s=interval,
            backlog=lambda: {"lag_seqs": self.lag(),
                             "shipped_seqs": dict(self.shipped_seqs),
                             "high_watermarks": dict(self._hw)})

    async def close(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.source.close()
        _LAG.remove(region=str(self.region if self.region is not None
                               else "_"))

    async def retarget(self, source) -> None:
        """Point the ship loop at a NEW primary (an election loser
        falling back to tailing the winner).  The mirror is kept: its
        bytes are the old primary's stream, which the winner replayed
        from its own mirror of the same stream, so per-segment sizes
        stay valid append offsets; a divergent tail (we out-shipped
        the winner) fails frame verification on the next read and
        takes the existing resync-from-scratch path for that segment."""
        old = self.source
        self.source = source
        await old.close()

    async def _ship_loop(self, hb, interval_s: float) -> None:
        while not self._stopping:
            hb.beat()
            try:
                shipped = await self.poll_once()
                hb.ok()
                del shipped
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — the primary may
                # be mid-restart or mid-death; shipping resumes where
                # the mirror left off on the next poll
                hb.error(exc)
                logger.warning("wal shipping poll failed: %s", exc)
            await asyncio.sleep(interval_s)

    # ---- one shipping pass ------------------------------------------------

    def _mirror_path(self, log: str, segment_id: int) -> str:
        return os.path.join(self.mirror_dir, log, f"{segment_id:020d}.wal")

    def _mirrored_size(self, log: str, segment_id: int) -> int:
        known = self._progress.get(log, {}).get(segment_id)
        if known is not None:
            return known
        try:
            return os.path.getsize(self._mirror_path(log, segment_id))
        except OSError:
            return 0

    def _recover_log_blocking(self, log: str) -> tuple[dict, int]:
        """Crash-resume: rebuild per-segment progress and the shipped
        watermark from the mirror's own frames (a restarted follower
        must not report full lag over bytes it already holds).  A torn
        tail from a death mid-append is truncated so appends resume on
        a frame boundary."""
        d = os.path.join(self.mirror_dir, log)
        prog: dict[int, int] = {}
        max_seq = 0
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return prog, max_seq
        for name in names:
            if not name.endswith(".wal"):
                continue
            try:
                seg_id = int(name[:-4])
            except ValueError:
                continue
            path = os.path.join(d, name)
            with open(path, "rb") as f:
                blob = f.read()
            aligned, seq, _count = verify_frames(blob)
            if aligned < len(blob):
                with open(path, "r+b") as f:
                    f.truncate(aligned)
                    f.flush()
                    os.fsync(f.fileno())
            prog[seg_id] = aligned
            max_seq = max(max_seq, seq)
        return prog, max_seq

    async def poll_once(self) -> int:
        """One full shipping pass: list, tail-read every segment with
        new committed bytes, mirror + fsync, drop segments the primary
        truncated, then ack the durable watermark.  Returns total bytes
        shipped this pass."""
        snap = await self.source.snapshot()
        self._hw = {log: int(hw)
                    for log, hw in snap.get("high_watermarks", {}).items()}
        self._flushed = {log: int(seq) for log, seq
                         in snap.get("flushed_seqs", {}).items()}
        total = 0
        for log, segs in snap.get("logs", {}).items():
            if log not in self._progress:
                prog0, seq0 = await asyncio.to_thread(
                    self._recover_log_blocking, log)
                self._progress[log] = prog0
                if seq0:
                    self.shipped_seqs[log] = max(
                        self.shipped_seqs.get(log, 0), seq0)
            prog = self._progress.setdefault(log, {})
            seen: set[int] = set()
            for seg in segs:
                seg_id = int(seg["id"])
                seen.add(seg_id)
                total += await self._ship_segment(log, seg_id,
                                                 int(seg["size"]))
            # segments gone from the listing were truncated (all seqs
            # flushed to shared SSTs + acked): the mirror drops them
            # too, bounding follower disk to the primary's WAL backlog.
            # Only honored when the remote's flushed floor COVERS what
            # we shipped for this log — a listing that drops segments
            # without the SST floor to justify it is a dying/aborted
            # primary, and these mirror bytes are the failover
            # candidate's only copy of the acked tail.
            if self._flushed.get(log, 0) >= self.shipped_seqs.get(log, 0):
                for seg_id in sorted(set(prog) - seen):
                    await asyncio.to_thread(
                        self._unlink_blocking,
                        self._mirror_path(log, seg_id))
                    prog.pop(seg_id, None)
            self._refresh_lag()
        if self.shipped_seqs:
            await self.source.ack(dict(self.shipped_seqs))
        return total

    async def _ship_segment(self, log: str, seg_id: int,
                            remote_size: int) -> int:
        prog = self._progress.setdefault(log, {})
        mirrored = self._mirrored_size(log, seg_id)
        prog.setdefault(seg_id, mirrored)
        shipped = 0
        while mirrored < remote_size and not self._stopping:
            res = await self.source.read(
                log, seg_id, mirrored,
                max(1, self.config.max_batch_bytes))
            if res is None:
                break  # truncated mid-poll; the next listing drops it
            blob, _sealed = res
            if not blob:
                break
            aligned, max_seq, _count = verify_frames(blob)
            if aligned == 0:
                # a nonzero read that verifies to nothing means the
                # offset no longer sits on a frame boundary (mirror
                # corrupted out-of-band?) — resync this segment from
                # scratch rather than shipping garbage
                logger.warning(
                    "wal mirror %s/%d: unverifiable chunk at offset "
                    "%d; resyncing segment", log, seg_id, mirrored)
                await asyncio.to_thread(
                    self._unlink_blocking, self._mirror_path(log, seg_id))
                prog[seg_id] = 0
                mirrored = 0
                continue
            await asyncio.to_thread(
                self._append_blocking, self._mirror_path(log, seg_id),
                blob[:aligned])
            mirrored += aligned
            prog[seg_id] = mirrored
            shipped += aligned
            _SHIPPED_BYTES.inc(aligned)
            if max_seq:
                self.shipped_seqs[log] = max(
                    self.shipped_seqs.get(log, 0), max_seq)
            if aligned < len(blob):
                # trailing partial frame: the rest arrives once the
                # primary commits it; do not spin on it this pass
                break
        return shipped

    def _append_blocking(self, path: str, blob: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "ab") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())

    def _unlink_blocking(self, path: str) -> None:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass

    def _refresh_lag(self) -> None:
        self._lag_child.set(self.lag())

    def lag(self) -> int:
        """Primary high-watermark minus durably covered seq, maxed over
        logs (0 = fully caught up).  A seq is covered when mirrored OR
        committed to a shared SST by the primary (its segment may be
        truncated — there is nothing left to ship)."""
        return max((hw - max(self.shipped_seqs.get(log, 0),
                             self._flushed.get(log, 0))
                    for log, hw in self._hw.items()), default=0)

    def healthy(self, max_lag_seqs: int = 0) -> bool:
        return self.lag() <= max_lag_seqs


# ---- failover ---------------------------------------------------------------


async def promote(root_path: str, store: ObjectStore, region_id: int,
                  lease_manager: LeaseManager, holder: str,
                  mirror_dir: str, wal_config, *,
                  segment_ms: int = 2 * 3600 * 1000, config=None,
                  lease_ttl_ms: int = 10_000,
                  reason: str = "primary_dead", url: str = "",
                  pre_open: Optional[
                      Callable[[], Awaitable[None]]] = None):
    """Failover: acquire the region's lease (bumping the epoch — the
    old primary is fenced from here on), then open a full engine over
    the region's SHARED paths with the WAL dir pointed at the mirror.
    Replay rebuilds the acked-but-unflushed tail into memtables with
    seqs preserved; flushed data comes from the shared SSTs via the
    manifest — together, grids byte-identical with what the old
    primary would have served.

    `url` is stamped into the lease record so lease-backed routing can
    re-resolve the region's owner after the takeover.  `pre_open` runs
    AFTER the lease is won but BEFORE the engine opens — the standby
    monitor uses it to stop its follower's ship loop, so no mirror
    append can race the replay (losers never reach it: a lost acquire
    raises first, leaving the follower tailing untouched).

    Returns (engine, lease); the lease is already installed as the
    fence on every WAL-fronted table and renewal is NOT started (the
    caller owns the heartbeat policy).
    """
    import dataclasses

    from horaedb_tpu.metric_engine import MetricEngine

    lease = await lease_manager.acquire(region_id, holder,
                                        ttl_ms=lease_ttl_ms, url=url)
    lease.grant_ttl_ms(lease_ttl_ms)
    wal_cfg = dataclasses.replace(wal_config, enabled=True,
                                  dir=mirror_dir)
    try:
        if pre_open is not None:
            await pre_open()
        engine = await MetricEngine.open(
            f"{root_path}/region_{region_id}", store,
            segment_ms=segment_ms, config=config, wal_config=wal_cfg)
    except BaseException:
        await lease.release()
        raise
    install_fence(engine, lease)
    _FAILOVERS.labels(reason=reason).inc()
    logger.info("failover: promoted %r for region %d at epoch %d (%s)",
                holder, region_id, lease.epoch, reason)
    return engine, lease


class StandbyMonitor:
    """Self-driving failover: one per standby (a `WalFollower` with
    [failover] on).  The loop — registered and heartbeated like every
    background loop — polls the primary's lease record in the SHARED
    store and treats it as the sole source of truth:

      * record live            -> reset; retarget tailing at its holder
      * record expired/missing -> arm a jittered grace deadline; once
        past it, run an ELECTION

    An election is the lease's monotonic-epoch acquire, nothing more:
    every standby that reaches its deadline publishes a FITNESS record
    (highest durably mirrored seq) next to the lease, waits one beat,
    and stands down if a fresh sibling record is strictly fitter — so
    the freshest mirror normally acquires uncontested, and when two
    tie the acquire's read-back verify still picks exactly one winner.
    Losers fall back to tailing the new primary (via `retarget`) under
    a cooldown, which is the flapping suppression: a standby that just
    lost cannot immediately re-arm against the winner's first renewal
    hiccup.

    A store PARTITION never elects: the deadline only arms/fires off a
    SUCCESSFUL read showing the lease expired, and an unreachable
    store fails the acquire anyway — the conservative outcome is a
    region with no primary, never two.
    """

    def __init__(self, follower: WalFollower,
                 lease_manager: LeaseManager, region_id: int,
                 holder: str, config: Optional[FailoverConfig],
                 wal_config, *,
                 segment_ms: int = 2 * 3600 * 1000, engine_config=None,
                 lease_ttl_ms: int = 10_000, url: str = "",
                 on_promoted: Optional[Callable] = None,
                 retarget: Optional[Callable] = None,
                 clock: Callable[[], int] = now_ms, rng=None):
        import random

        self.follower = follower
        self.lease_manager = lease_manager
        self.region = region_id
        self.holder = holder
        self.config = config or FailoverConfig()
        self.wal_config = wal_config
        self.segment_ms = segment_ms
        self.engine_config = engine_config
        self.lease_ttl_ms = lease_ttl_ms
        self.url = url
        # async (engine, lease) -> None: the owner's takeover hook
        # (start renewal, swap the served engine, open a hub...)
        self.on_promoted = on_promoted
        # async LeaseRecord -> None: re-point self.follower at the
        # record's holder (None = keep tailing the original source)
        self._retarget = retarget
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        self.role = "standby"
        self.engine = None
        self.lease: Optional[Lease] = None
        self.attempts = 0
        self.last_outcome: Optional[dict] = None
        self._observed: Optional[LeaseRecord] = None
        self._grace_deadline_ms: Optional[int] = None
        self._cooldown_until_ms = 0
        self._retargeted_epoch = 0
        self._task: Optional[asyncio.Task] = None
        self._stopping = False

    # ---- observability ----------------------------------------------------

    def election_state(self) -> dict:
        """/repl/status + /debug/tasks backlog: everything an operator
        needs to see where this standby stands in an election."""
        obs = self._observed
        return {
            "role": self.role,
            "region": self.region,
            "holder": self.holder,
            "observed_epoch": obs.epoch if obs is not None else 0,
            "observed_holder": obs.holder if obs is not None else "",
            "grace_deadline_ms": self._grace_deadline_ms,
            "cooldown_until_ms": self._cooldown_until_ms,
            "attempts": self.attempts,
            "last_outcome": self.last_outcome,
        }

    def _outcome(self, outcome: str, detail: str = "") -> None:
        rec = {"outcome": outcome, "at_ms": self._clock()}
        if detail:
            rec["detail"] = detail
        self.last_outcome = rec
        _ELECTIONS.labels(outcome=outcome).inc()

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> None:
        ensure(self._task is None, "standby monitor already started")
        interval = self.config.check_interval.seconds
        self._task = loops.spawn(
            lambda hb: self._loop(hb, interval),
            name=f"standby-monitor:region_{self.region}",
            kind="standby-monitor", owner="replication",
            period_s=interval, backlog=self.election_state)

    async def close(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # best-effort: drop our fitness record so a later election
        # round never weighs a departed standby
        try:
            await self.lease_manager.store.delete(self._fitness_path())
        except Exception:  # noqa: BLE001 — NotFound / store gone
            pass

    async def _loop(self, hb, interval_s: float) -> None:
        while not self._stopping:
            hb.beat()
            try:
                await self._tick()
                hb.ok()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — store partition
                # or election error: nothing arms or fires without a
                # successful lease read, so just retry next tick
                hb.error(exc)
                logger.warning("standby monitor region %d: %s",
                               self.region, exc)
            if self._stopping:
                return
            await asyncio.sleep(interval_s)

    # ---- the watch/elect state machine ------------------------------------

    async def _tick(self) -> None:
        if self.role != "standby":
            return
        now = self._clock()
        rec = await self.lease_manager.read(self.region)
        if (rec is not None and rec.holder
                and rec.expires_at_ms > now):
            # live primary: disarm, and (once per epoch) re-point our
            # tailing at whoever holds the lease now — the loser path
            self._observed = rec
            self._grace_deadline_ms = None
            if (rec.holder != self.holder
                    and self._retarget is not None
                    and rec.epoch > self._retargeted_epoch):
                await self._retarget(rec)
                self._retargeted_epoch = rec.epoch
            return
        if now < self._cooldown_until_ms:
            return
        if self._grace_deadline_ms is None:
            grace_ms = int(self.config.grace.seconds * 1000)
            jitter_ms = int(self._rng.random() * self.config.jitter
                            * grace_ms)
            self._grace_deadline_ms = now + grace_ms + jitter_ms
            await self._publish_fitness()
            return
        # keep our fitness fresh while the grace window runs, so
        # siblings deciding at their own deadlines see current numbers
        await self._publish_fitness()
        if now < self._grace_deadline_ms:
            return
        await self._elect()

    async def _elect(self) -> None:
        # final drain: the primary is presumed dead, but its already-
        # committed tail may still be readable (shared hub / surviving
        # log plane) — best effort, a dead wire just fails fast
        try:
            await self.follower.poll_once()
        except Exception:  # noqa: BLE001 — dead primary, expected
            pass
        await self._publish_fitness()
        await asyncio.sleep(self.config.fitness_wait.seconds)
        fitter = await self._fresher_sibling()
        if fitter is not None:
            # stand down this round; re-arm so we run again if the
            # fitter sibling dies before claiming
            self._outcome("deferred", detail=f"fresher mirror {fitter}")
            self._grace_deadline_ms = None
            self._cooldown_until_ms = (
                self._clock()
                + int(self.config.cooldown.seconds * 1000))
            return
        self.attempts += 1
        try:
            engine, lease = await promote(
                self.lease_manager.root_path, self.lease_manager.store,
                self.region, self.lease_manager, self.holder,
                self.follower.mirror_dir, self.wal_config,
                segment_ms=self.segment_ms, config=self.engine_config,
                lease_ttl_ms=self.lease_ttl_ms,
                reason="standby_election", url=self.url,
                pre_open=self.follower.close)
        except ReplicationError as exc:
            # lost the race (a sibling's acquire landed first): fall
            # back to tailing — the next live-lease tick retargets us
            self._outcome("lost", detail=str(exc))
            self._grace_deadline_ms = None
            self._cooldown_until_ms = (
                self._clock()
                + int(self.config.cooldown.seconds * 1000))
            return
        self.engine, self.lease = engine, lease
        self.role = "primary"
        self._grace_deadline_ms = None
        self._outcome("won", detail=f"epoch {lease.epoch}")
        self._stopping = True
        logger.info("standby %r won region %d election at epoch %d",
                    self.holder, self.region, lease.epoch)
        if self.on_promoted is not None:
            await self.on_promoted(engine, lease)

    # ---- fitness: freshest mirror wins ------------------------------------

    def _fitness_path(self, holder: Optional[str] = None) -> str:
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in (holder or self.holder))
        return (f"{self.lease_manager.root_path}/leases/"
                f"region_{self.region}.fitness.{safe}.json")

    def _fitness(self) -> int:
        """Durably covered progress, summed over logs (seqs are
        per-log monotonic, so the sum is monotone in coverage).  Falls
        back to scanning the mirror's own frames when the follower has
        not polled yet (a standby restarted straight into an outage)."""
        f = self.follower
        logs = set(f.shipped_seqs) | set(f._flushed)
        if not logs:
            return sum(mirror_watermarks(f.mirror_dir).values())
        return sum(max(f.shipped_seqs.get(log, 0),
                       f._flushed.get(log, 0)) for log in logs)

    async def _publish_fitness(self) -> None:
        rec = {"holder": self.holder, "fitness": self._fitness(),
               "at_ms": self._clock()}
        await self.lease_manager.store.put(
            self._fitness_path(), json.dumps(rec).encode())

    async def _fresher_sibling(self) -> Optional[str]:
        """The holder of a FRESH sibling fitness record strictly fitter
        than ours, else None.  Stale records (older than the grace
        horizon) are a departed standby's leftovers and never block."""
        store = self.lease_manager.store
        prefix = (f"{self.lease_manager.root_path}/leases/"
                  f"region_{self.region}.fitness.")
        now = self._clock()
        horizon_ms = max(
            1000,
            int(self.config.grace.seconds * 1000)
            + 2 * int(self.config.fitness_wait.seconds * 1000))
        mine = self._fitness()
        my_path = self._fitness_path()
        for meta in await store.list(prefix):
            if meta.path == my_path:
                continue
            try:
                d = json.loads(await store.get(meta.path))
            except (NotFoundError, ValueError):
                continue
            if now - int(d.get("at_ms", 0)) > horizon_ms:
                continue
            if int(d.get("fitness", 0)) > mine:
                return str(d.get("holder", meta.path))
        return None


# ---- auto-executed rebalance ------------------------------------------------


class RebalanceExecutor:
    """Executes the health monitor's split recommendations under the
    [rebalance] safety envelope.  Every plan entry flows through the
    same gate order — disabled / cooldown / throttle / replica-health
    / dry-run — and every decision is counted
    (`rebalance_moves_total{kind,outcome}`) and kept in a bounded
    history for /debug/tasks.

    Split entries carry machine-executable fields (pivot_key,
    new_region_id) from `Cluster._rebalance_from_stats`; whole-region
    moves need a peer to adopt the region, which this node cannot
    conjure — they record `no_target` unless a `move_target` hook is
    wired by an outer control plane."""

    _HISTORY = 32

    def __init__(self, cluster, config: Optional[RebalanceConfig] = None,
                 clock: Callable[[], int] = now_ms):
        self.cluster = cluster
        self.config = config or RebalanceConfig()
        self._clock = clock
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        self._last_move_ms: dict[int, int] = {}
        self._inflight = 0
        self.history: list[dict] = []
        # Optional[Callable[[int], bool]]: is region `rid`'s replica
        # healthy enough to survive losing its primary mid-move?  None
        # = no replica wired = vacuously healthy
        self.replica_healthy: Optional[Callable[[int], bool]] = None
        # Optional[Callable[[int, dict], Awaitable[bool]]]: execute a
        # whole-region move (detach here + adopt elsewhere); absent by
        # default
        self.move_target: Optional[
            Callable[[int, dict], Awaitable[bool]]] = None

    def start(self) -> None:
        ensure(self._task is None, "rebalance executor already started")
        interval = self.config.interval.seconds
        self._task = loops.spawn(
            lambda hb: self._loop(hb, interval),
            name="rebalance-exec", kind="rebalance", owner="cluster",
            period_s=interval,
            backlog=lambda: {"inflight": self._inflight,
                             "dry_run": self.config.dry_run,
                             "recent": self.history[-8:]})

    async def close(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self, hb, interval_s: float) -> None:
        while not self._stopping:
            await asyncio.sleep(interval_s)
            if self._stopping:
                return
            hb.beat()
            try:
                await self.run_once()
                hb.ok()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — next tick retries
                hb.error(exc)
                logger.exception("rebalance pass failed")

    async def run_once(self) -> list[dict]:
        """One executor pass over the freshest survey plan.  Returns
        the outcome records (also appended to `history`)."""
        survey = self.cluster.rebalance_survey
        if survey is None:
            survey = await self.cluster.survey_load(self.config.skew_ratio)
        outcomes = []
        for entry in survey.get("plan", []):
            outcomes.append(await self._execute(entry))
        return outcomes

    def _record(self, entry: dict, kind: str, outcome: str,
                detail: str = "") -> dict:
        rec = {"region": entry.get("region"), "kind": kind,
               "outcome": outcome, "at_ms": self._clock()}
        if detail:
            rec["detail"] = detail
        _REBALANCE_MOVES.labels(kind=kind, outcome=outcome).inc()
        self.history.append(rec)
        del self.history[:-self._HISTORY]
        return rec

    async def _execute(self, entry: dict) -> dict:
        cfg = self.config
        rid = int(entry["region"])
        kind = entry.get("kind") or (
            "split" if entry.get("new_region_id") is not None else "move")
        if not cfg.enabled:
            return self._record(entry, kind, "disabled")
        last = self._last_move_ms.get(rid)
        if (last is not None
                and self._clock() - last < cfg.cooldown.seconds * 1000):
            return self._record(entry, kind, "cooldown")
        if self._inflight >= cfg.max_concurrent_moves:
            return self._record(entry, kind, "throttled")
        if (cfg.require_replica_healthy
                and self.replica_healthy is not None
                and not self.replica_healthy(rid)):
            return self._record(entry, kind, "replica_unhealthy")
        if cfg.dry_run:
            return self._record(entry, kind, "dry_run",
                                detail=entry.get("reason", ""))
        if kind == "split":
            pivot = entry.get("pivot_key")
            new_rid = entry.get("new_region_id")
            if pivot is None or new_rid is None:
                return self._record(entry, kind, "no_pivot")
            self._inflight += 1
            try:
                await self.cluster.split_region(
                    rid, int(pivot), int(new_rid), cfg.table_ttl_ms)
            except Exception as exc:  # noqa: BLE001 — counted, surfaced
                return self._record(entry, kind, "error", detail=str(exc))
            finally:
                self._inflight -= 1
            self._last_move_ms[rid] = self._clock()
            return self._record(entry, kind, "executed")
        # whole-region move: needs a peer to adopt it
        if self.move_target is None:
            return self._record(entry, kind, "no_target")
        self._inflight += 1
        try:
            moved = await self.move_target(rid, entry)
        except Exception as exc:  # noqa: BLE001 — counted, surfaced
            return self._record(entry, kind, "error", detail=str(exc))
        finally:
            self._inflight -= 1
        if not moved:
            return self._record(entry, kind, "declined")
        self._last_move_ms[rid] = self._clock()
        return self._record(entry, kind, "executed")
