"""Range-partition routing with per-rule TTL (ref: RFC 20240827:36-76).

Key space: hash(metric + sorted labels) masked to [0, 2^63) — the same
canonical series key the TSID uses, so one series always routes to one
region.  Rules are half-open key ranges [start_key, end_key) with:

  - created_at: when the rule became active (ms),
  - ttl_expire_at: when the rule's data stops being queryable
    (MAX_TTL = forever for live rules).

Writes go to the covering rule with the LARGEST ttl_expire_at (the RFC's
"find the rule with the max TTL in the interval").  Queries return every
covering rule whose [created_at, ttl_expire_at) intersects the query
time window — after a split, old data is still in the pre-split region
until the old rule's TTL lapses, so both regions are consulted.

split() implements the RFC's `alter table root split partition` flow:
the old rule gets ttl_expire_at = now + table_ttl, the new sub-ranges
get MAX_TTL.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.seahash import hash64
from horaedb_tpu.metric_engine.types import series_key_of

KEY_SPACE = 1 << 63
MAX_TTL = (1 << 63) - 1


def routing_key(metric: str, labels) -> int:
    """hash(metric + sorted_tags) into [0, 2^63) (RFC:34)."""
    return hash64(series_key_of(metric, list(labels))) % KEY_SPACE


@dataclass(frozen=True)
class PartitionRule:
    start_key: int
    end_key: int  # exclusive
    region_id: int
    created_at: int = 0
    ttl_expire_at: int = MAX_TTL

    def covers(self, key: int) -> bool:
        return self.start_key <= key < self.end_key

    def alive_for_query(self, q_start: int, q_end: int,
                        strict_time_routing: bool = False) -> bool:
        """Whether this rule's region must be consulted for a query window.

        Always: not yet TTL-expired at the window start.  With
        strict_time_routing (the RFC's routing table, which assumes data
        time == ingest time), additionally prune rules created after the
        window ends — unsafe under backfill, where late writes carry old
        timestamps into post-split regions, so it is opt-in.  (Data
        timestamps inside the region are filtered by the engine either
        way.)"""
        if self.ttl_expire_at <= q_start:
            return False
        if strict_time_routing and self.created_at >= q_end:
            return False
        return True


@dataclass
class RoutingTable:
    rules: list[PartitionRule] = field(default_factory=list)
    # RFC-style timestamp pruning of post-split rules; leave False when
    # backfill (writes with old timestamps) is possible
    strict_time_routing: bool = False

    @classmethod
    def uniform(cls, region_ids: list[int]) -> "RoutingTable":
        """Initial layout: equal key ranges, one per region."""
        ensure(region_ids, "at least one region required")
        n = len(region_ids)
        step = KEY_SPACE // n
        rules = []
        for i, rid in enumerate(region_ids):
            end = KEY_SPACE if i == n - 1 else (i + 1) * step
            rules.append(PartitionRule(i * step, end, rid))
        return cls(rules)

    def route_write(self, key: int, now_ms: int) -> int:
        """Region for a write: covering rule with the largest TTL
        (RFC: "找到对应区间内 TTL 最大的")."""
        best: Optional[PartitionRule] = None
        for r in self.rules:
            if r.covers(key) and r.ttl_expire_at > now_ms:
                if best is None or r.ttl_expire_at > best.ttl_expire_at:
                    best = r
        if best is None:
            raise Error(f"no live partition rule covers key {key}")
        return best.region_id

    def route_query(self, key: Optional[int], q_start: int,
                    q_end: int) -> list[int]:
        """Regions a query must consult.  key=None (no full tag set to
        hash) fans out to every live rule — the RFC accepts this for
        un-pinnable queries."""
        out: list[int] = []
        for r in self.rules:
            if key is not None and not r.covers(key):
                continue
            if (r.alive_for_query(q_start, q_end, self.strict_time_routing)
                    and r.region_id not in out):
                out.append(r.region_id)
        return out

    def split(self, region_id: int, pivot_key: int, new_region_id: int,
              now_ms: int, table_ttl_ms: int) -> None:
        """Split a hot region's range at pivot_key: [a,p) stays, [p,b)
        moves to the new region.  The old rule lives on with
        ttl = now + table_ttl so existing data stays queryable until it
        ages out (RFC's split table: old rule TTL = t+30d)."""
        live = [r for r in self.rules
                if r.region_id == region_id and r.ttl_expire_at == MAX_TTL
                and r.covers(pivot_key)]
        ensure(len(live) == 1,
               f"expected exactly one live rule covering pivot {pivot_key} "
               f"in region {region_id}, found {len(live)}")
        old = live[0]
        ensure(old.start_key < pivot_key < old.end_key,
               "pivot must fall strictly inside the rule's range")
        self.rules.remove(old)
        # old rule expires after the table TTL; until then queries fan out
        self.rules.append(replace(old, ttl_expire_at=now_ms + table_ttl_ms))
        self.rules.append(PartitionRule(old.start_key, pivot_key,
                                        region_id, created_at=now_ms))
        self.rules.append(PartitionRule(pivot_key, old.end_key,
                                        new_region_id, created_at=now_ms))

    def gc_expired(self, now_ms: int) -> list[PartitionRule]:
        """Drop rules whose TTL fully lapsed; returns the dropped rules
        so the caller can reclaim region data."""
        dead = [r for r in self.rules if r.ttl_expire_at <= now_ms]
        self.rules = [r for r in self.rules if r.ttl_expire_at > now_ms]
        return dead

    def region_ids(self) -> list[int]:
        out: list[int] = []
        for r in self.rules:
            if r.region_id not in out:
                out.append(r.region_id)
        return out

    # ---- persistence (the cluster's "root table" state) -------------------

    def to_json(self) -> str:
        import json

        return json.dumps({
            "strict_time_routing": self.strict_time_routing,
            "rules": [{"start_key": r.start_key, "end_key": r.end_key,
                       "region_id": r.region_id,
                       "created_at": r.created_at,
                       "ttl_expire_at": r.ttl_expire_at}
                      for r in self.rules],
        })

    @classmethod
    def from_json(cls, data: str) -> "RoutingTable":
        import json

        doc = json.loads(data)
        return cls(
            rules=[PartitionRule(**r) for r in doc["rules"]],
            strict_time_routing=doc.get("strict_time_routing", False))
