"""Per-region circuit breaker for the scatter-gather plane
(docs/robustness.md, query-path failure domains).

HoraeDB's design treats the query plane as a failure domain with
fail-fast routing (SURVEY.md P6); the breaker is the per-region piece:
after `failure_threshold` CONSECUTIVE failures (RPC errors, timeouts,
or failed heartbeat pings) a region's circuit opens and gather skips it
immediately — no connect attempts, no timeout waits — reporting it in
`missing_regions` instead of stalling the whole query.

State machine:

    closed ── failures >= threshold ──> open
    open ── cooldown elapsed OR health-monitor ping OK ──> half_open
    half_open ── one probe query succeeds ──> closed
    half_open ── probe fails ──> open (cooldown restarts)

The half-open probe "rides the existing health monitor" two ways: a
successful ping promotes open -> half_open without waiting out the
cooldown, and the NEXT real query is the single admitted probe.  All
transitions feed /metrics counters so open/half-open/close flapping is
observable in production.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.utils import registry

# one labeled family per event kind (docs/observability.md label
# conventions): per-region + per-target-state series replace the old
# per-state metric-name one-offs
_TRANSITIONS = registry.counter(
    "cluster_breaker_transitions_total",
    "circuit breaker state transitions by region and target state")
_REJECTED = registry.counter(
    "cluster_breaker_rejected_total",
    "region calls skipped because the circuit was open, by region")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclass
class BreakerConfig:
    """[breaker] config: per-region circuit breaking + the RPC-level
    timeout/retry/hedge policy the gather path applies around remote
    region calls."""

    enabled: bool = True
    # consecutive failures (errors, timeouts, failed pings) that open
    # the circuit
    failure_threshold: int = 3
    # how long an open circuit waits before admitting a probe on its
    # own (a successful health-monitor ping short-circuits the wait)
    open_cooldown: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("10s"))
    # per-attempt remote RPC timeout; the effective budget is
    # min(rpc_timeout, deadline remaining)
    rpc_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("10s"))
    # bounded retry count for idempotent reads (writes never retry)
    retries: int = 1
    # hedged reads: after this delay with no response, fire a second
    # identical request and take whichever succeeds first.  0 disables.
    hedge_delay: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_millis(0))


class CircuitBreaker:
    """One region's breaker.  Thread-safe (the health monitor and
    gather tasks share it), but all users run on one event loop in
    practice."""

    def __init__(self, name: str, config: BreakerConfig | None = None,
                 clock=time.monotonic):
        self.name = name
        self.config = config or BreakerConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        # labeled children bound once (label lookup off the hot path)
        self._m_opened = _TRANSITIONS.labels(region=name, to=OPEN)
        self._m_half_open = _TRANSITIONS.labels(region=name, to=HALF_OPEN)
        self._m_closed = _TRANSITIONS.labels(region=name, to=CLOSED)
        self._m_rejected = _REJECTED.labels(region=name)

    @property
    def state(self) -> str:
        with self._lock:
            # surface the lazy open -> half_open cooldown transition
            if self._state == OPEN and self._cooldown_elapsed():
                return HALF_OPEN
            return self._state

    def _cooldown_elapsed(self) -> bool:
        return (self._clock() - self._opened_at
                >= self.config.open_cooldown.seconds)

    def allow(self) -> bool:
        """Whether a call may proceed.  In half-open exactly ONE probe
        is admitted at a time; its outcome decides the next state."""
        if not self.config.enabled:
            return True
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if not self._cooldown_elapsed():
                    self._m_rejected.inc()
                    return False
                self._to_half_open_locked()
            # half-open: admit a single probe
            if self._probe_inflight:
                self._m_rejected.inc()
                return False
            self._probe_inflight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._state = CLOSED
                self._m_closed.inc()

    def record_failure(self) -> None:
        if not self.config.enabled:
            return  # a disabled breaker must not open (nor suppress
            # the gather's bounded retries via a non-closed state)
        with self._lock:
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                # failed probe: back to open, cooldown restarts
                self._to_open_locked()
                return
            self._failures += 1
            if (self._state == CLOSED
                    and self._failures >= self.config.failure_threshold):
                self._to_open_locked()

    def abort_probe(self) -> None:
        """Release a claimed probe slot with NO outcome recorded — the
        probe never actually ran (its requester's deadline expired, or
        its task was cancelled).  Without this, a half-open breaker
        whose probe evaporated would reject every caller until a ping
        re-armed it."""
        with self._lock:
            self._probe_inflight = False

    def on_ping_ok(self) -> None:
        """A health-monitor ping succeeded: an open circuit moves to
        half-open immediately (the probe rides the monitor instead of
        waiting out the cooldown); a closed circuit forgets stale
        failures so unrelated blips can't accumulate into an open.  In
        half-open the probe slot is re-armed: a probe whose task died
        between allow() and its outcome (cancelled gather) must not
        wedge the breaker rejecting forever while the peer answers
        pings."""
        with self._lock:
            if self._state == OPEN:
                self._to_half_open_locked()
            elif self._state == HALF_OPEN:
                self._probe_inflight = False
            elif self._state == CLOSED:
                self._failures = 0

    def _to_open_locked(self) -> None:
        self._state = OPEN
        self._failures = 0
        self._opened_at = self._clock()
        self._probe_inflight = False
        self._m_opened.inc()

    def _to_half_open_locked(self) -> None:
        self._state = HALF_OPEN
        self._probe_inflight = False
        self._m_half_open.inc()

    def __repr__(self) -> str:
        return f"CircuitBreaker({self.name}: {self.state})"
