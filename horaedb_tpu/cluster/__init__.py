"""Sharding / scale-out layer (ref: RFC 20240827:20-76, SURVEY.md P6).

The reference designs (but does not implement) a range-partitioned
cluster: one `root` super-table whose rows are series, range-partitioned
by hash(metric + sorted_tags); a Region is the shard unit; partition
split rules carry per-rule TTLs so writes route by the freshest rule
while queries fan out to every rule whose lifetime intersects the query
window.  This module implements that design over in-process MetricEngine
regions; a multi-host deployment swaps RegionBackend for an HTTP client
speaking the server's /write + /query endpoints (DCN plane).
"""

from horaedb_tpu.cluster.router import (
    MAX_TTL,
    PartitionRule,
    RoutingTable,
    routing_key,
)
from horaedb_tpu.cluster.breaker import BreakerConfig, CircuitBreaker
from horaedb_tpu.cluster.cluster import Cluster, GatherMeta
from horaedb_tpu.cluster.remote import RemoteRegion
from horaedb_tpu.cluster.replication import (
    FailoverConfig,
    HttpWalSource,
    Lease,
    LeaseManager,
    LocalWalSource,
    RebalanceConfig,
    RebalanceExecutor,
    ReplicationConfig,
    ReplicationError,
    ReplicationHub,
    StaleEpochError,
    StaleOwnerError,
    StandbyMonitor,
    WalFollower,
    install_fence,
    promote,
)
from horaedb_tpu.cluster.placement import (
    LeaseOwnerResolver,
    PlacementController,
)

__all__ = ["BreakerConfig", "CircuitBreaker", "Cluster",
           "FailoverConfig", "GatherMeta", "HttpWalSource", "Lease",
           "LeaseManager", "LeaseOwnerResolver", "LocalWalSource",
           "MAX_TTL", "PartitionRule", "PlacementController",
           "RebalanceConfig", "RebalanceExecutor", "RemoteRegion",
           "ReplicationConfig", "ReplicationError", "ReplicationHub",
           "RoutingTable", "StaleEpochError", "StaleOwnerError",
           "StandbyMonitor", "WalFollower", "install_fence", "promote",
           "routing_key"]
