"""Remote region backend: the MetricEngine API over the server's HTTP
endpoints — the cluster's DCN plane (SURVEY.md P6: the legacy reference
forwards via HoraeMeta + gRPC; our control/data plane is the aiohttp
server, so a region can live in any process that runs one).

RemoteRegion duck-types the MetricEngine surface the Cluster facade uses
(write / query / query_downsample / label_values / close), so a Cluster
can mix in-process and remote regions freely.

Every RPC is bounded: each call gets an `aiohttp.ClientTimeout` of
`min(timeout_s, ambient deadline remaining)` — aiohttp's 5-minute
default total timeout is never inherited (docs/robustness.md).  The
remaining budget also rides ahead of the request as `X-Deadline-Ms`,
so the peer's server can bind the same deadline for ITS downstream
work instead of scanning for a client that already gave up.
"""

from __future__ import annotations

import math
from typing import Optional

import pyarrow as pa

import aiohttp

from horaedb_tpu.common.deadline import current_deadline, remaining_budget
from horaedb_tpu.common.error import Error
from horaedb_tpu.common.tenant import current_tenant
from horaedb_tpu.metric_engine.types import Sample
from horaedb_tpu.storage.types import TimeRange
from horaedb_tpu.utils import span, tracing

# default per-RPC total timeout when no deadline is bound and no
# override is configured; generous for bulk ingest, far below aiohttp's
# 5-minute default
DEFAULT_RPC_TIMEOUT_S = 60.0


def _stale_owner_error(base_url: str, path: str, text: str):
    """Typed 409 stale-owner answer; carries the region/new-owner hint
    from the JSON body when the peer knows it."""
    import json

    from horaedb_tpu.cluster.replication import StaleOwnerError

    region = owner = None
    try:
        body = json.loads(text)
        region = body.get("region")
        owner = body.get("owner")
    except (ValueError, AttributeError):
        pass
    return StaleOwnerError(
        f"remote region {base_url}{path} answered 409 stale-owner: "
        f"{text[:200]}", region=region, owner=owner)


class RemoteRegion:
    def __init__(self, base_url: str,
                 session: Optional[aiohttp.ClientSession] = None,
                 timeout_s: float = DEFAULT_RPC_TIMEOUT_S):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._session = session
        self._own_session = session is None

    async def _ensure_session(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._own_session and self._session is not None:
            await self._session.close()
            self._session = None

    def _rpc_budget(self) -> tuple[aiohttp.ClientTimeout, dict]:
        """Per-call (timeout, deadline headers).  Raises rather than
        firing an RPC whose request is already out of time."""
        dl = current_deadline()
        if dl is not None:
            dl.check()
        budget = remaining_budget(self.timeout_s)
        headers = {}
        if dl is not None and dl.deadline_at is not None:
            # remaining budget in whole ms, floored so the peer's view
            # is never LONGER than ours
            headers["X-Deadline-Ms"] = str(
                max(1, math.floor((budget or 0.0) * 1000)))
        # the trace context rides the same plumbing as the deadline: the
        # peer traces its share of the work under OUR trace id and hands
        # its spans back on X-Trace-Export for stitching
        trace = tracing.active_trace()
        if trace is not None and not trace.finished:
            headers[tracing.TRACE_HEADER] = trace.trace_id
        # tenant identity + node-tier weight ride along so the peer's
        # fair scheduler grants this tenant its configured share even
        # when the peer's own [tenants] table doesn't know the name
        # (auto-minted tenants there default to weight 1.0 otherwise)
        tenant = current_tenant()
        if tenant is not None:
            headers["X-Tenant"] = tenant.name
            headers["X-Tenant-Weight"] = repr(tenant.limits.weight)
        return aiohttp.ClientTimeout(total=budget), headers

    async def _post_raw(self, path: str, **kwargs) -> bytes:
        """POST with the shared status-first error contract; returns the
        raw response body.  Every call carries an explicit timeout
        derived from the propagated deadline (capped by `timeout_s`)."""
        session = await self._ensure_session()
        with span("rpc", path=path, url=self.base_url):
            timeout, dl_headers = self._rpc_budget()
            headers = {**dl_headers, **kwargs.pop("headers", {})}
            async with session.post(self.base_url + path, timeout=timeout,
                                    headers=headers, **kwargs) as resp:
                if resp.status == 409:
                    # stale owner: the peer lost this region's lease
                    # mid-failover.  Typed so the coordinator's gather
                    # can re-resolve ownership and retry ONE hop
                    # instead of degrading immediately.
                    raise _stale_owner_error(self.base_url, path,
                                             await resp.text())
                if resp.status != 200:
                    # body may be a non-JSON error page (404, 500 html)
                    text = await resp.text()
                    raise Error(f"remote region {self.base_url}{path} "
                                f"returned {resp.status}: {text[:200]}")
                # stitch the peer's spans under this RPC span
                tracing.ingest_export(
                    resp.headers.get(tracing.EXPORT_HEADER))
                return await resp.read()

    async def _post(self, path: str, body: dict) -> dict:
        import json

        return json.loads(await self._post_raw(path, json=body))

    async def ping(self, timeout_s: float = 2.0) -> bool:
        """Cheap liveness probe (the server's hello endpoint).  False on
        any failure — the health monitor turns repeated falses into a
        dead mark so queries fail fast instead of at gather time."""
        try:
            session = await self._ensure_session()
            async with session.get(
                    self.base_url + "/",
                    timeout=aiohttp.ClientTimeout(total=timeout_s)) as resp:
                return resp.status == 200
        except Exception:
            return False

    async def stats(self, timeout_s: float = 10.0) -> dict:
        """Remote region data volume (rows/bytes) via the server's
        /stats endpoint — the cluster's real load signal.  Bounded by
        its own timeout: a blackholed peer must degrade the stats
        survey, not stall it for aiohttp's 5-minute default."""
        import json

        session = await self._ensure_session()
        async with session.get(
                self.base_url + "/stats",
                timeout=aiohttp.ClientTimeout(total=timeout_s)) as resp:
            if resp.status != 200:
                raise Error(f"remote region {self.base_url}/stats "
                            f"returned {resp.status}")
            return json.loads(await resp.read())

    # ---- MetricEngine surface ---------------------------------------------

    async def write(self, samples: list[Sample]) -> None:
        body = {"samples": [
            {"name": s.name,
             "labels": {l.name: l.value for l in s.labels},
             "timestamp": s.timestamp, "value": s.value,
             "field": s.field_name}
            for s in samples
        ]}
        await self._post("/write", body)

    async def write_arrow(self, metric: str, tag_columns: list[str],
                          batch: pa.RecordBatch,
                          field: str = "value") -> None:
        """Bulk ingest over the Arrow-IPC data plane (zstd buffers for
        the DCN hop; the server's pyarrow reader auto-detects)."""
        from horaedb_tpu.common.ipc import serialize_stream

        await self._post_raw(
            "/write_arrow",
            params={"metric": metric, "tags": ",".join(tag_columns),
                    "field": field},
            data=serialize_stream(batch, "zstd"),
            headers={"Content-Type": "application/vnd.apache.arrow.stream"})

    async def query(self, metric: str, filters: list[tuple[str, str]],
                    time_range: TimeRange, field: str = "value") -> pa.Table:
        """Row queries ride the Arrow-IPC plane (no per-row JSON); the
        region-to-region hop opts into zstd buffers."""
        import pyarrow.ipc

        body = await self._post_raw("/query_arrow", json={
            "metric": metric, "filters": [list(f) for f in filters],
            "start": int(time_range.start), "end": int(time_range.end),
            "field": field, "compression": "zstd"})
        return pyarrow.ipc.open_stream(body).read_all()

    async def query_downsample(self, metric: str,
                               filters: list[tuple[str, str]],
                               time_range: TimeRange, bucket_ms: int,
                               field: str = "value") -> dict:
        """Downsample grids ride the Arrow-IPC plane like row queries:
        zstd'd FixedSizeList buffers instead of JSON decimal text (2.6x
        fewer DCN bytes even on random grids; NaN preserved without a
        null round trip)."""
        import pyarrow.ipc

        from horaedb_tpu.common.ipc import downsample_from_arrow

        body = await self._post_raw("/query_arrow", json={
            "metric": metric, "filters": [list(f) for f in filters],
            "start": int(time_range.start), "end": int(time_range.end),
            "bucket_ms": bucket_ms, "field": field,
            "compression": "zstd"})
        return downsample_from_arrow(pyarrow.ipc.open_stream(body).read_all())

    async def label_values(self, metric: str, tag_key: str,
                           time_range: TimeRange) -> list[str]:
        session = await self._ensure_session()
        with span("rpc", path="/label_values", url=self.base_url):
            timeout, dl_headers = self._rpc_budget()
            # status FIRST (the _post_raw contract): a non-JSON error
            # page (404 text, 500 html) must surface as Error, not as a
            # ContentTypeError from reading the body as JSON
            async with session.get(self.base_url + "/label_values",
                                   params={
                    "metric": metric, "key": tag_key,
                    "start": str(int(time_range.start)),
                    "end": str(int(time_range.end))},
                    timeout=timeout, headers=dl_headers) as resp:
                if resp.status == 409:
                    raise _stale_owner_error(self.base_url,
                                             "/label_values",
                                             await resp.text())
                if resp.status != 200:
                    text = await resp.text()
                    raise Error(
                        f"remote region {self.base_url}/label_values "
                        f"returned {resp.status}: {text[:200]}")
                tracing.ingest_export(
                    resp.headers.get(tracing.EXPORT_HEADER))
                data = await resp.json()
                return data["values"]
