"""Closed placement loop: ONE controller owns follower placement,
promotion choice, and region moves (ISSUE 17 / ROADMAP item 3b).

PR 16 left the control seams open on purpose — the RebalanceExecutor
records `no_target` for whole-region moves and treats replica health
as vacuously true; `Cluster.owner_resolver` is None.  This module
closes them:

  * `PlacementController` is fed by the cluster's manifests
    (region_stats / rebalance_survey) and by `replication_lag_seqs`
    probes (each region's WalFollower.lag), and implements the
    executor's `replica_healthy` / `move_target` hooks plus the
    promotion-choice seam (`choose_promotion` / `promote_region`).
    Every decision — refusals included — lands in a bounded history
    surfaced on /debug/tasks through the controller's heartbeated
    loop.
  * `LeaseOwnerResolver` is the `Cluster.owner_resolver` that answers
    from LIVE lease records in the shared store (with a small TTL'd
    cache), so the 409 stale-owner routed retry follows real
    failovers instead of test stubs.

The controller never invents authority: promotion still goes through
`promote()` (the lease's monotonic-epoch acquire), moves still flow
through the executor's safety envelope, and routing still answers
from the lease records every fence already trusts.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Optional

from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.loops import loops
from horaedb_tpu.common.time_ext import now_ms
from horaedb_tpu.cluster.replication import (LeaseManager, LeaseRecord,
                                             RebalanceConfig)

logger = logging.getLogger(__name__)


class LeaseOwnerResolver:
    """`Cluster.owner_resolver` backed by the region's lease record.

    On a 409 stale-owner the gather path asks for a fresh backend;
    this resolver reads the CURRENT lease record (the same record the
    winner's fence commits against) and maps it to a backend — via the
    record's advertised `url` (RemoteRegion over HTTP) or a caller
    `backend_factory` for in-process topologies.  Resolutions are
    cached for `cache_ttl_ms` so a 409 storm during an election costs
    one store read per region per TTL, not one per failed request; a
    409 whose owner hint contradicts the cached record busts the cache
    (the record moved under us mid-TTL).

    Returns None — degrading the gather to a partial answer — when no
    live lease exists: mid-election there IS no owner to route to.
    """

    def __init__(self, lease_manager: LeaseManager,
                 backend_factory: Optional[
                     Callable[[LeaseRecord], object]] = None,
                 cache_ttl_ms: int = 1000,
                 clock: Callable[[], int] = now_ms):
        self.lease_manager = lease_manager
        self.backend_factory = backend_factory
        self.cache_ttl_ms = cache_ttl_ms
        self._clock = clock
        # region -> (resolved_at_ms, record, backend)
        self._cache: dict[int, tuple[int, LeaseRecord, object]] = {}

    async def __call__(self, region_id: int, exc) -> Optional[object]:
        now = self._clock()
        hint = getattr(exc, "owner", None)
        hit = self._cache.get(region_id)
        if hit is not None:
            at, rec, backend = hit
            stale = now - at > self.cache_ttl_ms
            contradicted = bool(hint) and hint not in (rec.url,
                                                       rec.holder)
            if not stale and not contradicted:
                return backend
        rec = await self.lease_manager.read(region_id)
        if (rec is None or not rec.holder
                or rec.expires_at_ms <= now):
            return None
        backend = self._make_backend(rec)
        if backend is not None:
            self._cache[region_id] = (now, rec, backend)
        return backend

    def _make_backend(self, rec: LeaseRecord) -> Optional[object]:
        if self.backend_factory is not None:
            return self.backend_factory(rec)
        if rec.url:
            from horaedb_tpu.cluster.remote import RemoteRegion

            return RemoteRegion(rec.url)
        return None


class PlacementController:
    """The single decision-maker for where regions live and who serves
    them.  It does not move data itself: it answers the executor's
    questions (is the replica healthy? where should this region go?)
    and, when asked to fail a region over, picks the freshest
    registered standby — so every placement decision has one owner and
    one audit trail.

    Wiring:
      controller.attach(executor)        # replica_healthy + move_target
      controller.register_follower(rid, follower)   # lag probe
      controller.register_standby(rid, holder, fitness, promote_cb)
      controller.register_node(node, adopt, load)   # move destinations
      controller.start()                 # the observing loop
    """

    _HISTORY = 64

    def __init__(self, cluster,
                 config: Optional[RebalanceConfig] = None,
                 clock: Callable[[], int] = now_ms):
        self.cluster = cluster
        self.config = config or RebalanceConfig()
        self._clock = clock
        # region -> replication lag probe (WalFollower.lag or peer
        # /repl/status reading) — the replica_healthy signal
        self._lag_probes: dict[int, Callable[[], int]] = {}
        # region -> {holder -> {"fitness": () -> int,
        #                       "promote": async () -> (engine, lease)}}
        self._standbys: dict[int, dict[str, dict]] = {}
        # node_id -> {"adopt": async (rid, entry) -> bool,
        #             "load": () -> int}
        self._nodes: dict[str, dict] = {}
        self.history: list[dict] = []
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        # refreshed each loop tick from manifests + lag probes
        self.snapshot: dict = {}

    # ---- registration ------------------------------------------------------

    def register_follower(self, region_id: int, follower) -> None:
        """Feed a region's `replication_lag_seqs` from a local
        WalFollower (the common case)."""
        self.register_lag_probe(region_id, follower.lag)

    def register_lag_probe(self, region_id: int,
                           probe: Callable[[], int]) -> None:
        self._lag_probes[region_id] = probe

    def register_standby(self, region_id: int, holder: str,
                         fitness: Callable[[], int],
                         promote: Callable[[], Awaitable]) -> None:
        """A candidate for promotion: `fitness` returns its mirrored
        watermark, `promote` performs its lease-acquiring takeover."""
        self._standbys.setdefault(region_id, {})[holder] = {
            "fitness": fitness, "promote": promote}

    def register_node(self, node_id: str,
                      adopt: Callable[[int, dict], Awaitable[bool]],
                      load: Optional[Callable[[], int]] = None) -> None:
        """A move destination: `adopt` takes (region_id, plan entry)
        and returns True once the node serves the region; `load` ranks
        candidates (lower = preferred)."""
        self._nodes[node_id] = {"adopt": adopt,
                                "load": load or (lambda: 0)}

    def attach(self, executor) -> None:
        """Close the executor's open seams: placement decisions now
        come from this controller."""
        executor.replica_healthy = self.replica_healthy
        executor.move_target = self.move_target

    # ---- decision history --------------------------------------------------

    def _record(self, kind: str, outcome: str, region=None,
                detail: str = "") -> dict:
        rec = {"kind": kind, "outcome": outcome,
               "at_ms": self._clock()}
        if region is not None:
            rec["region"] = region
        if detail:
            rec["detail"] = detail
        self.history.append(rec)
        del self.history[:-self._HISTORY]
        return rec

    # ---- the executor's seams ----------------------------------------------

    def replica_healthy(self, region_id: int) -> bool:
        """Is the region safe to move/split — i.e. would its replica
        survive losing the primary mid-operation?  A region with no
        lag probe has no replica wired: vacuously healthy, matching
        the executor's pre-controller behavior.  Refusals are recorded
        (healthy checks are too chatty to log)."""
        probe = self._lag_probes.get(region_id)
        if probe is None:
            return True
        lag = probe()
        if lag <= self.config.max_replica_lag_seqs:
            return True
        self._record("replica_check", "unhealthy", region=region_id,
                     detail=f"lag {lag} seqs")
        return False

    async def move_target(self, region_id: int, entry: dict) -> bool:
        """Execute a whole-region move: pick the least-loaded
        registered node and ask it to adopt the region (ownership
        handoff over the shared store — no data copy).  Declining
        nodes are skipped; no willing node means no move."""
        cands = sorted(self._nodes.items(),
                       key=lambda kv: kv[1]["load"]())
        for node_id, node in cands:
            try:
                adopted = await node["adopt"](region_id, entry)
            except Exception as exc:  # noqa: BLE001 — counted, and the
                # next candidate is tried; all-declined records no_target
                self._record("move", "error", region=region_id,
                             detail=f"{node_id}: {exc}")
                continue
            if adopted:
                self._record("move", "executed", region=region_id,
                             detail=f"-> {node_id}")
                return True
        self._record("move", "no_target", region=region_id,
                     detail=f"{len(cands)} candidates declined")
        return False

    # ---- promotion choice --------------------------------------------------

    def choose_promotion(self, region_id: int) -> Optional[str]:
        """The standby that should take over `region_id`: freshest
        mirror (highest fitness) wins, ties broken by holder name for
        determinism.  None when no standby is registered."""
        best: Optional[str] = None
        best_fit = -1
        for holder in sorted(self._standbys.get(region_id, {})):
            fit = self._standbys[region_id][holder]["fitness"]()
            if fit > best_fit:
                best, best_fit = holder, fit
        return best

    async def promote_region(self, region_id: int):
        """Operator/controller-initiated failover: promote the chosen
        standby (its own `promote` callback acquires the lease — the
        election discipline holds even on the manual path).  Returns
        whatever the callback returns, or None with a recorded refusal
        when no standby exists."""
        holder = self.choose_promotion(region_id)
        if holder is None:
            self._record("promotion", "no_standby", region=region_id)
            return None
        try:
            result = await self._standbys[region_id][holder]["promote"]()
        except Exception as exc:
            self._record("promotion", "error", region=region_id,
                         detail=f"{holder}: {exc}")
            raise
        self._record("promotion", "executed", region=region_id,
                     detail=f"-> {holder}")
        return result

    # ---- the observing loop ------------------------------------------------

    def start(self, interval_s: Optional[float] = None) -> None:
        ensure(self._task is None, "placement controller already started")
        interval = (interval_s if interval_s is not None
                    else self.config.interval.seconds)
        self._task = loops.spawn(
            lambda hb: self._loop(hb, interval),
            name="placement-ctl", kind="placement", owner="cluster",
            period_s=interval,
            backlog=lambda: {"snapshot": self.snapshot,
                             "recent": self.history[-8:]})

    async def close(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self, hb, interval_s: float) -> None:
        while not self._stopping:
            await asyncio.sleep(interval_s)
            if self._stopping:
                return
            hb.beat()
            try:
                await self.refresh()
                hb.ok()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — next tick retries
                hb.error(exc)
                logger.warning("placement refresh failed: %s", exc)

    async def refresh(self) -> dict:
        """One observation pass: fold the manifest view (the health
        monitor's survey when fresh, else a direct region_stats read)
        together with the live lag probes into the snapshot that
        /debug/tasks serves — the controller's inputs are always
        inspectable next to its decisions."""
        survey = self.cluster.rebalance_survey
        if survey is not None:
            stats = survey.get("stats", {})
        else:
            stats = await self.cluster.region_stats()
        regions = {}
        for rid, s in stats.items():
            rid = int(rid)
            probe = self._lag_probes.get(rid)
            lag = probe() if probe is not None else None
            regions[rid] = {
                "bytes": s.get("bytes"),
                "rules": s.get("rules"),
                "lag_seqs": lag,
                "healthy": (lag is None
                            or lag <= self.config.max_replica_lag_seqs),
                "standbys": sorted(self._standbys.get(rid, {})),
            }
        self.snapshot = {
            "regions": regions,
            "nodes": {nid: {"load": n["load"]()}
                      for nid, n in self._nodes.items()},
            "at_ms": self._clock(),
        }
        return self.snapshot
