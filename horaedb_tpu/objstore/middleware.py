"""Resilient object-store middleware: retry, fault injection, metrics.

The engine's safety story is order-of-operations discipline; this module
adds the failure-domain hardening around it (no reference analogue —
the reference's object_store crate gets retries from the AWS SDK):

- `RetryingObjectStore`: backend-agnostic bounded retries with
  exponential backoff + jitter, a per-op deadline, and a shared retry
  *budget* (token bucket) so a store brown-out cannot amplify into a
  retry storm.  `NotFoundError` is semantic, not transient — it passes
  through untouched, as does cancellation.  The S3 backend keeps its own
  protocol-level retry loop (re-signing, multipart bookkeeping); this
  wrapper is the ONE retry layer the engine adds for every other
  backend, and is applied to the manifest plane (see storage.py).
- `FaultInjectingStore`: the single library-grade fault injector.
  Scripted one-shot/sticky faults keyed by (op, path substring) — the
  superset of the old test-local FlakyStore — plus seeded probabilistic
  faults, seeded latency injection, and crash-at-operation-index for
  the torture harness.  Faults fire either BEFORE the op (the op never
  happened) or AFTER it (the op landed but the ack was lost) — the
  distinction crash-consistency invariants care about.
- `InstrumentedStore`: per-op counters + latency histograms into
  `utils.metrics.MetricsRegistry` (exposed at /metrics).

All three wrap any `ObjectStore` and compose freely, e.g.
`InstrumentedStore(RetryingObjectStore(FaultInjectingStore(inner)))`.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Optional

from horaedb_tpu.common.error import Error
from horaedb_tpu.objstore.api import (
    DEFAULT_STREAM_CHUNK,
    NotFoundError,
    ObjectMeta,
    ObjectStore,
)
from horaedb_tpu.objstore.memory import MemoryObjectStore
from horaedb_tpu.utils import registry, tracing

OPS = ("put", "get", "get_range", "head", "delete", "list",
       "put_stream", "get_stream")


class WrappedObjectStore(ObjectStore):
    """Base delegating wrapper: every verb forwards to `inner`.

    Subclasses override `_call` (one interception point) rather than the
    six verbs, so a new verb added to the ABC cannot silently bypass a
    middleware."""

    def __init__(self, inner: ObjectStore):
        self.inner = inner

    async def _call(self, op: str, *args):
        return await getattr(self.inner, op)(*args)

    async def put(self, path: str, data: bytes) -> None:
        return await self._call("put", path, data)

    async def get(self, path: str) -> bytes:
        return await self._call("get", path)

    async def get_range(self, path: str, start: int, end: int) -> bytes:
        return await self._call("get_range", path, start, end)

    async def head(self, path: str) -> ObjectMeta:
        return await self._call("head", path)

    async def delete(self, path: str) -> None:
        return await self._call("delete", path)

    async def list(self, prefix: str) -> list[ObjectMeta]:
        return await self._call("list", prefix)

    async def put_stream(self, path: str, chunks) -> int:
        # routed through _call so middleware sees it (faults, metrics),
        # but chunk iterators are one-shot: the retry layer never
        # replays a stream, and no middleware may buffer it (the
        # backend's own put_stream owns its atomicity/cleanup story)
        return await self._call("put_stream", path, chunks)

    def get_stream(self, path: str,
                   chunk_size: int = DEFAULT_STREAM_CHUNK):
        # streamed reads delegate through _stream (the async-generator
        # twin of _call) so the INNER store's chunking survives
        # wrapping; like put_stream, streams are one-shot — the retry
        # layer never replays one (data-plane reads are single-shot by
        # the engine's retry discipline anyway)
        return self._stream("get_stream", path, chunk_size)

    async def _stream(self, op: str, path: str, chunk_size: int):
        del op  # interception point for subclasses
        async for chunk in self.inner.get_stream(path, chunk_size):
            yield chunk

    async def close(self) -> None:
        closer = getattr(self.inner, "close", None)
        if closer is not None:
            await closer()


# ---------------------------------------------------------------------------
# RetryingObjectStore
# ---------------------------------------------------------------------------

_RETRIES = registry.counter(
    "objstore_retries_total", "object-store operations retried")
_RETRY_BUDGET_EXHAUSTED = registry.counter(
    "objstore_retry_budget_exhausted_total",
    "retries suppressed because the retry budget was empty")
_DEADLINES_EXCEEDED = registry.counter(
    "objstore_deadline_exceeded_total",
    "object-store operations failed on their per-op deadline")


class DeadlineExceededError(Error):
    """Raised when an operation (including its retries) overruns the
    policy's per-op deadline.  Not retryable by construction."""


@dataclass
class RetryPolicy:
    """Knobs for RetryingObjectStore (see storage.config.RetryConfig for
    the TOML surface)."""

    max_retries: int = 2
    base_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    # total wall-clock allowed per operation INCLUDING retries/backoff;
    # None = unbounded
    op_deadline_s: Optional[float] = None
    # token bucket shared across all ops of one store: a retry spends a
    # token, tokens refill continuously — sustained failure degrades to
    # fail-fast instead of multiplying load on a struggling backend
    budget: float = 32.0
    budget_refill_per_s: float = 4.0


class _TokenBucket:
    def __init__(self, capacity: float, refill_per_s: float):
        self.capacity = capacity
        self.refill_per_s = refill_per_s
        self.tokens = capacity
        self._last = time.monotonic()

    def take(self, n: float = 1.0) -> bool:
        now = time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self._last) * self.refill_per_s)
        self._last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class RetryingObjectStore(WrappedObjectStore):
    """Bounded-retry decorator for any ObjectStore.

    Retryable = any exception except NotFoundError (semantic),
    CancelledError (cooperative shutdown), and DeadlineExceededError.
    `rng` is injectable so tests (and the seeded torture harness) get
    deterministic jitter."""

    def __init__(self, inner: ObjectStore,
                 policy: Optional[RetryPolicy] = None,
                 rng: Optional[random.Random] = None):
        super().__init__(inner)
        self.policy = policy or RetryPolicy()
        self._rng = rng or random.Random()
        self._budget = _TokenBucket(self.policy.budget,
                                    self.policy.budget_refill_per_s)

    async def _call(self, op: str, *args):
        policy = self.policy
        loop = asyncio.get_running_loop()
        deadline = (loop.time() + policy.op_deadline_s
                    if policy.op_deadline_s is not None else None)
        fn = getattr(self.inner, op)
        if op == "put_stream":
            # one-shot chunk iterator: a replay would re-send nothing.
            # Single attempt, deadline still enforced.
            if deadline is not None:
                try:
                    return await asyncio.wait_for(fn(*args),
                                                  timeout=policy.op_deadline_s)
                except (TimeoutError, asyncio.TimeoutError) as e:
                    _DEADLINES_EXCEEDED.inc()
                    raise DeadlineExceededError(
                        f"objstore {op} deadline exceeded "
                        f"({policy.op_deadline_s}s)") from e
            return await fn(*args)
        attempt = 0
        while True:
            try:
                if deadline is not None:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        _DEADLINES_EXCEEDED.inc()
                        raise DeadlineExceededError(
                            f"objstore {op} deadline exceeded "
                            f"({policy.op_deadline_s}s)")
                    return await asyncio.wait_for(fn(*args),
                                                  timeout=remaining)
                return await fn(*args)
            except (NotFoundError, DeadlineExceededError,
                    asyncio.CancelledError):
                raise
            except (TimeoutError, asyncio.TimeoutError) as e:
                # with a deadline armed, wait_for's TimeoutError IS the
                # deadline firing; without one it is the backend's own
                # timeout — transient, handled below (asyncio's alias is
                # a distinct class before Python 3.11, so catch both)
                if deadline is not None and loop.time() >= deadline:
                    _DEADLINES_EXCEEDED.inc()
                    raise DeadlineExceededError(
                        f"objstore {op} deadline exceeded "
                        f"({policy.op_deadline_s}s)") from e
                attempt = self._next_attempt(op, attempt, e)
                await self._backoff(attempt, deadline, loop)
            except Exception as e:  # noqa: BLE001 — retry boundary
                attempt = self._next_attempt(op, attempt, e)
                await self._backoff(attempt, deadline, loop)

    def _next_attempt(self, op: str, attempt: int, exc: Exception) -> int:
        attempt += 1
        if attempt > self.policy.max_retries:
            raise exc
        if not self._budget.take():
            _RETRY_BUDGET_EXHAUSTED.inc()
            raise exc
        _RETRIES.inc()
        return attempt

    async def _backoff(self, attempt: int, deadline: Optional[float],
                       loop) -> None:
        backoff = min(self.policy.max_backoff_s,
                      self.policy.base_backoff_s * (2 ** (attempt - 1)))
        backoff *= 1 + self._rng.random()  # full jitter upward
        if deadline is not None:
            # never sleep past the deadline; the next loop turn raises
            backoff = min(backoff, max(0.0, deadline - loop.time()))
        await asyncio.sleep(backoff)


# ---------------------------------------------------------------------------
# FaultInjectingStore
# ---------------------------------------------------------------------------


class InjectedFault(OSError):
    """A scripted or probabilistic transient fault.  Subclasses OSError
    so code under test treats it exactly like a real backend error."""


class InjectedCrash(Exception):
    """The simulated process death.  After it fires the store is halted:
    every subsequent op raises InjectedFault, so nothing can 'survive'
    the crash by accident — state below the crash point is exactly what
    a restart would recover from."""


@dataclass
class _FaultRule:
    op: str  # one of OPS or "*"
    path_part: str
    times: int  # remaining firings; -1 = sticky
    mode: str = "before"  # "before": op never ran; "after": ack lost

    def matches(self, op: str, path: str) -> bool:
        # "put" rules cover put_stream too (and "get" covers
        # get_stream): both are object writes/reads, and which variant
        # a code path uses is an implementation detail the fault script
        # should not have to know
        op_ok = (self.op in ("*", op)
                 or (self.op == "put" and op == "put_stream")
                 or (self.op == "get" and op == "get_stream"))
        return op_ok and self.path_part in path


class FaultInjectingStore(WrappedObjectStore):
    """Library-grade fault injector (replaces the test-local FlakyStore).

    - `fail_next(op, path_part)`: scripted faults; `times=-1` is sticky,
      `after=True` applies the op then raises (lost-ack).
    - `seed` + `fault_rate`: probabilistic faults, deterministic per
      seed.  Mutating ops (put/delete) pick before/after at 50/50; reads
      always fault before (a lost read ack is indistinguishable).
    - `latency_range`: seeded uniform delay injected before each op.
    - `crash_at`: global op index at which InjectedCrash fires and the
      store halts; `revive()` clears the halt (the "restart").
    """

    def __init__(self, inner: Optional[ObjectStore] = None,
                 seed: Optional[int] = None, fault_rate: float = 0.0,
                 latency_range: tuple[float, float] = (0.0, 0.0),
                 crash_at: Optional[int] = None):
        super().__init__(inner if inner is not None else MemoryObjectStore())
        self._rules: list[_FaultRule] = []
        self._rng = random.Random(seed)
        self.fault_rate = fault_rate
        self.latency_range = latency_range
        self.crash_at = crash_at
        self.ops_seen = 0
        self.halted = False

    # -- scripting ---------------------------------------------------------

    def fail_next(self, op: str, path_part: str, times: int = 1,
                  after: bool = False) -> None:
        self._rules.append(_FaultRule(op=op, path_part=path_part,
                                      times=times,
                                      mode="after" if after else "before"))

    def clear_faults(self) -> None:
        self._rules = []

    def crash(self) -> None:
        self.halted = True

    def revive(self) -> None:
        self.halted = False
        self.crash_at = None

    # -- injection ---------------------------------------------------------

    def _scripted(self, op: str, path: str) -> Optional[str]:
        """First matching rule's mode, consuming one firing."""
        for i, rule in enumerate(self._rules):
            if rule.matches(op, path):
                if rule.times > 0:
                    rule.times -= 1
                    if rule.times == 0:
                        del self._rules[i]
                return rule.mode
        return None

    def _probabilistic(self, op: str) -> Optional[str]:
        if self.fault_rate and self._rng.random() < self.fault_rate:
            if (op in ("put", "delete", "put_stream")
                    and self._rng.random() < 0.5):
                return "after"
            return "before"
        return None

    async def _call(self, op: str, *args):
        path = args[0] if args else ""
        if self.halted:
            raise InjectedFault(f"store halted (crashed): {op} {path}")
        self.ops_seen += 1
        if self.latency_range[1] > 0:
            await asyncio.sleep(self._rng.uniform(*self.latency_range))

        crash = self.crash_at is not None and self.ops_seen >= self.crash_at
        if crash:
            # a crash straddles the op like any fault: before = the op
            # never hit the backend, after = it landed but the process
            # died before acting on the response
            mode = ("after" if op in ("put", "delete", "put_stream")
                    and self._rng.random() < 0.5 else "before")
            if mode == "before":
                self.crash()
                raise InjectedCrash(f"crash before {op} {path}")
            await super()._call(op, *args)
            self.crash()
            raise InjectedCrash(f"crash after {op} {path}")

        mode = self._scripted(op, path) or self._probabilistic(op)
        if mode == "before":
            raise InjectedFault(f"injected {op} failure for {path}")
        result = await super()._call(op, *args)
        if mode == "after":
            raise InjectedFault(f"injected lost-ack {op} failure for {path}")
        return result

    async def _stream(self, op: str, path: str, chunk_size: int):
        """Streamed reads take the same injection points as get: the
        fault/crash fires at stream START (a read that dies mid-stream
        is indistinguishable from one that never started — callers see
        an exception either way, and reads have no ack to lose)."""
        if self.halted:
            raise InjectedFault(f"store halted (crashed): {op} {path}")
        self.ops_seen += 1
        if self.latency_range[1] > 0:
            await asyncio.sleep(self._rng.uniform(*self.latency_range))
        if self.crash_at is not None and self.ops_seen >= self.crash_at:
            self.crash()
            raise InjectedCrash(f"crash before {op} {path}")
        mode = self._scripted(op, path) or self._probabilistic(op)
        if mode is not None:
            raise InjectedFault(f"injected {op} failure for {path}")
        async for chunk in self.inner.get_stream(path, chunk_size):
            yield chunk


# ---------------------------------------------------------------------------
# InstrumentedStore
# ---------------------------------------------------------------------------


class InstrumentedStore(WrappedObjectStore):
    """Counts and times every op into a MetricsRegistry:

        objstore_<op>_total, objstore_<op>_errors_total,
        objstore_<op>_seconds (histogram)

    NotFoundError counts in _total but not _errors_total — a missing key
    is an answer, not a failure.

    When a request trace is ambient (utils.tracing), each op is ALSO
    attributed to it: `objstore_<op>_total`, wall ms, and — for
    get/get_range — `objstore_get_bytes`, so `/debug/traces/{id}`
    shows exactly how much store IO one query paid.  Ops after the
    trace finished attribute to nothing (the Trace drops late adds)."""

    def __init__(self, inner: ObjectStore, metrics=None,
                 prefix: str = "objstore"):
        super().__init__(inner)
        metrics = metrics if metrics is not None else registry
        self._ops = {}
        for op in OPS:
            self._ops[op] = (
                metrics.counter(f"{prefix}_{op}_total",
                                f"object-store {op} calls"),
                metrics.counter(f"{prefix}_{op}_errors_total",
                                f"object-store {op} failures"),
                metrics.histogram(f"{prefix}_{op}_seconds",
                                  f"object-store {op} latency"),
            )

    async def _call(self, op: str, *args):
        total, errors, seconds = self._ops[op]
        total.inc()
        t0 = time.perf_counter()
        result = None
        try:
            result = await super()._call(op, *args)
            return result
        except NotFoundError:
            raise
        except BaseException:
            errors.inc()
            raise
        finally:
            dt = time.perf_counter() - t0
            seconds.observe(dt)
            if tracing.active_trace() is not None:
                tracing.trace_add(f"objstore_{op}_total")
                tracing.trace_add(f"objstore_{op}_ms", dt * 1e3)
                if op in ("get", "get_range") and isinstance(
                        result, (bytes, bytearray)):
                    tracing.trace_add("objstore_get_bytes", len(result))

    async def _stream(self, op: str, path: str, chunk_size: int):
        """One get_stream op = one timed entry covering the full drain,
        with get-style byte attribution summed over chunks."""
        total, errors, seconds = self._ops["get_stream"]
        total.inc()
        t0 = time.perf_counter()
        nbytes = 0
        try:
            async for chunk in self.inner.get_stream(path, chunk_size):
                nbytes += len(chunk)
                yield chunk
        except NotFoundError:
            raise
        except BaseException:
            errors.inc()
            raise
        finally:
            dt = time.perf_counter() - t0
            seconds.observe(dt)
            if tracing.active_trace() is not None:
                tracing.trace_add("objstore_get_stream_total")
                tracing.trace_add("objstore_get_stream_ms", dt * 1e3)
                tracing.trace_add("objstore_get_bytes", nbytes)
