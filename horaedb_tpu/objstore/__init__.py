"""Object-storage abstraction (ref: object_store 0.11 crate usage).

The reference's data + metadata plane is `Arc<dyn ObjectStore>`
(ref: src/storage/src/types.rs:135), with LocalFileSystem used everywhere
and S3 config present but unimplemented.  We mirror that: an async ABC,
a local-filesystem impl, and an in-memory fake for tests.
"""

from horaedb_tpu.objstore.api import NotFoundError, ObjectMeta, ObjectStore
from horaedb_tpu.objstore.local import LocalObjectStore
from horaedb_tpu.objstore.memory import MemoryObjectStore
from horaedb_tpu.objstore.middleware import (
    DeadlineExceededError,
    FaultInjectingStore,
    InjectedCrash,
    InjectedFault,
    InstrumentedStore,
    RetryingObjectStore,
    RetryPolicy,
    WrappedObjectStore,
)

__all__ = [
    "DeadlineExceededError",
    "FaultInjectingStore",
    "InjectedCrash",
    "InjectedFault",
    "InstrumentedStore",
    "LocalObjectStore",
    "MemoryObjectStore",
    "NotFoundError",
    "ObjectMeta",
    "ObjectStore",
    "RetryPolicy",
    "RetryingObjectStore",
    "WrappedObjectStore",
]
