"""In-memory ObjectStore — the universal test fake (the reference uses
LocalFileSystem for this role; memory is faster and hermetic)."""

from __future__ import annotations

import asyncio

from horaedb_tpu.objstore.api import NotFoundError, ObjectMeta, ObjectStore


class MemoryObjectStore(ObjectStore):
    def __init__(self) -> None:
        self._objects: dict[str, bytes] = {}
        self._lock = asyncio.Lock()

    async def put(self, path: str, data: bytes) -> None:
        async with self._lock:
            self._objects[path] = bytes(data)

    async def get(self, path: str) -> bytes:
        async with self._lock:
            try:
                return self._objects[path]
            except KeyError:
                raise NotFoundError(f"object not found: {path}") from None

    async def get_range(self, path: str, start: int, end: int) -> bytes:
        data = await self.get(path)
        if start == 0 and end >= len(data):
            # whole-object range: skip the slice COPY — header probes
            # over small objects hit this constantly on the cold path
            return data
        return data[start:end]

    async def head(self, path: str) -> ObjectMeta:
        data = await self.get(path)
        return ObjectMeta(path=path, size=len(data))

    async def delete(self, path: str) -> None:
        async with self._lock:
            if path not in self._objects:
                raise NotFoundError(f"object not found: {path}")
            del self._objects[path]

    async def list(self, prefix: str) -> list[ObjectMeta]:
        async with self._lock:
            return sorted(
                (ObjectMeta(path=p, size=len(d))
                 for p, d in self._objects.items() if p.startswith(prefix)),
                key=lambda m: m.path,
            )
