"""In-memory ObjectStore — the universal test fake (the reference uses
LocalFileSystem for this role; memory is faster and hermetic)."""

from __future__ import annotations

import asyncio

from horaedb_tpu.common.memledger import ledger as memledger
from horaedb_tpu.objstore.api import NotFoundError, ObjectMeta, ObjectStore


class MemoryObjectStore(ObjectStore):
    def __init__(self) -> None:
        self._objects: dict[str, bytes] = {}
        self._lock = asyncio.Lock()
        # memory plane (common/memledger.py): the resident
        # parquet+sidecar copy is exactly what the 1B projection says
        # breaks first (ROADMAP item 3) — it must be an ACCOUNT, not
        # the unattributed residue.  O(1) running total; the account
        # anchors weakly (an abandoned test store prunes on the next
        # sweep — there is no close API to deregister from)
        self._resident_bytes = 0
        self._mem_account = memledger.register(
            "objstore_memory", lambda s: s._resident_bytes,
            anchor=self, kind="objstore_memory", owner="objstore")

    async def put(self, path: str, data: bytes) -> None:
        async with self._lock:
            old = self._objects.get(path)
            self._objects[path] = bytes(data)
            self._resident_bytes += len(data) - (
                0 if old is None else len(old))

    async def get(self, path: str) -> bytes:
        async with self._lock:
            try:
                return self._objects[path]
            except KeyError:
                raise NotFoundError(f"object not found: {path}") from None

    async def get_range(self, path: str, start: int, end: int) -> bytes:
        data = await self.get(path)
        if start == 0 and end >= len(data):
            # whole-object range: skip the slice COPY — header probes
            # over small objects hit this constantly on the cold path
            return data
        return data[start:end]

    async def head(self, path: str) -> ObjectMeta:
        data = await self.get(path)
        return ObjectMeta(path=path, size=len(data))

    async def delete(self, path: str) -> None:
        async with self._lock:
            if path not in self._objects:
                raise NotFoundError(f"object not found: {path}")
            self._resident_bytes -= len(self._objects[path])
            del self._objects[path]

    async def list(self, prefix: str) -> list[ObjectMeta]:
        async with self._lock:
            return sorted(
                (ObjectMeta(path=p, size=len(d))
                 for p, d in self._objects.items() if p.startswith(prefix)),
                key=lambda m: m.path,
            )
