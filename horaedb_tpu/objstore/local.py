"""Local-filesystem ObjectStore (ref: object_store::local::LocalFileSystem,
the store used by the server at src/server/src/main.rs:112).

Puts are atomic (temp file + rename) to preserve the manifest's
crash-consistency: a torn snapshot write must never be observable.
Blocking syscalls run in the default thread pool via asyncio.to_thread.
"""

from __future__ import annotations

import asyncio
import os
import tempfile

from horaedb_tpu.common.error import Error
from horaedb_tpu.objstore.api import (
    DEFAULT_STREAM_CHUNK,
    NotFoundError,
    ObjectMeta,
    ObjectStore,
)


class LocalObjectStore(ObjectStore):
    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _fs_path(self, path: str) -> str:
        fs = os.path.normpath(os.path.join(self.root, path.lstrip("/")))
        if not fs.startswith(self.root + os.sep) and fs != self.root:
            raise Error(f"path escapes store root: {path}")
        return fs

    async def put(self, path: str, data: bytes) -> None:
        def _put() -> None:
            fs = self._fs_path(path)
            os.makedirs(os.path.dirname(fs), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(fs), prefix=".tmp-put-")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                os.replace(tmp, fs)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        await asyncio.to_thread(_put)

    async def put_stream(self, path: str, chunks) -> int:
        """Stream chunks to a temp file, then rename — peak RSS is one
        chunk; the atomic-replace crash contract of put() holds."""
        fs = self._fs_path(path)

        def _open():
            os.makedirs(os.path.dirname(fs), exist_ok=True)
            return tempfile.mkstemp(dir=os.path.dirname(fs),
                                    prefix=".tmp-put-")

        fd, tmp = await asyncio.to_thread(_open)
        total = 0
        f = os.fdopen(fd, "wb")
        try:
            async for chunk in chunks:
                await asyncio.to_thread(f.write, chunk)
                total += len(chunk)
            await asyncio.to_thread(f.flush)
            f.close()
            await asyncio.to_thread(os.replace, tmp, fs)
            return total
        except BaseException:
            try:
                f.close()
            except OSError:
                pass
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    async def get(self, path: str) -> bytes:
        def _get() -> bytes:
            try:
                with open(self._fs_path(path), "rb") as f:
                    return f.read()
            except FileNotFoundError:
                raise NotFoundError(f"object not found: {path}") from None

        return await asyncio.to_thread(_get)

    async def get_stream(self, path: str,
                         chunk_size: int = DEFAULT_STREAM_CHUNK):
        """File chunks: peak RSS is one chunk, whatever the object
        size."""
        try:
            f = await asyncio.to_thread(open, self._fs_path(path), "rb")
        except FileNotFoundError:
            raise NotFoundError(f"object not found: {path}") from None
        try:
            while True:
                chunk = await asyncio.to_thread(f.read, chunk_size)
                if not chunk:
                    return
                yield chunk
        finally:
            await asyncio.to_thread(f.close)

    async def get_range(self, path: str, start: int, end: int) -> bytes:
        def _get_range() -> bytes:
            try:
                with open(self._fs_path(path), "rb") as f:
                    # clamp to the file size: read(count) PREALLOCATES
                    # count bytes, so a past-EOF range (callers use it
                    # for "the rest of the object") must not allocate
                    # the nominal span
                    f.seek(0, 2)
                    size = f.tell()
                    f.seek(start)
                    return f.read(max(0, min(end, size) - start))
            except FileNotFoundError:
                raise NotFoundError(f"object not found: {path}") from None

        return await asyncio.to_thread(_get_range)

    async def head(self, path: str) -> ObjectMeta:
        def _head() -> ObjectMeta:
            try:
                st = os.stat(self._fs_path(path))
            except FileNotFoundError:
                raise NotFoundError(f"object not found: {path}") from None
            return ObjectMeta(path=path, size=st.st_size)

        return await asyncio.to_thread(_head)

    async def delete(self, path: str) -> None:
        def _delete() -> None:
            try:
                os.unlink(self._fs_path(path))
            except FileNotFoundError:
                raise NotFoundError(f"object not found: {path}") from None

        await asyncio.to_thread(_delete)

    async def list(self, prefix: str) -> list[ObjectMeta]:
        def _list() -> list[ObjectMeta]:
            # Walk only the subtree the prefix's directory part points at —
            # the manifest merger lists the delta dir every few seconds and
            # must not pay for a scan of the (much larger) data/ tree.
            dir_part = prefix.rsplit("/", 1)[0] if "/" in prefix else ""
            walk_root = self._fs_path(dir_part) if dir_part else self.root
            if not os.path.isdir(walk_root):
                return []
            out: list[ObjectMeta] = []
            for dirpath, _dirnames, filenames in os.walk(walk_root):
                for name in filenames:
                    if name.startswith(".tmp-put-"):
                        continue
                    fs = os.path.join(dirpath, name)
                    key = os.path.relpath(fs, self.root).replace(os.sep, "/")
                    if key.startswith(prefix):
                        out.append(ObjectMeta(path=key, size=os.stat(fs).st_size))
            out.sort(key=lambda m: m.path)
            return out

        return await asyncio.to_thread(_list)

    def local_path(self, path: str) -> str:
        """Filesystem path for zero-copy reads (parquet mmap fast path)."""
        return self._fs_path(path)
