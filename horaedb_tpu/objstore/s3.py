"""S3-compatible ObjectStore over aiohttp with SigV4 signing.

The reference defines a full S3 config but panics "S3 not support yet"
(ref: src/server/src/main.rs:112, config.rs:82-160).  This client
implements the five-verb contract against any S3-compatible endpoint
(AWS, MinIO, GCS-interop): AWS Signature Version 4, path-style
addressing, ListObjectsV2 with continuation, ranged reads — plus the
production surface the reference's config models:

- bounded retries with exponential backoff + jitter on connection
  errors, timeouts, and retryable statuses (5xx/429), re-signing each
  attempt (max_retries, ref: config.rs default_max_retries);
- non-IO vs IO timeouts (timeout/io_timeout, ref: TimeoutOptions) and a
  per-host connection pool cap (ref: HttpOptions);
- multipart upload for objects over multipart_threshold (large SSTs),
  parts uploaded concurrently, aborted on failure;
- an optional key prefix (ref: S3LikeStorageConfig.prefix).

Payloads are signed with their SHA-256 (no UNSIGNED-PAYLOAD), so a
corrupted body is rejected by the server.  DELETE is S3-native
idempotent (one round trip; missing keys succeed) — the engine's
deletes are background/best-effort fan-outs.  Set
S3Options.strict_delete for the strict ObjectStore contract
(NotFoundError via a HEAD probe).  A retried multipart initiate sweeps
stray upload ids it may have created (ListMultipartUploads + abort),
so orphaned uploads don't silently accrue storage.
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import random
import re
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Optional

import aiohttp
import yarl

from horaedb_tpu.common.error import Error
from horaedb_tpu.objstore.api import (
    DEFAULT_STREAM_CHUNK,
    NotFoundError,
    ObjectMeta,
    ObjectStore,
)

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
_RETRYABLE_STATUSES = {429, 500, 502, 503, 504}


@dataclass
class S3Options:
    endpoint: str  # e.g. "http://127.0.0.1:9000"
    region: str
    bucket: str
    access_key_id: str
    secret_access_key: str
    # key prefix inside the bucket (ref: S3LikeStorageConfig.prefix)
    prefix: str = ""
    # bounded retry with backoff (ref: default_max_retries = 3)
    max_retries: int = 3
    retry_base_backoff_s: float = 0.1
    # non-IO (head/delete/list) vs IO (get/put) deadlines, seconds
    # (ref: TimeoutOptions)
    timeout_s: float = 10.0
    io_timeout_s: float = 10.0
    # connection pool cap (ref: HttpOptions.pool_max_idle_per_host)
    pool_max_per_host: int = 64
    # objects at/above this upload via multipart in part_size chunks
    multipart_threshold: int = 64 << 20
    multipart_part_size: int = 16 << 20
    multipart_concurrency: int = 4
    # When True, DELETE probes with HEAD first so missing keys raise
    # NotFoundError (the strict ObjectStore contract).  Default False:
    # the engine's deletes are best-effort background fan-outs
    # (compaction inputs, manifest deltas) and the extra HEAD doubles
    # round trips on exactly that hot path — S3's native idempotent
    # DELETE (204 for missing keys) is the right trade.
    strict_delete: bool = False

    def __post_init__(self) -> None:
        # a trailing slash would double up in signed paths and fail every
        # signature check
        self.endpoint = self.endpoint.rstrip("/")
        self.prefix = self.prefix.strip("/")


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _uri_encode(s: str, *, encode_slash: bool) -> str:
    safe = "-._~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def _xml_text(body: bytes, tag: str) -> str:
    """Text of the first `tag` element, namespace-agnostic."""
    root = ET.fromstring(body)
    for el in root.iter():
        if el.tag == tag or el.tag.endswith("}" + tag):
            return el.text or ""
    return ""


def _canonical_query(query: dict[str, str]) -> str:
    """AWS-canonical query string — used both for signing and for the
    URL actually sent, so signed and sent bytes cannot diverge (aiohttp's
    yarl encoding differs from AWS's, e.g. '/' left raw in values)."""
    return "&".join(
        f"{_uri_encode(k, encode_slash=True)}="
        f"{_uri_encode(v, encode_slash=True)}"
        for k, v in sorted(query.items()))


class SigV4Signer:
    """AWS Signature Version 4 (the s3 service flavor: single-chunk,
    signed payload hash)."""

    def __init__(self, opts: S3Options):
        self.opts = opts

    def sign(self, method: str, path: str, canonical_query: str,
             payload_sha256: str,
             now: Optional[datetime.datetime] = None) -> dict[str, str]:
        """canonical_query MUST be the exact query string sent on the
        wire (produced by _canonical_query) — taking the string rather
        than a dict makes signed==sent structural, not coincidental."""
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        host = urllib.parse.urlparse(self.opts.endpoint).netloc

        headers = {
            "host": host,
            "x-amz-content-sha256": payload_sha256,
            "x-amz-date": amz_date,
        }
        signed_headers = ";".join(sorted(headers))
        canonical_headers = "".join(
            f"{k}:{headers[k].strip()}\n" for k in sorted(headers))
        canonical_request = "\n".join([
            method, _uri_encode(path, encode_slash=False), canonical_query,
            canonical_headers, signed_headers, payload_sha256,
        ])

        scope = f"{datestamp}/{self.opts.region}/s3/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ])
        k = _hmac(("AWS4" + self.opts.secret_access_key).encode(), datestamp)
        k = _hmac(k, self.opts.region)
        k = _hmac(k, "s3")
        k = _hmac(k, "aws4_request")
        signature = hmac.new(k, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()

        return {
            "x-amz-content-sha256": payload_sha256,
            "x-amz-date": amz_date,
            "Authorization": (
                f"AWS4-HMAC-SHA256 "
                f"Credential={self.opts.access_key_id}/{scope}, "
                f"SignedHeaders={signed_headers}, Signature={signature}"),
        }


class S3ObjectStore(ObjectStore):
    def __init__(self, opts: S3Options,
                 session: Optional[aiohttp.ClientSession] = None):
        self.opts = opts
        self.signer = SigV4Signer(opts)
        self._session = session
        self._own_session = session is None

    async def _ensure(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(
                    limit_per_host=self.opts.pool_max_per_host))
        return self._session

    async def close(self) -> None:
        if self._own_session and self._session is not None:
            await self._session.close()
            self._session = None

    def _path(self, key: str) -> str:
        if self.opts.prefix:
            return f"/{self.opts.bucket}/{self.opts.prefix}/{key.lstrip('/')}"
        return f"/{self.opts.bucket}/{key.lstrip('/')}"

    async def _request(self, method: str, key: str,
                       query: Optional[dict[str, str]] = None,
                       data=b"",
                       extra_headers: Optional[dict] = None,
                       ok_status=(200,), io: bool = True,
                       collect: bool = False,
                       attempts_out: Optional[list] = None):
        """One S3 request with bounded retries: each attempt is re-signed
        (the date header changes) and backed off exponentially with
        jitter.  Callers only pass verbs that are safe to retry (the
        non-idempotent multipart complete handles its own lost-response
        case).  IO requests use progress-based timeouts (connect +
        socket read) rather than a total deadline, so a slow transfer
        that IS making progress never fails.

        With collect=True the body is read INSIDE the retry loop (a
        connection dying mid-body is retried like any other transient
        failure) and (response, body) is returned; otherwise the caller
        owns the unread response.

        `attempts_out`, when given, receives the number of attempts
        actually sent — callers with non-idempotent verbs (multipart
        initiate) use it to detect that a retry may have left server-side
        state behind."""
        query = query or {}
        path = self._path(key) if key is not None else f"/{self.opts.bucket}"
        payload_hash = (hashlib.sha256(data).hexdigest()
                        if data else _EMPTY_SHA256)
        cq = _canonical_query(query)
        # send the EXACT bytes that were signed: canonical-encoded path +
        # canonical query, marked pre-encoded so yarl doesn't re-quote
        url = yarl.URL(
            self.opts.endpoint + _uri_encode(path, encode_slash=False)
            + (f"?{cq}" if cq else ""),
            encoded=True)
        if io:
            timeout = aiohttp.ClientTimeout(connect=self.opts.timeout_s,
                                            sock_read=self.opts.io_timeout_s)
        else:
            timeout = aiohttp.ClientTimeout(total=self.opts.timeout_s)
        session = await self._ensure()

        last_err: Optional[str] = None
        for attempt in range(self.opts.max_retries + 1):
            if attempts_out is not None:
                attempts_out.append(attempt + 1)
            if attempt:
                backoff = (self.opts.retry_base_backoff_s * (2 ** (attempt - 1))
                           * (1 + random.random()))
                await asyncio.sleep(backoff)
            headers = self.signer.sign(method, path, cq, payload_hash)
            if extra_headers:
                headers.update(extra_headers)
            try:
                resp = await session.request(method, url, data=data,
                                             headers=headers,
                                             timeout=timeout)
                if resp.status in _RETRYABLE_STATUSES:
                    try:
                        detail = (await resp.text())[:200]
                    finally:
                        resp.release()
                    last_err = f"status {resp.status}: {detail}"
                    continue
                if resp.status == 404:
                    resp.release()
                    raise NotFoundError(f"object not found: {key}")
                if resp.status not in ok_status:
                    text = (await resp.text())[:300]
                    raise Error(f"s3 {method} {path} failed "
                                f"({resp.status}): {text}")
                if collect:
                    body = await resp.read()
                    resp.release()
                    return resp, body
                return resp
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                last_err = f"{type(e).__name__}: {e}"
                continue
        raise Error(f"s3 {method} {path} failed after "
                    f"{self.opts.max_retries + 1} attempts: {last_err}")

    # ---- ObjectStore ------------------------------------------------------

    async def put(self, path: str, data: bytes) -> None:
        if len(data) >= self.opts.multipart_threshold:
            await self._put_multipart(path, data)
            return
        resp = await self._request("PUT", path, data=data)
        resp.release()

    async def _initiate_multipart(self, path: str) -> str:
        """CreateMultipartUpload; a RETRIED initiate may have created an
        upload whose response was lost — that orphan would accrue
        storage until a bucket lifecycle rule fires.  SST keys have
        exactly one writer, so any OTHER in-progress upload for the key
        is a stray from our own retries: sweep them (best-effort)."""
        attempts: list = []
        _resp, body = await self._request("POST", path,
                                          query={"uploads": ""},
                                          collect=True,
                                          attempts_out=attempts)
        upload_id = _xml_text(body, "UploadId")
        if not upload_id:
            raise Error(f"s3 multipart initiate returned no UploadId "
                        f"for {path}")
        if len(attempts) > 1:
            await self._abort_stray_uploads(path, keep=upload_id)
        return upload_id

    async def _abort_multipart(self, path: str, upload_id: str) -> None:
        """Best-effort AbortMultipartUpload (the caller's error wins)."""
        try:
            r = await self._request("DELETE", path,
                                    query={"uploadId": upload_id},
                                    ok_status=(200, 204), io=False)
            r.release()
        except Exception:
            pass

    @staticmethod
    def _complete_xml(etags: list[tuple[int, str]]) -> bytes:
        parts = "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
            for n, e in etags)
        return (f"<CompleteMultipartUpload>{parts}"
                f"</CompleteMultipartUpload>").encode()

    @staticmethod
    def _expected_multipart_etag(etags: list[tuple[int, str]]
                                 ) -> Optional[str]:
        """The S3 multipart ETag is md5(concat(part md5s))-N and the
        part PUT responses already carry each part's md5 — build the
        expected object ETag from them (no client-side hashing) so a
        lost complete response can be verified.  SSE-KMS/SSE-C buckets
        return non-md5 part ETags; returns None there (size fallback
        still applies)."""
        try:
            digests = b"".join(bytes.fromhex(e.strip('"'))
                               for _n, e in etags)
            return f"{hashlib.md5(digests).hexdigest()}-{len(etags)}"
        except ValueError:
            return None

    async def _put_multipart(self, path: str, data: bytes) -> None:
        """Multipart upload: initiate, upload parts concurrently (each
        part retried independently by _request), complete; abort on any
        failure so no orphaned upload accrues storage."""
        upload_id = await self._initiate_multipart(path)
        part_size = self.opts.multipart_part_size
        view = memoryview(data)  # parts slice lazily — no payload copy
        n_parts = -(-len(data) // part_size)
        sem = asyncio.Semaphore(max(1, self.opts.multipart_concurrency))

        async def upload(num: int) -> tuple[int, str]:
            async with sem:
                chunk = view[(num - 1) * part_size: num * part_size]
                r = await self._request(
                    "PUT", path,
                    query={"partNumber": str(num), "uploadId": upload_id},
                    data=chunk)
                etag = r.headers.get("ETag", "")
                r.release()
                return num, etag

        try:
            tasks = [asyncio.create_task(upload(i + 1))
                     for i in range(n_parts)]
            try:
                etags = await asyncio.gather(*tasks)
            except BaseException:
                # stop in-flight siblings BEFORE aborting: parts racing
                # the abort can still be stored as orphans
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                raise
            etags = list(etags)
            await self._complete_multipart(
                path, upload_id, self._complete_xml(etags),
                self._expected_multipart_etag(etags), len(data))
        except BaseException:
            await self._abort_multipart(path, upload_id)
            raise

    async def put_stream(self, path: str, chunks) -> int:
        """Streaming put: chunks accumulate to multipart part size and
        upload as they fill, so peak memory is ~one part (16 MiB
        default), not the object.  Objects that finish under the
        multipart threshold fall back to one ordinary PUT.  On any
        failure the in-progress upload is aborted — no readable object
        and no orphaned parts."""
        part_size = self.opts.multipart_part_size
        buf = bytearray()
        upload_id: Optional[str] = None
        etags: list[tuple[int, str]] = []
        total = 0

        async def upload_part(data: bytes) -> None:
            nonlocal upload_id
            if upload_id is None:
                upload_id = await self._initiate_multipart(path)
            num = len(etags) + 1
            r = await self._request(
                "PUT", path,
                query={"partNumber": str(num), "uploadId": upload_id},
                data=data)
            etags.append((num, r.headers.get("ETag", "")))
            r.release()

        try:
            async for chunk in chunks:
                buf += chunk
                total += len(chunk)
                while len(buf) >= part_size:
                    await upload_part(bytes(buf[:part_size]))
                    del buf[:part_size]
            if upload_id is None:
                # small object: single PUT, no multipart bookkeeping
                await self.put(path, bytes(buf))
                return total
            if buf or not etags:
                await upload_part(bytes(buf))
            await self._complete_multipart(
                path, upload_id, self._complete_xml(etags),
                self._expected_multipart_etag(etags), total)
            return total
        except BaseException:
            if upload_id is not None:
                await self._abort_multipart(path, upload_id)
            raise

    async def _abort_stray_uploads(self, key: str, keep: str) -> None:
        """Abort in-progress multipart uploads for `key` other than
        `keep` (our live upload id).  Best-effort: listing may not be
        supported by every S3-alike, and a failure here must not fail
        the actual upload."""
        full_key = (f"{self.opts.prefix}/{key.lstrip('/')}" if self.opts.prefix
                    else key.lstrip("/"))
        try:
            _resp, body = await self._request(
                "GET", None, query={"uploads": "", "prefix": full_key},
                collect=True, io=False)
            root = ET.fromstring(body)
            strays = []
            for el in root.iter():
                if el.tag == "Upload" or el.tag.endswith("}Upload"):
                    k = uid = None
                    for child in el:
                        if child.tag == "Key" or child.tag.endswith("}Key"):
                            k = child.text
                        elif (child.tag == "UploadId"
                              or child.tag.endswith("}UploadId")):
                            uid = child.text
                    if k == full_key and uid and uid != keep:
                        strays.append(uid)
        except Exception:
            return  # listing failed; lifecycle rules are the backstop
        for uid in strays:
            try:
                r = await self._request("DELETE", key,
                                        query={"uploadId": uid},
                                        ok_status=(200, 204), io=False)
                r.release()
            except Exception:
                # one already-reaped (404) or failing abort must not
                # stop the sweep of the remaining strays
                continue

    async def _complete_multipart(self, path: str, upload_id: str,
                                  xml: bytes, expected_etag: str,
                                  expected_size: int) -> None:
        """CompleteMultipartUpload is NOT idempotent: a retry after a
        lost success response gets 404 NoSuchUpload — confirm via HEAD
        that OUR object landed (not a stale previous object at the same
        overwritten key) before treating that as success.  A 200 can
        also carry an error body (AWS documents InternalError-in-200
        for this call), which must not pass as success."""
        try:
            _resp, body = await self._request(
                "POST", path, query={"uploadId": upload_id}, data=xml,
                collect=True)
            if b"<Error" in body or not body:
                raise Error(f"s3 multipart complete for {path} returned "
                            f"an error body: {body[:200]!r}")
        except NotFoundError:
            # a previous attempt whose response was lost may have
            # completed the upload; verify the object at the key is OURS
            resp = await self._request("HEAD", path, io=False)
            etag = resp.headers.get("ETag", "").strip('"')
            size = int(resp.headers.get("Content-Length", -1))
            resp.release()
            # only an md5-shaped multipart ETag ("<32 hex>-N") is
            # comparable; encrypted buckets produce opaque ETags — fall
            # back to the size check there
            comparable = (expected_etag is not None and etag
                          and re.fullmatch(r"[0-9a-f]{32}-\d+", etag))
            if comparable:
                if etag != expected_etag:
                    raise Error(
                        f"s3 multipart complete for {path} lost its "
                        f"upload and the object present has ETag {etag} "
                        f"!= expected {expected_etag} (stale object)")
            elif size != expected_size:
                raise Error(
                    f"s3 multipart complete for {path} lost its upload "
                    f"and the object present has size {size} != "
                    f"expected {expected_size}")

    async def get(self, path: str) -> bytes:
        _resp, body = await self._request("GET", path, collect=True)
        return body

    async def get_stream(self, path: str,
                         chunk_size: int = DEFAULT_STREAM_CHUNK):
        """Chunked ranged GETs: one HEAD for the size, then sequential
        Range reads — a whole-SST fetch holds one chunk resident
        instead of the object.  (S3's own GET response could stream
        too, but ranged reads keep each wire op bounded and retryable
        by the backend's protocol-level retry loop.)"""
        meta = await self.head(path)
        off = 0
        while off < meta.size:
            end = min(meta.size, off + max(1, chunk_size))
            yield await self.get_range(path, off, end)
            off = end

    async def get_range(self, path: str, start: int, end: int) -> bytes:
        resp, data = await self._request(
            "GET", path, extra_headers={"Range": f"bytes={start}-{end - 1}"},
            ok_status=(200, 206), collect=True)
        if resp.status == 200:
            # endpoint (or a proxy) ignored the Range header: slice here
            # so callers always get exactly [start, end)
            return data[start:end]
        return data

    async def head(self, path: str) -> ObjectMeta:
        resp = await self._request("HEAD", path, io=False)
        try:
            return ObjectMeta(path=path,
                              size=int(resp.headers.get("Content-Length", 0)))
        finally:
            resp.release()

    async def delete(self, path: str) -> None:
        # S3 DELETE is idempotent (204 for missing keys).  Only
        # strict_delete pays a HEAD probe to honor the ObjectStore
        # contract's NotFoundError; the default single round trip is
        # what the engine's best-effort background deletes want.
        if self.opts.strict_delete:
            await self.head(path)
        resp = await self._request("DELETE", path, ok_status=(200, 204),
                                   io=False)
        resp.release()

    async def list(self, prefix: str) -> list[ObjectMeta]:
        out: list[ObjectMeta] = []
        token: Optional[str] = None
        # the configured bucket prefix is transparent to callers: it is
        # prepended on the wire and stripped from returned keys
        wire_prefix = prefix.lstrip("/")
        strip = ""
        if self.opts.prefix:
            strip = self.opts.prefix + "/"
            wire_prefix = strip + wire_prefix
        while True:
            query = {"list-type": "2", "prefix": wire_prefix}
            if token:
                query["continuation-token"] = token
            _resp, body = await self._request("GET", None, query=query,
                                              io=False, collect=True)
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for contents in root.findall(f"{ns}Contents"):
                key = contents.find(f"{ns}Key").text or ""
                size = int(contents.find(f"{ns}Size").text or 0)
                if strip and key.startswith(strip):
                    key = key[len(strip):]
                out.append(ObjectMeta(path=key, size=size))
            truncated = (root.findtext(f"{ns}IsTruncated") == "true")
            token = root.findtext(f"{ns}NextContinuationToken")
            if not truncated or not token:
                break
        out.sort(key=lambda m: m.path)
        return out
