"""S3-compatible ObjectStore over aiohttp with SigV4 signing.

The reference defines a full S3 config but panics "S3 not support yet"
(ref: src/server/src/main.rs:112, config.rs:82-160).  This client
implements the five-verb contract against any S3-compatible endpoint
(AWS, MinIO, GCS-interop): AWS Signature Version 4, path-style
addressing, ListObjectsV2 with continuation, ranged reads.

Payloads are signed with their SHA-256 (no UNSIGNED-PAYLOAD), so a
corrupted body is rejected by the server.  DELETE honors the
ObjectStore contract (NotFoundError for missing keys) via a HEAD
pre-flight — deletes are background/best-effort in the engine, so the
extra round trip is acceptable.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Optional

import aiohttp
import yarl

from horaedb_tpu.common.error import Error
from horaedb_tpu.objstore.api import NotFoundError, ObjectMeta, ObjectStore

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


@dataclass
class S3Options:
    endpoint: str  # e.g. "http://127.0.0.1:9000"
    region: str
    bucket: str
    access_key_id: str
    secret_access_key: str

    def __post_init__(self) -> None:
        # a trailing slash would double up in signed paths and fail every
        # signature check
        self.endpoint = self.endpoint.rstrip("/")


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _uri_encode(s: str, *, encode_slash: bool) -> str:
    safe = "-._~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def _canonical_query(query: dict[str, str]) -> str:
    """AWS-canonical query string — used both for signing and for the
    URL actually sent, so signed and sent bytes cannot diverge (aiohttp's
    yarl encoding differs from AWS's, e.g. '/' left raw in values)."""
    return "&".join(
        f"{_uri_encode(k, encode_slash=True)}="
        f"{_uri_encode(v, encode_slash=True)}"
        for k, v in sorted(query.items()))


class SigV4Signer:
    """AWS Signature Version 4 (the s3 service flavor: single-chunk,
    signed payload hash)."""

    def __init__(self, opts: S3Options):
        self.opts = opts

    def sign(self, method: str, path: str, canonical_query: str,
             payload_sha256: str,
             now: Optional[datetime.datetime] = None) -> dict[str, str]:
        """canonical_query MUST be the exact query string sent on the
        wire (produced by _canonical_query) — taking the string rather
        than a dict makes signed==sent structural, not coincidental."""
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        host = urllib.parse.urlparse(self.opts.endpoint).netloc

        headers = {
            "host": host,
            "x-amz-content-sha256": payload_sha256,
            "x-amz-date": amz_date,
        }
        signed_headers = ";".join(sorted(headers))
        canonical_headers = "".join(
            f"{k}:{headers[k].strip()}\n" for k in sorted(headers))
        canonical_request = "\n".join([
            method, _uri_encode(path, encode_slash=False), canonical_query,
            canonical_headers, signed_headers, payload_sha256,
        ])

        scope = f"{datestamp}/{self.opts.region}/s3/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ])
        k = _hmac(("AWS4" + self.opts.secret_access_key).encode(), datestamp)
        k = _hmac(k, self.opts.region)
        k = _hmac(k, "s3")
        k = _hmac(k, "aws4_request")
        signature = hmac.new(k, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()

        return {
            "x-amz-content-sha256": payload_sha256,
            "x-amz-date": amz_date,
            "Authorization": (
                f"AWS4-HMAC-SHA256 "
                f"Credential={self.opts.access_key_id}/{scope}, "
                f"SignedHeaders={signed_headers}, Signature={signature}"),
        }


class S3ObjectStore(ObjectStore):
    def __init__(self, opts: S3Options,
                 session: Optional[aiohttp.ClientSession] = None):
        self.opts = opts
        self.signer = SigV4Signer(opts)
        self._session = session
        self._own_session = session is None

    async def _ensure(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._own_session and self._session is not None:
            await self._session.close()
            self._session = None

    def _path(self, key: str) -> str:
        return f"/{self.opts.bucket}/{key.lstrip('/')}"

    async def _request(self, method: str, key: str,
                       query: Optional[dict[str, str]] = None,
                       data: bytes = b"",
                       extra_headers: Optional[dict] = None,
                       ok_status=(200,)) -> aiohttp.ClientResponse:
        query = query or {}
        path = self._path(key) if key is not None else f"/{self.opts.bucket}"
        payload_hash = (hashlib.sha256(data).hexdigest()
                        if data else _EMPTY_SHA256)
        cq = _canonical_query(query)
        headers = self.signer.sign(method, path, cq, payload_hash)
        if extra_headers:
            headers.update(extra_headers)
        session = await self._ensure()
        # send the EXACT bytes that were signed: canonical-encoded path +
        # canonical query, marked pre-encoded so yarl doesn't re-quote
        url = yarl.URL(
            self.opts.endpoint + _uri_encode(path, encode_slash=False)
            + (f"?{cq}" if cq else ""),
            encoded=True)
        resp = await session.request(method, url, data=data,
                                     headers=headers)
        if resp.status == 404:
            resp.release()
            raise NotFoundError(f"object not found: {key}")
        if resp.status not in ok_status:
            text = (await resp.text())[:300]
            raise Error(f"s3 {method} {path} failed "
                        f"({resp.status}): {text}")
        return resp

    # ---- ObjectStore ------------------------------------------------------

    async def put(self, path: str, data: bytes) -> None:
        resp = await self._request("PUT", path, data=data)
        resp.release()

    async def get(self, path: str) -> bytes:
        resp = await self._request("GET", path)
        try:
            return await resp.read()
        finally:
            resp.release()

    async def get_range(self, path: str, start: int, end: int) -> bytes:
        resp = await self._request(
            "GET", path, extra_headers={"Range": f"bytes={start}-{end - 1}"},
            ok_status=(200, 206))
        try:
            data = await resp.read()
        finally:
            resp.release()
        if resp.status == 200:
            # endpoint (or a proxy) ignored the Range header: slice here
            # so callers always get exactly [start, end)
            return data[start:end]
        return data

    async def head(self, path: str) -> ObjectMeta:
        resp = await self._request("HEAD", path)
        try:
            return ObjectMeta(path=path,
                              size=int(resp.headers.get("Content-Length", 0)))
        finally:
            resp.release()

    async def delete(self, path: str) -> None:
        # S3 DELETE is idempotent (204 for missing keys); the ObjectStore
        # contract wants NotFoundError, so probe first
        await self.head(path)
        resp = await self._request("DELETE", path, ok_status=(200, 204))
        resp.release()

    async def list(self, prefix: str) -> list[ObjectMeta]:
        out: list[ObjectMeta] = []
        token: Optional[str] = None
        while True:
            query = {"list-type": "2", "prefix": prefix.lstrip("/")}
            if token:
                query["continuation-token"] = token
            resp = await self._request("GET", None, query=query)
            try:
                body = await resp.read()
            finally:
                resp.release()
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for contents in root.findall(f"{ns}Contents"):
                key = contents.find(f"{ns}Key").text or ""
                size = int(contents.find(f"{ns}Size").text or 0)
                out.append(ObjectMeta(path=key, size=size))
            truncated = (root.findtext(f"{ns}IsTruncated") == "true")
            token = root.findtext(f"{ns}NextContinuationToken")
            if not truncated or not token:
                break
        out.sort(key=lambda m: m.path)
        return out
