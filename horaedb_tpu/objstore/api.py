"""ObjectStore interface (ref: object_store crate get/put/list/delete/head,
consumed at src/storage/src/manifest/mod.rs:139-156, storage.rs:213-217)."""

from __future__ import annotations

import abc
from dataclasses import dataclass

from horaedb_tpu.common.error import Error


class NotFoundError(Error):
    """Raised by get/head/delete when the object does not exist."""


@dataclass(frozen=True)
class ObjectMeta:
    path: str
    size: int


# default chunk for streamed whole-object reads (get_stream): large
# enough to amortize per-chunk overhead, small enough that a stream's
# resident footprint stays two orders of magnitude under a big SST
DEFAULT_STREAM_CHUNK = 8 << 20


class ObjectStore(abc.ABC):
    """Async key→bytes store; paths are '/'-separated keys, not OS paths."""

    @abc.abstractmethod
    async def put(self, path: str, data: bytes) -> None:
        """Atomically create/replace the object at `path`."""

    @abc.abstractmethod
    async def get(self, path: str) -> bytes:
        """Read the whole object; raises NotFoundError."""

    @abc.abstractmethod
    async def get_range(self, path: str, start: int, end: int) -> bytes:
        """Read bytes [start, end); raises NotFoundError."""

    @abc.abstractmethod
    async def head(self, path: str) -> ObjectMeta:
        """Object metadata; raises NotFoundError."""

    @abc.abstractmethod
    async def delete(self, path: str) -> None:
        """Delete the object.

        Memory/local backends raise NotFoundError for a missing key.
        S3 is idempotent by default (missing keys succeed — deletes are
        best-effort background fan-outs in the engine); opt into the
        probing NotFoundError contract with S3Options.strict_delete.
        Callers must not rely on NotFoundError from delete() for
        correctness."""

    @abc.abstractmethod
    async def list(self, prefix: str) -> list[ObjectMeta]:
        """All objects whose path starts with `prefix`, sorted by path."""

    async def get_stream(self, path: str,
                         chunk_size: int = DEFAULT_STREAM_CHUNK):
        """Read the whole object as an async iterator of byte chunks.

        Streaming-capable backends bound peak RSS by `chunk_size` —
        Local reads file chunks, S3 issues ranged GETs — so a whole-SST
        fetch of a multi-GiB object never materializes it in the
        caller's memory (the consumer decides where the bytes land:
        a spooled temp file for parquet decode, a socket for a proxy).
        This default falls back to ONE `get` (correct for the in-RAM
        memory store, where the object IS a resident buffer already)
        and re-chunks it, so every store satisfies the contract.
        Raises NotFoundError like get()."""
        data = await self.get(path)
        for off in range(0, len(data), max(1, chunk_size)):
            yield data[off:off + chunk_size]

    async def put_stream(self, path: str, chunks) -> int:
        """Atomically create/replace `path` from an async iterator of
        byte chunks; returns total bytes written.

        Streaming-capable backends (local files, S3 multipart) bound
        peak memory by the chunk/part size — a 1 GiB compaction output
        costs one row group of RSS, not 1 GiB (ref: the reference
        streams AsyncArrowWriter -> ParquetObjectWriter,
        storage.rs:192-212).  This default buffers (correct for the
        in-RAM memory store, where the object IS the buffer).  Partial
        failures must not leave a readable object at `path`."""
        buf = bytearray()
        async for chunk in chunks:
            buf += chunk
        await self.put(path, bytes(buf))
        return len(buf)
