"""[scanagent] configuration: the near-data shard map + client policy.

The shard map is CONFIG-DECLARED (PAPERS.md "Near Data Processing in
Taurus Database": the coordinator knows which storage node holds which
rows; here, which agent is colocated with which store shard).  Segments
hash onto `num_slots` round-robin slots by segment index
(segment_start // segment_duration), and each agent declares the slots
it owns.  A segment whose slot no agent owns is UNCOVERED and scans
through the normal direct path; a covered segment routes to its owning
agent and falls back per segment on agent failure.

`mode = "off"` (the default) detaches routing entirely and reproduces
the direct scan byte-for-byte — THE control the seeded chaos suite
compares against (tests/test_scanagent.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from horaedb_tpu.common import Error, ReadableDuration, ensure

SCANAGENT_MODES = ("off", "on")


@dataclass(frozen=True)
class AgentSpec:
    """One near-data agent: a name (metric label), its HTTP base URL,
    and the shard slots it owns."""

    name: str
    url: str
    slots: tuple = ()


@dataclass
class ScanAgentConfig:
    """[scanagent]: near-data aggregate routing (scanagent/)."""

    # "on" routes covered segments' aggregate scans to their agents;
    # "off" (default) is the direct-scan bit-identity control
    mode: str = "off"
    # shard slots in the map; slot(segment) = segment_index % num_slots
    num_slots: int = 1
    agents: tuple = ()
    # per-RPC total timeout cap; the effective budget is
    # min(timeout, ambient deadline remaining), like every remote RPC
    timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("10s"))
    # agents refuse to serialize a per-segment partial beyond this
    # (HTTP 413); the coordinator falls back to the direct read — a
    # pathological group-cardinality segment must not ship a "partial"
    # bigger than the rows it summarizes
    max_partial_bytes: int = 32 << 20
    # per-segment fallback to direct store reads on agent error/
    # timeout/breaker-open.  False = degraded gather: failed segments
    # are DROPPED from the grid with scanagent_degraded_segments_total
    # accounting (the cluster tier's partial-results discipline; see
    # docs/robustness.md near-data failure domains)
    fallback: bool = True
    # consecutive per-agent failures that open its circuit, and how
    # long an open circuit waits before admitting a probe
    breaker_failures: int = 3
    breaker_cooldown: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("5s"))
    # concurrent segment RPCs per agent: excess segments queue at the
    # coordinator WITHOUT their RPC budget ticking (the timeout is
    # taken after the slot) — an unbounded gather over a 1000-segment
    # cold scan would otherwise queue on the connector with the clock
    # running, time out spuriously, and open breakers under exactly
    # the load routing exists for
    max_inflight_per_agent: int = 16

    def __post_init__(self):
        ensure(self.mode in SCANAGENT_MODES,
               f"unknown [scanagent] mode {self.mode!r}; expected one "
               f"of {SCANAGENT_MODES}")
        ensure(self.num_slots >= 1,
               "[scanagent] num_slots must be >= 1")
        ensure(self.max_inflight_per_agent >= 1,
               "[scanagent] max_inflight_per_agent must be >= 1")
        for a in self.agents:
            for s in a.slots:
                ensure(0 <= s < self.num_slots,
                       f"[scanagent] agent {a.name!r} slot {s} outside "
                       f"[0, {self.num_slots})")

    @property
    def active(self) -> bool:
        return self.mode == "on" and bool(self.agents)

    def slot_of(self, segment_start: int, segment_duration_ms: int) -> int:
        return (segment_start // max(1, segment_duration_ms)) \
            % self.num_slots

    def owner(self, segment_start: int,
              segment_duration_ms: int) -> "AgentSpec | None":
        """The agent owning a segment's slot, or None (uncovered)."""
        slot = self.slot_of(segment_start, segment_duration_ms)
        for a in self.agents:
            if slot in a.slots:
                return a
        return None


_AGENT_KEYS = {"name", "url", "slots"}
_CONFIG_KEYS = {"mode", "num_slots", "agents", "timeout",
                "max_partial_bytes", "fallback", "breaker_failures",
                "breaker_cooldown", "max_inflight_per_agent"}
_DURATION_KEYS = {"timeout", "breaker_cooldown"}


def _agent_from_dict(data: dict, where: str) -> AgentSpec:
    ensure(isinstance(data, dict), f"{where} expects a table")
    unknown = set(data) - _AGENT_KEYS
    if unknown:
        raise Error(f"unknown {where} keys: {sorted(unknown)}")
    name = data.get("name", "")
    url = data.get("url", "")
    ensure(isinstance(name, str) and name,
           f"{where} requires a non-empty name")
    ensure(isinstance(url, str) and url,
           f"{where} requires a non-empty url")
    slots = data.get("slots", [])
    ensure(isinstance(slots, (list, tuple))
           and all(isinstance(s, int) and not isinstance(s, bool)
                   for s in slots),
           f"{where} slots expects a list of integers")
    return AgentSpec(name=name, url=url.rstrip("/"), slots=tuple(slots))


def scanagent_from_dict(data: dict) -> ScanAgentConfig:
    """[scanagent] TOML table -> ScanAgentConfig; unknown keys rejected
    (the repo-wide deny_unknown_fields discipline)."""
    ensure(isinstance(data, dict), "[scanagent] must be a table")
    unknown = set(data) - _CONFIG_KEYS
    if unknown:
        raise Error(f"unknown config keys for [scanagent]: "
                    f"{sorted(unknown)}")
    kwargs: dict = {}
    for key, value in data.items():
        if key in _DURATION_KEYS:
            if not isinstance(value, ReadableDuration):
                ensure(isinstance(value, str),
                       f'[scanagent] {key} expects a duration string '
                       f'like "10s"')
                value = ReadableDuration.parse(value)
            kwargs[key] = value
        elif key == "agents":
            ensure(isinstance(value, (list, tuple)),
                   "[scanagent] agents expects an array of tables")
            kwargs[key] = tuple(
                _agent_from_dict(a, f"[scanagent.agents[{i}]]")
                for i, a in enumerate(value))
        elif key == "fallback":
            ensure(isinstance(value, bool),
                   "[scanagent] fallback expects a boolean")
            kwargs[key] = value
        elif key == "mode":
            ensure(isinstance(value, str),
                   "[scanagent] mode expects a string")
            kwargs[key] = value
        else:  # num_slots / max_partial_bytes / breaker_failures
            ensure(isinstance(value, int) and not isinstance(value, bool),
                   f"[scanagent] {key} expects an integer")
            kwargs[key] = value
    names = [a.name for a in kwargs.get("agents", ())]
    ensure(len(names) == len(set(names)),
           "[scanagent] agent names must be unique")
    return ScanAgentConfig(**kwargs)
