"""Near-data scan agents (PAPERS.md "Near Data Processing in Taurus
Database"): push filter + partial-aggregate to the store shard.

  config.py  [scanagent] — the config-declared shard map + policy
  wire.py    plan request (JSON) / partial response (Arrow IPC)
  agent.py   AgentService — the store-colocated HTTP service
  client.py  ScanAgentClient + ScanRouter — coordinator-side routing
"""

from horaedb_tpu.scanagent.config import (
    AgentSpec,
    ScanAgentConfig,
    scanagent_from_dict,
)
from horaedb_tpu.scanagent.agent import AgentService
from horaedb_tpu.scanagent.client import (
    AgentError,
    ScanAgentClient,
    ScanRouter,
)

__all__ = [
    "AgentSpec",
    "ScanAgentConfig",
    "scanagent_from_dict",
    "AgentService",
    "AgentError",
    "ScanAgentClient",
    "ScanRouter",
]
