"""Coordinator side of the near-data scan plane: the per-agent HTTP
client (deadline-budgeted, circuit-broken) and the ScanRouter the
reader consults from `aggregate_segments`.

Routing contract (docs/robustness.md, near-data failure domains):

  * the shard map is config-declared ([scanagent]); covered segments'
    aggregate RPCs run CONCURRENTLY with the normal pipeline scanning
    the uncovered rest;
  * every agent failure is handled PER SEGMENT: error / timeout /
    breaker-open / oversized-partial / stale-SSTs all fall back to the
    direct store read (`scanagent_fallback_total{reason=}`), so a dead
    agent degrades a query's latency, never its answer;
  * with `[scanagent] fallback = false` a failed shard instead DROPS
    its segments with degraded-gather accounting
    (`scanagent_degraded_segments_total`) — the cluster tier's
    partial-results discipline, for deployments where the coordinator
    has no direct path to the shard's bytes;
  * a tenant quota 429 from the agent re-raises as QuotaExceeded — a
    quota breach must surface to the client as the same 429 it would
    get from a local scan, not burn MORE resources falling back.

Every RPC carries an explicit `aiohttp.ClientTimeout` of
`min([scanagent] timeout, ambient deadline remaining)` plus the
X-Deadline-Ms / X-Trace-Id / X-Tenant headers, so the agent's work is
bounded, attributed, and charged exactly like the coordinator's own.
"""

from __future__ import annotations

import asyncio
import math
from typing import Optional

import aiohttp

from horaedb_tpu.cluster.breaker import BreakerConfig, CircuitBreaker
from horaedb_tpu.common.deadline import (
    current_deadline,
    remaining_budget,
)
from horaedb_tpu.common.error import Error
from horaedb_tpu.common.tenant import QuotaExceeded, current_tenant
from horaedb_tpu.scanagent import wire
from horaedb_tpu.scanagent.config import AgentSpec, ScanAgentConfig
from horaedb_tpu.utils import registry, span, tracing

_REQUESTS = registry.counter(
    "scanagent_requests_total",
    "near-data scan RPCs issued by the coordinator, by agent and "
    "outcome")
_PARTIAL_BYTES = registry.counter(
    "scanagent_partial_bytes_total",
    "serialized partial bytes received from agents (the coordinator's "
    "data-plane bytes on agent-served segments)")
_FALLBACKS = registry.counter(
    "scanagent_fallback_total",
    "covered segments that fell back to direct store reads, by reason")
_DEGRADED = registry.counter(
    "scanagent_degraded_segments_total",
    "covered segments DROPPED because their shard was lost and "
    "[scanagent] fallback is disabled (degraded gather)")

# memory plane (common/memledger.py): serialized partials buffered
# between receive and decode.  Transient — a gather holds at most
# max_inflight_per_agent responses per agent — but at 32 MB per
# partial cap that is real RSS the coordinator must attribute
from horaedb_tpu.common.memledger import ledger as _memledger  # noqa: E402

_WIRE_ACCOUNT = _memledger.flow(
    "scanagent_wire", kind="scanagent_wire", owner="scanagent/client")


class AgentError(Error):
    """A per-segment agent failure the router may fall back on.
    `reason` feeds scanagent_fallback_total{reason=}."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"scanagent {reason}"
                         + (f": {detail}" if detail else ""))


class ScanAgentClient:
    """HTTP client for the agent protocol, shared by every routed
    table: one session, one circuit breaker per agent."""

    def __init__(self, config: ScanAgentConfig,
                 session: Optional[aiohttp.ClientSession] = None):
        self.config = config
        self._session = session
        self._own_session = session is None
        bc = BreakerConfig(failure_threshold=config.breaker_failures,
                           open_cooldown=config.breaker_cooldown,
                           rpc_timeout=config.timeout, retries=0)
        self.breakers = {a.name: CircuitBreaker(f"agent:{a.name}", bc)
                         for a in config.agents}

    async def _ensure_session(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._own_session and self._session is not None:
            await self._session.close()
            self._session = None

    def _budget(self) -> tuple[aiohttp.ClientTimeout, dict]:
        """(per-RPC timeout, propagation headers) — the RemoteRegion
        discipline: never inherit aiohttp's 5-minute default, never
        outlive the ambient deadline, and raise rather than fire an RPC
        whose request is already out of time."""
        dl = current_deadline()
        if dl is not None:
            dl.check()
        budget = remaining_budget(self.config.timeout.seconds)
        headers = {}
        if dl is not None and dl.deadline_at is not None:
            headers["X-Deadline-Ms"] = str(
                max(1, math.floor((budget or 0.0) * 1000)))
        trace = tracing.active_trace()
        if trace is not None and not trace.finished:
            headers[tracing.TRACE_HEADER] = trace.trace_id
        tenant = current_tenant()
        if tenant is not None:
            headers["X-Tenant"] = tenant.name
        return aiohttp.ClientTimeout(total=budget), headers

    async def _register_table(self, agent: AgentSpec,
                              table_meta: dict) -> None:
        import base64

        session = await self._ensure_session()
        timeout, headers = self._budget()
        body = dict(table_meta)
        body["schema"] = base64.b64encode(body["schema"]).decode("ascii")
        async with session.post(agent.url + "/v1/tables", json=body,
                                timeout=timeout,
                                headers=headers) as resp:
            if resp.status != 200:
                raise AgentError(
                    "error", f"table registration returned "
                             f"{resp.status}: "
                             f"{(await resp.text())[:200]}")

    # AgentError reasons that are protocol ANSWERS from a live agent
    # (oversized refusal, stale plan, its deadline share expired, an
    # unknown table after the registration retry): these settle the
    # breaker as a SUCCESS — without it, a half-open probe ending in a
    # refusal would leak the probe slot (breaker.allow admits exactly
    # one probe) and disable the agent for the life of the process
    _PROTOCOL_REASONS = frozenset({"oversized", "stale", "deadline",
                                   "unknown_table"})

    async def scan_segment(self, agent: AgentSpec, body: dict,
                           table_meta: dict) -> list:
        """One covered segment's partials from its owning agent, or
        AgentError(reason) for the router's fallback dispatch.
        QuotaExceeded propagates (never a direct read that spends
        more); an agent 504 first re-checks the AMBIENT deadline — an
        expired query propagates DeadlineExceeded, while a 504 caused
        only by the per-RPC cap falls back with the budget that
        remains."""
        breaker = self.breakers[agent.name]
        if not breaker.allow():
            _REQUESTS.labels(agent=agent.name,
                             outcome="breaker_open").inc()
            raise AgentError("breaker_open", agent.name)
        try:
            parts = await self._scan_once(agent, body, table_meta)
        except QuotaExceeded:
            breaker.record_success()  # the agent answered; the quota
            raise                     # is the tenant's outcome
        except AgentError as e:
            if e.reason in self._PROTOCOL_REASONS:
                breaker.record_success()
            # "error" answers recorded their failure at the classify
            # site; connect failures below record theirs here
            raise
        except asyncio.CancelledError:
            breaker.abort_probe()
            raise
        except (asyncio.TimeoutError, TimeoutError) as e:
            breaker.record_failure()
            _REQUESTS.labels(agent=agent.name, outcome="timeout").inc()
            raise AgentError("timeout", str(e)) from e
        except Exception as e:  # noqa: BLE001 — RPC boundary
            breaker.record_failure()
            _REQUESTS.labels(agent=agent.name, outcome="error").inc()
            raise AgentError("error", str(e)) from e
        breaker.record_success()
        return parts

    async def _scan_once(self, agent: AgentSpec, body: dict,
                         table_meta: dict) -> list:
        session = await self._ensure_session()
        for attempt in (0, 1):
            timeout, headers = self._budget()
            async with session.post(agent.url + "/v1/scan", json=body,
                                    timeout=timeout,
                                    headers=headers) as resp:
                if resp.status == 200:
                    # wire bytes are resident from the body read until
                    # decode returns (the decoded parts re-own the
                    # values as numpy).  Charged at Content-Length
                    # BEFORE the read await — concurrent gathers'
                    # in-flight bodies must overlap in the account,
                    # which a charge around the synchronous decode
                    # alone can never show — then trued up to the
                    # actual size
                    held = int(resp.headers.get("Content-Length") or 0)
                    _WIRE_ACCOUNT.charge(held)
                    try:
                        data = await resp.read()
                        if len(data) > held:
                            _WIRE_ACCOUNT.charge(len(data) - held)
                        elif held > len(data):
                            _WIRE_ACCOUNT.credit(held - len(data))
                        held = len(data)
                        tracing.ingest_export(
                            resp.headers.get(tracing.EXPORT_HEADER))
                        _REQUESTS.labels(agent=agent.name,
                                         outcome="ok").inc()
                        _PARTIAL_BYTES.inc(len(data))
                        tracing.trace_add("scanagent_partial_bytes",
                                          len(data))
                        return wire.decode_parts(data)
                    finally:
                        _WIRE_ACCOUNT.credit(held)
                tracing.ingest_export(
                    resp.headers.get(tracing.EXPORT_HEADER))
                err = await self._classify_error(agent, resp)
                if err == "unknown_table" and attempt == 0:
                    await self._register_table(agent, table_meta)
                    continue
                raise AgentError(err)
        raise AgentError("error", "unreachable")  # pragma: no cover

    async def _classify_error(self, agent: AgentSpec,
                              resp) -> str:
        """Map a non-200 agent response to a fallback reason — or
        raise, for statuses that must propagate (tenant quota).  The
        agent ANSWERED: these are protocol outcomes, not breaker
        failures (a healthy agent refusing an oversized partial must
        not open its circuit)."""
        try:
            payload = await resp.json()
        except Exception:  # noqa: BLE001 — error body may be html
            payload = {}
        code = payload.get("code", "")
        if resp.status == 429 and code == "quota":
            _REQUESTS.labels(agent=agent.name, outcome="quota").inc()
            raise QuotaExceeded(payload.get("tenant", "?"),
                                payload.get("quota", "scan_bytes"),
                                float(payload.get("retry_after_s", 1.0)))
        if resp.status == 504:
            # the agent's budget was min(rpc cap, query remaining): if
            # the QUERY deadline is what expired, propagate — a
            # fallback would burn time the request no longer has.  If
            # only the per-RPC cap fired, the direct read still has
            # budget and the segment falls back (reason="deadline").
            dl = current_deadline()
            if dl is not None:
                dl.check()
        outcome = {
            413: "oversized",
            504: "deadline",
            409: "stale",
            404: "unknown_table" if code == "unknown_table" else "error",
        }.get(resp.status, "error")
        _REQUESTS.labels(agent=agent.name, outcome=outcome).inc()
        if outcome == "error":
            # a 500-class answer counts against the breaker: the agent
            # is failing scans, not refusing one
            self.breakers[agent.name].record_failure()
        return outcome


class ScanRouter:
    """Per-table routing state the reader consults: the shard map
    (from [scanagent]) plus everything needed to phrase a segment's
    plan as an agent request."""

    def __init__(self, config: ScanAgentConfig, client: ScanAgentClient,
                 table_root: str, schema, num_primary_keys: int,
                 segment_duration_ms: int):
        self.config = config
        self.client = client
        self.table_root = table_root.rstrip("/")
        self.segment_duration_ms = segment_duration_ms
        # the agent rebuilds the table from this on auto-registration
        self._table_meta = {
            "table": self.table_root,
            "num_primary_keys": num_primary_keys,
            "segment_duration_ms": segment_duration_ms,
            "schema": schema.serialize().to_pybytes(),
        }

    @property
    def active(self) -> bool:
        return self.config.active

    def split(self, segments: list) -> tuple[list, list]:
        """(covered [(agent, segment)], uncovered [segment])."""
        covered, uncovered = [], []
        for seg in segments:
            agent = self.config.owner(seg.segment_start,
                                      self.segment_duration_ms)
            if agent is None:
                uncovered.append(seg)
            else:
                covered.append((agent, seg))
        return covered, uncovered

    def covers_any(self, segments: list) -> bool:
        return self.active and any(
            self.config.owner(s.segment_start,
                              self.segment_duration_ms) is not None
            for s in segments)

    async def gather(self, plan, spec, covered: list
                     ) -> tuple[list, list]:
        """All covered segments' partials, concurrently: returns
        (served [(segment_start, parts)], failed [SegmentPlan]) —
        `failed` is what the reader's declared fallback seam scans
        directly.  QuotaExceeded / DeadlineExceeded abort the whole
        gather and propagate."""

        # per-agent in-flight bound: a queued segment's RPC budget must
        # not tick while it waits for a slot (the timeout is derived
        # inside scan_segment, after acquisition) — see
        # [scanagent] max_inflight_per_agent
        sems = {a.name: asyncio.Semaphore(
            self.config.max_inflight_per_agent)
            for a, _seg in covered}

        async def one(agent: AgentSpec, seg):
            body = wire.encode_scan_request(
                self.table_root, seg.segment_start, seg.ssts,
                plan.range, plan.predicate, spec)
            body["columns"] = list(seg.columns)
            async with sems[agent.name]:
                with span("scanagent_rpc", agent=agent.name,
                          segment=seg.segment_start):
                    return await self.client.scan_segment(
                        agent, body, self._table_meta)

        tasks = [asyncio.create_task(one(agent, seg))
                 for agent, seg in covered]
        try:
            results = await asyncio.gather(*tasks,
                                           return_exceptions=True)
        except asyncio.CancelledError:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        served, failed = [], []
        for (agent, seg), res in zip(covered, results):
            if isinstance(res, AgentError):
                if self.config.fallback:
                    _FALLBACKS.labels(reason=res.reason).inc()
                    tracing.trace_add("scanagent_fallback_segments")
                    failed.append(seg)
                else:
                    _DEGRADED.inc()
                    tracing.trace_add("scanagent_degraded_segments")
                continue
            if isinstance(res, BaseException):
                # QuotaExceeded, DeadlineExceeded, cancellation, bugs:
                # not fallback material — the query's outcome
                raise res
            served.append((seg.segment_start, res))
            tracing.trace_add("scanagent_served_segments")
        return served, failed
