"""Near-data scan agent: a small HTTP service colocated with a store
shard that executes aggregate scan plans over its LOCALLY-resident SSTs
and returns per-segment partials instead of segments (PAPERS.md "Near
Data Processing in Taurus Database": push filter + partial-aggregate to
where the bytes live).

The agent wraps any `ObjectStore` and reuses the engine's OWN read
path — `ParquetReader.aggregate_segments` with the fused sidecar
decode, leaf-filter/merge-dedup/bucket-aggregate pipeline, tier-2
cache, and device-decode routing all intact — so an agent-served
partial is produced by exactly the code the coordinator would have run,
which is what makes the end-to-end grids byte-identical with the
direct scan (tests/test_scanagent.py asserts it under seeded chaos).

Request surface:

  GET  /            liveness probe
  POST /v1/tables   register a table (schema travels as Arrow IPC)
  POST /v1/scan     one segment's aggregate partials (wire.py)

Headers honored end to end: `X-Deadline-Ms` binds the ambient deadline
(PR 2) so an expired budget aborts the scan at the next cooperative
checkpoint and answers 504; `X-Tenant` binds the tenant scope (PR 10)
so the scan-byte quota is charged AT the agent — the 429 carries the
bucket's deficit-derived Retry-After for the coordinator to surface;
`X-Trace-Id` adopts the coordinator's trace (PR 5) and the agent's
spans ride back on `X-Trace-Export` for stitching under the routing
span.
"""

from __future__ import annotations

import asyncio
import base64
import logging
import math
from typing import Optional

import pyarrow as pa

from aiohttp import web

from horaedb_tpu.common.deadline import (
    Deadline,
    DeadlineExceeded,
    deadline_scope,
)
from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.tenant import (
    QuotaExceeded,
    TenantRegistry,
    tenant_scope,
)
from horaedb_tpu.objstore import NotFoundError, ObjectStore
from horaedb_tpu.scanagent import wire
from horaedb_tpu.scanagent.config import ScanAgentConfig
from horaedb_tpu.storage.config import StorageConfig, UpdateMode
from horaedb_tpu.storage.read import ParquetReader, ScanRequest
from horaedb_tpu.storage.types import StorageSchema
from horaedb_tpu.utils import registry, tracing

logger = logging.getLogger(__name__)

_SCANS = registry.counter(
    "scanagent_agent_scans_total",
    "near-data scan requests served by this agent, by outcome")
_PARTIAL_BYTES = registry.counter(
    "scanagent_agent_partial_bytes_total",
    "serialized partial bytes returned by this agent")
_SCAN_SECONDS = registry.histogram(
    "scanagent_agent_scan_seconds",
    "per-segment aggregate scan latency at the agent")

PARTIAL_CONTENT_TYPE = "application/vnd.horaedb.scanagent-partial"


class _AgentTable:
    """One registered table: its schema + a ParquetReader over the
    agent's local store.  The reader keeps its tier-2/scan caches, so
    repeat dashboard scans at the agent are as cache-served as they
    would be at the coordinator — the cache just lives near the data
    now."""

    __slots__ = ("schema", "reader", "segment_duration_ms")

    def __init__(self, schema: StorageSchema, reader: ParquetReader,
                 segment_duration_ms: int):
        self.schema = schema
        self.reader = reader
        self.segment_duration_ms = segment_duration_ms


class AgentService:
    """The near-data scan service for one store shard.

    Construct with the shard's `ObjectStore`, `register_table` each
    served table root (or let coordinators auto-register via
    POST /v1/tables), then `start()` — or mount `build_app()` into an
    existing aiohttp runner."""

    def __init__(self, store: ObjectStore,
                 config: Optional[ScanAgentConfig] = None,
                 storage_config: Optional[StorageConfig] = None,
                 tenants: Optional[TenantRegistry] = None,
                 runtimes=None):
        from horaedb_tpu.common import runtimes as runtimes_mod

        self.store = store
        self.config = config or ScanAgentConfig()
        self.storage_config = storage_config or StorageConfig()
        self.tenants = tenants
        self._own_runtimes = runtimes is None
        self.runtimes = runtimes or runtimes_mod.from_config(
            self.storage_config.threads,
            sst_override=self.storage_config.scan.decode_workers)
        self._tables: dict[str, _AgentTable] = {}
        self._runner: Optional[web.AppRunner] = None
        self.url: Optional[str] = None

    # ---- table registry ---------------------------------------------------

    def register_table(self, root_path: str, user_schema: pa.Schema,
                       num_primary_keys: int,
                       segment_duration_ms: int) -> None:
        root = root_path.rstrip("/")
        if root in self._tables:
            return
        schema = StorageSchema.try_new(user_schema, num_primary_keys,
                                       UpdateMode.OVERWRITE)
        reader = ParquetReader(self.store, root, schema,
                               self.storage_config, segment_duration_ms,
                               runtimes=self.runtimes)
        self._tables[root] = _AgentTable(schema, reader,
                                         segment_duration_ms)
        logger.info("scanagent: registered table %r (segment %dms)",
                    root, segment_duration_ms)

    # ---- lifecycle --------------------------------------------------------

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> str:
        """Serve on `host:port` (port 0 = ephemeral); returns the base
        URL."""
        self._runner = web.AppRunner(self.build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        bound = self._runner.addresses[0][1]
        self.url = f"http://{host}:{bound}"
        return self.url

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        for t in self._tables.values():
            # release tier-2 residency and its process-wide byte gauge
            t.reader.encoded_cache.clear()
        self._tables.clear()
        if self._own_runtimes:
            self.runtimes.close()

    # ---- HTTP surface -----------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=64 << 20)
        app.router.add_get("/", self._hello)
        app.router.add_post("/v1/tables", self._register)
        app.router.add_post("/v1/scan", self._scan)
        return app

    async def _hello(self, _req: web.Request) -> web.Response:
        return web.json_response({"ok": True,
                                  "tables": sorted(self._tables)})

    async def _register(self, req: web.Request) -> web.Response:
        try:
            body = await req.json()
            schema = pa.ipc.read_schema(pa.BufferReader(
                base64.b64decode(body["schema"])))
            self.register_table(body["table"], schema,
                                int(body["num_primary_keys"]),
                                int(body["segment_duration_ms"]))
            return web.json_response({"ok": True})
        except Exception as e:  # noqa: BLE001 — registration surface
            return web.json_response({"error": str(e)}, status=400)

    def _deadline_of(self, req: web.Request) -> Optional[Deadline]:
        raw = req.headers.get("X-Deadline-Ms")
        if raw is None:
            return None
        return Deadline.after(max(0.0, int(raw) / 1000.0),
                              reason="scanagent")

    async def _scan(self, req: web.Request) -> web.Response:
        """One segment's aggregate partials.  Status codes are the
        protocol the coordinator's fallback dispatches on:

          200  Arrow IPC partial payload (wire.encode_parts)
          404  code=unknown_table — register, then retry
          409  code=stale_ssts — the plan's SSTs are not (all) at this
               shard: stale shard map or a racing compaction
          413  code=partial_too_large — partial exceeds
               [scanagent] max_partial_bytes; scanning it here would
               ship MORE than the rows, so the coordinator reads direct
          429  tenant scan-byte quota charged at this agent fired
          504  the propagated deadline expired mid-scan
        """
        incoming = req.headers.get(tracing.TRACE_HEADER)
        trace = None
        if incoming:
            trace = tracing.recorder.start("scanagent/scan",
                                           trace_id=incoming, forced=True)

        def _respond(resp: web.Response, outcome: str) -> web.Response:
            _SCANS.labels(outcome=outcome).inc()
            if trace is not None:
                done = tracing.recorder.finish(
                    trace, status="ok" if resp.status == 200 else "error")
                resp.headers[tracing.TRACE_HEADER] = trace.trace_id
                resp.headers[tracing.EXPORT_HEADER] = \
                    tracing.export_payload(done)
            return resp

        try:
            deadline = self._deadline_of(req)
        except ValueError:
            return _respond(web.json_response(
                {"error": "bad X-Deadline-Ms"}, status=400), "error")
        if deadline is not None and deadline.remaining() <= 0.0:
            return _respond(web.json_response(
                {"error": "deadline exceeded before scan",
                 "code": "deadline"}, status=504), "deadline")
        tenant = None
        if self.tenants is not None:
            try:
                tenant = self.tenants.resolve(req.headers.get("X-Tenant"))
            except Error as e:
                return _respond(web.json_response(
                    {"error": str(e)}, status=400), "error")
        try:
            with tracing.trace_scope(trace), deadline_scope(deadline), \
                    tenant_scope(tenant):
                return _respond(*await self._scan_governed(req, deadline))
        except QuotaExceeded as e:
            # the quota charged AT the agent: the coordinator re-raises
            # this as its own QuotaExceeded so the server's 429 carries
            # the same tenant/resource/Retry-After
            return _respond(web.json_response(
                {"error": str(e), "code": "quota", "quota": e.resource,
                 "tenant": e.tenant,
                 "retry_after_s": e.retry_after_s},
                status=429,
                headers={"Retry-After":
                         str(max(1, math.ceil(e.retry_after_s)))}),
                "quota")
        except (DeadlineExceeded, asyncio.TimeoutError):
            return _respond(web.json_response(
                {"error": "deadline exceeded mid-scan",
                 "code": "deadline"}, status=504), "deadline")
        except NotFoundError as e:
            # an SST named by the plan is not at this shard: stale
            # shard map, or a compaction deleted it mid-scan — the
            # coordinator replans/falls back either way
            return _respond(web.json_response(
                {"error": str(e), "code": "stale_ssts"}, status=409),
                "stale")
        except Error as e:
            return _respond(web.json_response(
                {"error": str(e)}, status=400), "error")
        except Exception as e:  # noqa: BLE001 — service boundary
            logger.exception("scanagent scan failed")
            return _respond(web.json_response(
                {"error": str(e)}, status=500), "error")

    async def _scan_governed(self, req: web.Request,
                             deadline: Optional[Deadline]
                             ) -> tuple[web.Response, str]:
        import time

        t0 = time.perf_counter()
        body = await req.json()
        (table, segment_start, ssts, rng, predicate, spec,
         projections) = wire.decode_scan_request(body)
        entry = self._tables.get(table.rstrip("/"))
        if entry is None:
            return (web.json_response(
                {"error": f"unknown table {table!r}",
                 "code": "unknown_table"}, status=404), "unknown_table")
        scan_req = ScanRequest(range=rng, predicate=predicate,
                               projections=projections)
        plan = entry.reader.build_plan(ssts, scan_req)
        columns = body.get("columns")
        if columns is not None:
            # the coordinator's exact column set: cache keys and decode
            # behavior must match the plan it would have executed
            for seg in plan.segments:
                seg.columns = list(columns)
        parts_out: list = []

        async def run() -> None:
            agg_iter = entry.reader.aggregate_segments(plan, spec)
            try:
                async for seg_start, parts in agg_iter:
                    ensure(seg_start == segment_start,
                           f"scan produced segment {seg_start}, "
                           f"expected {segment_start}")
                    parts_out.extend(parts)
            finally:
                await agg_iter.aclose()

        if deadline is not None:
            # hard backstop around the cooperative checkpoints, like
            # the server's query path
            await asyncio.wait_for(run(), deadline.remaining())
        else:
            await run()
        payload = wire.encode_parts(parts_out)
        if len(payload) > self.config.max_partial_bytes:
            return (web.json_response(
                {"error": f"partial is {len(payload)} bytes "
                          f"(> {self.config.max_partial_bytes})",
                 "code": "partial_too_large", "bytes": len(payload)},
                status=413), "oversized")
        _PARTIAL_BYTES.inc(len(payload))
        _SCAN_SECONDS.observe(time.perf_counter() - t0)
        return (web.Response(body=payload,
                             content_type=PARTIAL_CONTENT_TYPE), "ok")
