"""Scan-agent wire format: the aggregate plan request (JSON) and the
per-segment partial response (Arrow IPC).

A partial is exactly `_flush_window_batch`'s part shape —
`(group_values, bucket_lo, grids)` with `grids` a dict of
(groups, width) numpy arrays — because that is the shape every existing
consumer (sorted-segment-order combine, the PartsMemo, the cluster
downsample merge) already folds.  Serialization must round-trip BOTH
values and dtypes exactly: the coordinator's combine is byte-identity
-tested against the direct scan, so a uint64 group column must not come
back int64 and a float32 grid must not come back float64.

Each part travels as one self-contained Arrow IPC stream (its own
schema: a `__values__` column of length `groups` plus one
FixedSizeList<width> column per grid), framed by a JSON header that
carries the per-part bucket_lo, dtype tags, and grid widths.  Framing:

    HSAP1 | u32 header_len | header JSON | (u32 blob_len | IPC blob)*
"""

from __future__ import annotations

import base64
import json
import struct

import numpy as np
import pyarrow as pa
import pyarrow.ipc

from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.ops import filter as filter_ops
from horaedb_tpu.storage.sst import FileMeta, SstFile
from horaedb_tpu.storage.types import TimeRange

MAGIC = b"HSAP1"

# ---------------------------------------------------------------------------
# predicate tree <-> JSON
# ---------------------------------------------------------------------------

_LEAF_OPS = {"eq": filter_ops.Eq, "ne": filter_ops.Ne,
             "lt": filter_ops.Lt, "le": filter_ops.Le,
             "gt": filter_ops.Gt, "ge": filter_ops.Ge}


def _encode_value(v):
    if isinstance(v, bool):
        return {"t": "bool", "v": bool(v)}
    if isinstance(v, (int, np.integer)):
        return {"t": "i", "v": int(v)}
    if isinstance(v, (float, np.floating)):
        return {"t": "f", "v": float(v)}
    if isinstance(v, str):
        return {"t": "s", "v": v}
    if isinstance(v, (bytes, np.bytes_)):
        return {"t": "b", "v": base64.b64encode(bytes(v)).decode("ascii")}
    raise Error(f"unsupported predicate constant type {type(v).__name__}")


def _decode_value(obj):
    t, v = obj["t"], obj["v"]
    if t == "bool":
        return bool(v)
    if t == "i":
        return int(v)
    if t == "f":
        return float(v)
    if t == "s":
        return v
    if t == "b":
        return base64.b64decode(v)
    raise Error(f"unknown predicate constant tag {t!r}")


def encode_predicate(pred) -> "dict | None":
    if pred is None:
        return None
    if isinstance(pred, (filter_ops.And, filter_ops.Or)):
        op = "and" if isinstance(pred, filter_ops.And) else "or"
        return {"op": op,
                "children": [encode_predicate(c) for c in pred.children]}
    if isinstance(pred, filter_ops.Not):
        return {"op": "not", "child": encode_predicate(pred.child)}
    if isinstance(pred, filter_ops.In):
        vals = pred.values
        if isinstance(vals, np.ndarray):
            # dtype preserved: In-list membership in encoded space keys
            # off exact values, and the canonical predicate key renders
            # each element — the agent must rebuild the same array
            return {"op": "in", "col": pred.column,
                    "nd": vals.dtype.str,
                    "values": [_encode_value(v) for v in vals.tolist()]}
        return {"op": "in", "col": pred.column,
                "values": [_encode_value(v) for v in vals]}
    if isinstance(pred, filter_ops.TimeRangePred):
        return {"op": "range", "col": pred.column,
                "start": int(pred.start), "end": int(pred.end)}
    for name, cls in _LEAF_OPS.items():
        if isinstance(pred, cls):
            return {"op": name, "col": pred.column,
                    "value": _encode_value(pred.value)}
    raise Error(f"unsupported predicate node {type(pred).__name__}")


def decode_predicate(obj):
    if obj is None:
        return None
    op = obj["op"]
    if op in ("and", "or"):
        children = [decode_predicate(c) for c in obj["children"]]
        return (filter_ops.And(children) if op == "and"
                else filter_ops.Or(children))
    if op == "not":
        return filter_ops.Not(decode_predicate(obj["child"]))
    if op == "in":
        values = [_decode_value(v) for v in obj["values"]]
        if "nd" in obj:
            return filter_ops.In(obj["col"],
                                 np.asarray(values, dtype=obj["nd"]))
        return filter_ops.In(obj["col"], values)
    if op == "range":
        return filter_ops.TimeRangePred(obj["col"], int(obj["start"]),
                                        int(obj["end"]))
    cls = _LEAF_OPS.get(op)
    if cls is None:
        raise Error(f"unknown predicate op {op!r}")
    return cls(obj["col"], _decode_value(obj["value"]))


# ---------------------------------------------------------------------------
# scan request <-> JSON
# ---------------------------------------------------------------------------


def encode_scan_request(table: str, segment_start: int,
                        ssts: list, time_range,
                        predicate, spec,
                        projections=None) -> dict:
    """The POST /v1/scan body for ONE segment: the coordinator's view
    of the segment's SST set travels with the request, so the agent
    serves exactly the files the coordinator planned (a stale shard
    map or a racing compaction surfaces as stale_ssts, not as silently
    different data)."""
    return {
        "table": table,
        "segment_start": int(segment_start),
        "ssts": [{"id": int(f.id),
                  "rows": int(f.meta.num_rows),
                  "size": int(f.meta.size),
                  "seq": int(f.meta.max_sequence),
                  "range": [int(f.meta.time_range.start),
                            int(f.meta.time_range.end)]}
                 for f in ssts],
        "range": [int(time_range.start), int(time_range.end)],
        "predicate": encode_predicate(predicate),
        "projections": (None if projections is None
                        else [int(i) for i in projections]),
        "spec": {
            "group_col": spec.group_col, "ts_col": spec.ts_col,
            "value_col": spec.value_col,
            "range_start": int(spec.range_start),
            "bucket_ms": int(spec.bucket_ms),
            "num_buckets": int(spec.num_buckets),
            "which": list(spec.which),
        },
    }


def decode_scan_request(body: dict):
    """-> (table, segment_start, [SstFile], TimeRange, predicate,
    AggregateSpec, projections)."""
    from horaedb_tpu.storage.read import AggregateSpec

    ensure(isinstance(body, dict), "scan request must be a JSON object")
    for key in ("table", "segment_start", "ssts", "range", "spec"):
        ensure(key in body, f"scan request missing {key!r}")
    ssts = [SstFile(int(f["id"]), FileMeta(
        max_sequence=int(f["seq"]), num_rows=int(f["rows"]),
        size=int(f["size"]),
        time_range=TimeRange.new(int(f["range"][0]),
                                 int(f["range"][1]))))
        for f in body["ssts"]]
    rng = TimeRange.new(int(body["range"][0]), int(body["range"][1]))
    s = body["spec"]
    spec = AggregateSpec(
        group_col=s["group_col"], ts_col=s["ts_col"],
        value_col=s["value_col"], range_start=int(s["range_start"]),
        bucket_ms=int(s["bucket_ms"]),
        num_buckets=int(s["num_buckets"]), which=tuple(s["which"]))
    proj = body.get("projections")
    if proj is not None:
        proj = [int(i) for i in proj]
    return (body["table"], int(body["segment_start"]), ssts, rng,
            decode_predicate(body.get("predicate")), spec, proj)


# ---------------------------------------------------------------------------
# parts <-> Arrow IPC
# ---------------------------------------------------------------------------


def _values_to_arrow(values: np.ndarray):
    """(arrow array, dtype tag) for a part's group-values array.  The
    tag drives exact dtype restoration on decode."""
    dt = values.dtype
    if dt.kind in "iuf":
        return pa.array(np.ascontiguousarray(values)), f"np:{dt.str}"
    if dt.kind == "S":
        return (pa.array(values.tolist(), type=pa.binary()),
                f"np:{dt.str}")
    if dt.kind == "U":
        return (pa.array(values.tolist(), type=pa.string()),
                f"np:{dt.str}")
    if dt.kind == "O":
        items = values.tolist()
        if all(isinstance(v, bytes) for v in items):
            return pa.array(items, type=pa.binary()), "obj:bytes"
        if all(isinstance(v, str) for v in items):
            return pa.array(items, type=pa.string()), "obj:str"
        if all(isinstance(v, int) for v in items):
            return pa.array(items, type=pa.int64()), "obj:int"
        raise Error("unsupported mixed-type group values")
    raise Error(f"unsupported group-values dtype {dt!r}")


def _values_from_arrow(col: pa.Array, tag: str) -> np.ndarray:
    if tag.startswith("np:"):
        dt = np.dtype(tag[3:])
        if dt.kind in "iuf":
            return col.to_numpy(zero_copy_only=False).astype(dt,
                                                             copy=False)
        return np.asarray(col.to_pylist(), dtype=dt)
    if tag == "obj:bytes":
        return np.asarray([bytes(v) for v in col.to_pylist()],
                          dtype=object)
    if tag == "obj:str":
        return np.asarray(col.to_pylist(), dtype=object)
    if tag == "obj:int":
        return np.asarray([int(v) for v in col.to_pylist()],
                          dtype=object)
    raise Error(f"unknown group-values tag {tag!r}")


def _part_to_ipc(values: np.ndarray, grids: dict) -> tuple[bytes, dict]:
    """One part's grids as a single-batch IPC stream + its header
    entry.  Grids ride as FixedSizeList<width> columns over `groups`
    rows so the exact (g, w) shape reconstructs without trusting the
    header for anything but dtype."""
    varr, vtag = _values_to_arrow(values)
    g = len(values)
    cols: dict = {"__values__": varr}
    meta: dict = {"values": vtag, "grids": {}}
    for name, grid in grids.items():
        arr = np.ascontiguousarray(grid)
        ensure(arr.ndim == 2 and arr.shape[0] == g,
               f"grid {name!r} shape {arr.shape} does not match "
               f"{g} groups")
        w = int(arr.shape[1])
        ensure(w >= 1, f"grid {name!r} has zero width")
        flat = pa.array(arr.reshape(-1))
        cols[f"g_{name}"] = pa.FixedSizeListArray.from_arrays(flat, w)
        meta["grids"][name] = arr.dtype.str
    batch = pa.record_batch(cols)
    sink = pa.BufferOutputStream()
    with pyarrow.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.getvalue().to_pybytes(), meta


def _part_from_ipc(blob: bytes, meta: dict,
                   lo: int) -> tuple[np.ndarray, int, dict]:
    tbl = pyarrow.ipc.open_stream(blob).read_all().combine_chunks()
    values = _values_from_arrow(tbl.column("__values__").combine_chunks(),
                                meta["values"])
    g = len(values)
    grids = {}
    for name, dt in meta["grids"].items():
        col = tbl.column(f"g_{name}").combine_chunks()
        w = col.type.list_size
        flat = col.values.to_numpy(zero_copy_only=False)
        grids[name] = flat.astype(np.dtype(dt),
                                  copy=False).reshape(g, w)
    return values, int(lo), grids


def encode_parts(parts: list) -> bytes:
    """Serialize one segment's part list (window order preserved —
    the combine folds a segment's parts in exactly this order)."""
    blobs = []
    entries = []
    for values, lo, grids in parts:
        blob, meta = _part_to_ipc(values, grids)
        meta["lo"] = int(lo)
        entries.append(meta)
        blobs.append(blob)
    header = json.dumps({"version": 1, "parts": entries}).encode()
    out = bytearray(MAGIC)
    out += struct.pack("<I", len(header))
    out += header
    for blob in blobs:
        out += struct.pack("<I", len(blob))
        out += blob
    return bytes(out)


def decode_parts(data: bytes) -> list:
    ensure(data[:len(MAGIC)] == MAGIC,
           "malformed partial payload (bad magic)")
    off = len(MAGIC)
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    header = json.loads(data[off:off + hlen].decode())
    ensure(header.get("version") == 1,
           f"unsupported partial payload version "
           f"{header.get('version')!r}")
    off += hlen
    parts = []
    for meta in header["parts"]:
        (blen,) = struct.unpack_from("<I", data, off)
        off += 4
        parts.append(_part_from_ipc(data[off:off + blen], meta,
                                    meta["lo"]))
        off += blen
    ensure(off == len(data), "trailing bytes in partial payload")
    return parts
