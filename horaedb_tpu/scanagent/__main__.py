"""Standalone near-data scan agent: serve aggregate partials for the
SSTs under a local object-store directory.

    python -m horaedb_tpu.scanagent --data-dir /data/shard0 --port 9201

Coordinators auto-register tables over POST /v1/tables, so the agent
needs no schema configuration of its own — point it at the shard's
bytes and add it to the coordinator's [scanagent] map.
"""

from __future__ import annotations

import argparse
import asyncio
import logging


def main() -> None:
    parser = argparse.ArgumentParser(description="near-data scan agent")
    parser.add_argument("--data-dir", required=True,
                        help="local object-store root this agent serves")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9201)
    parser.add_argument("--max-partial-bytes", type=int,
                        default=32 << 20)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run() -> None:
        from horaedb_tpu.objstore import LocalObjectStore
        from horaedb_tpu.scanagent import AgentService, ScanAgentConfig

        service = AgentService(
            LocalObjectStore(args.data_dir),
            config=ScanAgentConfig(
                max_partial_bytes=args.max_partial_bytes))
        url = await service.start(args.host, args.port)
        logging.getLogger(__name__).info("scanagent serving at %s", url)
        try:
            await asyncio.Event().wait()
        finally:
            await service.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
