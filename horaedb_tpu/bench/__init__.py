"""Benchmark suite: TSBS-style data generation + the 5 BASELINE configs
(ref: src/benchmarks is a criterion harness without recorded results;
BASELINE.md defines the workloads we must stand up)."""
