"""The BASELINE benchmark configs (BASELINE.md):

  1. single-table avg GROUP BY time(1m)            -> bench.py (driver default)
  2. TSBS cpu-only, WHERE host=? + range, min/max/avg downsample
  3. TSBS devops-100, 10 fields, tag filter + GROUP BY host, time(5m)
  4. multi-SST merge-scan: top-k hosts by max(cpu) across 64 SSTs
  5. compaction rollup: 1s -> 1h over 30d, all aggregators, write-back
  6. manifest snapshot codec (the reference's own criterion benchmark)
  7. mixed read/write: varied downsample queries under sustained write
     load + compaction churn (vs_baseline here is mixed_p50/quiet_p50 —
     query latency degradation under churn, 1.0 = churn-proof)
  8. durable ingest: acked writes/s + p99 ack, WAL on/off sweep
  9. tiered scan-cache cold ladder (cached/post-flush/hbm-evicted/
     tier2-cold/true-cold/tier2-off)
 10. query-tracing overhead A/B: off vs unsampled vs fully-traced on
     the cached path (vs_baseline = on_p50/off_p50, bar < 1.02)

Each run_configN returns {metric, value (p50 ms), unit, vs_baseline
(device_p50 / cpu_p50, lower is better — except config 7, above)}.
Sizes are scaled by `rows` so the suite runs anywhere; the driver's
headline numbers come from bench.py.

CLI: python -m horaedb_tpu.bench.suite --config 2 [--rows N] [--iters K]
"""

from __future__ import annotations

import argparse
import asyncio
import functools
import json
import sys
import time

import numpy as np


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def provenance() -> dict:
    """Backend identity for result lines — a CPU-fallback number must
    never masquerade as a device number (round-1 lesson).  `fallback`
    is true whenever the run did NOT execute on an accelerator,
    including deliberate CPU runs."""
    import os

    import jax

    platform = jax.devices()[0].platform
    return {"backend": platform,
            "fallback": os.environ.get("_HORAEDB_BENCH_REEXEC") == "1"
            or platform == "cpu"}


def _clear_scan_tiers(table) -> None:
    """TRUE-cold reset for engine legs: drop tier-1 HBM windows AND
    tier-2 host-RAM encoded parts — write-through admission would
    otherwise serve a 'cold' query from RAM and the leg would silently
    measure the tier-2 path instead (config 9 measures the tiers
    explicitly).  The delta-summation parts memo (ISSUE 9) is a third
    serving tier with the same hazard — config 14's refine leg
    measures it on purpose; everywhere else cold means cold."""
    table.reader.scan_cache.clear()
    table.reader.encoded_cache.clear()
    table.reader.parts_memo.clear()


def _p50(fn, iters: int) -> float:
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.percentile(times, 50))


def _pad_pow2(a: np.ndarray, dtype) -> np.ndarray:
    # same capacity rule as the engine's encode path — benches must compile
    # the same program shapes the engine uses
    from horaedb_tpu.ops.encode import pad_capacity

    n = len(a)
    return np.pad(a.astype(dtype), (0, pad_capacity(n) - n))


def _check_i32_span(ts_off: np.ndarray, what: str) -> None:
    from horaedb_tpu.common.error import ensure

    ensure(int(ts_off.max(initial=0)) < 2**31,
           f"{what}: ts offsets exceed int32 — lower --rows (the device "
           "path buckets int32 offsets; larger spans must be segmented)")


def _host_record_batch(names, host_id: np.ndarray, ts: np.ndarray,
                       values: np.ndarray):
    """The engine-leg ingest batch shape shared by configs 3 and 7:
    dictionary-encoded host tag + int64 timestamps + float64 values."""
    import pyarrow as pa

    return pa.record_batch({
        "host": pa.DictionaryArray.from_arrays(
            pa.array(host_id.astype(np.int32)), names),
        "timestamp": pa.array(ts, type=pa.int64()),
        "value": pa.array(values.astype(np.float64)),
    })


# ---------------------------------------------------------------------------
# config 2: single-host filter + min/max/avg downsample
# ---------------------------------------------------------------------------


def run_config2(rows: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp

    from horaedb_tpu.bench.tsbs import TsbsConfig, generate_cpu_arrays
    from horaedb_tpu.ops.downsample import time_bucket_aggregate

    hosts = 100
    interval = 10_000
    cfg = TsbsConfig(num_hosts=hosts, num_fields=3, interval_ms=interval,
                     span_ms=(rows // hosts) * interval)
    cols = generate_cpu_arrays(cfg, shuffle=True)
    n = len(cols["ts"])
    target_host = 42
    # query window: middle half of the span
    q_start = cfg.start_ms + cfg.span_ms // 4
    q_end = q_start + cfg.span_ms // 2
    bucket = 60_000
    num_buckets = -(-(q_end - q_start) // bucket)

    ts_off = cols["ts"] - q_start
    _check_i32_span(ts_off, "config2")
    in_range = (ts_off >= 0) & (ts_off < (q_end - q_start))
    is_host = cols["host_id"] == target_host
    vals = cols["usage_user"].astype(np.float32)

    # WHERE host=? is a PK predicate: the engine pushes it into the
    # Parquet read, so the device only ever sees matching rows.  The
    # timed step models that: host-side selection (the pushdown's role)
    # + device transfer + downsample of the selected rows.  The upload
    # is ONE coalesced put (ts + bitcast f32 values in a (2, cap)
    # array): per-transfer latency, not bytes, dominates small uploads
    # on remote-attached devices.
    @jax.jit  # noqa: bench-local kernel — stays an unprofiled baseline
    def unpack_and_aggregate(packed, k):
        sel_ts = packed[0]
        sel_vals = jax.lax.bitcast_convert_type(packed[1], jnp.float32)
        gid = jnp.zeros_like(sel_ts)
        return time_bucket_aggregate(sel_ts, gid, sel_vals, k, bucket,
                                     num_groups=1, num_buckets=num_buckets)

    def device_run():
        m = is_host & in_range
        sel_ts = ts_off[m].astype(np.int32)
        sel_vals = vals[m]
        k = len(sel_ts)
        packed = np.stack([_pad_pow2(sel_ts, np.int32),
                           _pad_pow2(sel_vals, np.float32).view(np.int32)])
        out = unpack_and_aggregate(jax.device_put(packed), k)
        jax.block_until_ready(out["avg"])
        return out

    out = device_run()  # compile
    dev_p50 = _p50(device_run, iters)

    def cpu_run():
        m = is_host & in_range
        b = ts_off[m] // bucket
        v = vals[m].astype(np.float64)
        sums = np.bincount(b, weights=v, minlength=num_buckets)
        counts = np.bincount(b, minlength=num_buckets)
        mins = np.full(num_buckets, np.inf)
        np.minimum.at(mins, b, v)
        maxs = np.full(num_buckets, -np.inf)
        np.maximum.at(maxs, b, v)
        return sums, counts, mins, maxs

    cpu_p50 = _p50(cpu_run, max(3, iters // 4))

    sums, counts, mins, maxs = cpu_run()
    occ = counts > 0
    np.testing.assert_allclose(np.asarray(out["min"])[0][occ], mins[occ],
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["max"])[0][occ], maxs[occ],
                               rtol=1e-5)
    _log(f"config2: n={n:,} dev={dev_p50*1e3:.2f}ms cpu={cpu_p50*1e3:.2f}ms")
    point = _config2_engine_point(rows)
    return {"metric": f"TSBS cpu-only WHERE host + min/max/avg, {n/1e6:.1f}M rows, p50",
            "value": round(dev_p50 * 1e3, 3), "unit": "ms",
            "vs_baseline": round(dev_p50 / cpu_p50, 4),
            **point}


def _config2_engine_point(rows: int) -> dict:
    """ENGINE leg of config 2: the WHERE host=? point query COLD through
    MetricEngine on a filesystem store — the shape sidecar block pruning
    exists for.  Reports the cold p50 and the fraction of sidecar BYTES
    the scan actually fetched (1.0 = whole objects, i.e. no pruning —
    measured at the store, so a broken pruner cannot fake it)."""
    import asyncio
    import tempfile
    import time as _t

    import pyarrow as pa

    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import LocalObjectStore
    from horaedb_tpu.storage.types import TimeRange

    class MeteredStore(LocalObjectStore):
        """Counts bytes served for .enc objects (get + get_range)."""

        enc_bytes = 0

        async def get(self, path):
            b = await super().get(path)
            if path.endswith(".enc"):
                MeteredStore.enc_bytes += len(b)
            return b

        async def get_range(self, path, start, end):
            b = await super().get_range(path, start, end)
            if path.endswith(".enc"):
                MeteredStore.enc_bytes += len(b)
            return b

    hosts = 100
    n = min(rows, 2_000_000)
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    span = segment_ms  # one big single-segment SST: the pruning shape
    rng = np.random.default_rng(2)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])

    async def go():
        import glob
        import os

        from horaedb_tpu.storage.config import StorageConfig, from_dict

        with tempfile.TemporaryDirectory() as root:
            # tier-2 off: this leg meters how many sidecar BYTES cross
            # the store boundary (block pruning) — the encoded cache
            # would serve them from RAM and zero the metric (config 9
            # measures the cache tiers themselves)
            cfg = from_dict(StorageConfig, {
                "scan": {"cache": {"tier2_max_bytes": 0}}})
            e = await MetricEngine.open("cfg2", MeteredStore(root),
                                        segment_ms=segment_ms, config=cfg)
            try:
                await e.write_arrow("cpu", ["host"], pa.record_batch({
                    "host": pa.DictionaryArray.from_arrays(
                        pa.array(rng.integers(0, hosts, n).astype(np.int32)),
                        names),
                    "timestamp": pa.array(
                        T0 + rng.integers(0, span, n), type=pa.int64()),
                    "value": pa.array(rng.random(n), type=pa.float64()),
                }))
                enc_total = sum(
                    os.path.getsize(p) for p in glob.glob(
                        os.path.join(root, "cfg2", "data", "data",
                                     "*.enc")))

                async def q():
                    return await e.query_downsample(
                        "cpu", [("host", "host_042")],
                        TimeRange.new(T0, T0 + span), bucket_ms=60_000,
                        aggs=("min", "max", "avg"))

                out = await q()  # warm/compile
                assert len(out["tsids"]) == 1
                times = []
                bytes0 = MeteredStore.enc_bytes
                for _ in range(5):
                    e.tables["data"].reader.scan_cache.clear()
                    t0 = _t.perf_counter()
                    out = await q()
                    times.append(_t.perf_counter() - t0)
                fetched = (MeteredStore.enc_bytes - bytes0) / 5
                return (float(np.percentile(times, 50)), fetched,
                        max(1, enc_total))
            finally:
                await e.close()

    p50, fetched, enc_total = asyncio.run(go())
    frac = fetched / enc_total
    _log(f"config2 engine point query: cold p50 {p50 * 1e3:.1f} ms, "
         f"fetched {frac:.2f} of sidecar bytes (block pruning)")
    return {"engine_point_cold_ms": round(p50 * 1e3, 3),
            "engine_point_bytes_fetched_frac": round(frac, 4)}


# ---------------------------------------------------------------------------
# config 3: devops-100, 10 fields, region filter + GROUP BY host, time(5m)
# ---------------------------------------------------------------------------


def run_config3(rows: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp

    from horaedb_tpu.bench.tsbs import REGIONS, TsbsConfig, generate_cpu_arrays

    hosts = 100
    fields = 10
    interval = 10_000
    cfg = TsbsConfig(num_hosts=hosts, num_fields=fields, interval_ms=interval,
                     span_ms=(rows // hosts) * interval)
    cols = generate_cpu_arrays(cfg, shuffle=True)
    n = len(cols["ts"])
    bucket = 300_000  # 5m
    num_buckets = -(-cfg.span_ms // bucket)
    ts_off = (cols["ts"] - cfg.start_ms).astype(np.int64)
    _check_i32_span(ts_off, "config3")
    # region tag filter: hosts are round-robin across 9 regions
    host_region = np.arange(hosts) % len(REGIONS)
    target_region = 0
    host_in_region = host_region[cols["host_id"]] == target_region
    gid = np.where(host_in_region, cols["host_id"], -1).astype(np.int32)
    from horaedb_tpu.bench.tsbs import CPU_FIELDS

    field_mat = np.stack([cols[CPU_FIELDS[f]] for f in range(fields)],
                         axis=1).astype(np.float32)  # (n, 10)

    from horaedb_tpu.ops.encode import pad_capacity

    cap = pad_capacity(n)
    d_ts = jax.device_put(_pad_pow2(ts_off, np.int32))
    d_gid = jax.device_put(_pad_pow2(gid, np.int32))
    d_fields = jax.device_put(
        np.pad(field_mat, ((0, cap - n), (0, 0))))

    num_cells = hosts * num_buckets

    @functools.partial(jax.jit, static_argnames=(  # noqa: bench baseline
        "num_groups", "num_buckets"))
    def multi_field_avg(ts, g, fm, n_valid, bucket_ms, num_groups, num_buckets):
        iota = jnp.arange(ts.shape[0], dtype=jnp.int32)
        valid = iota < n_valid
        b = ts // bucket_ms
        in_grid = valid & (g >= 0) & (b >= 0) & (b < num_buckets)
        seg = jnp.where(in_grid, g * num_buckets + b, num_groups * num_buckets)
        counts = jax.ops.segment_sum(in_grid.astype(jnp.float32), seg,
                                     num_segments=num_groups * num_buckets + 1)
        sums = jax.ops.segment_sum(
            jnp.where(in_grid[:, None], fm, 0.0), seg,
            num_segments=num_groups * num_buckets + 1)
        avg = sums[:-1] / jnp.maximum(counts[:-1, None], 1.0)
        return avg, counts[:-1]

    def device_run():
        avg, counts = multi_field_avg(d_ts, d_gid, d_fields, n, bucket,
                                      num_groups=hosts, num_buckets=num_buckets)
        jax.block_until_ready(avg)
        return avg, counts

    avg, counts = device_run()
    dev_p50 = _p50(device_run, iters)

    def cpu_run():
        m = host_in_region
        cell = cols["host_id"][m].astype(np.int64) * num_buckets + ts_off[m] // bucket
        counts = np.bincount(cell, minlength=num_cells)
        sums = np.stack([
            np.bincount(cell, weights=field_mat[m, f].astype(np.float64),
                        minlength=num_cells)
            for f in range(fields)
        ], axis=1)
        return sums / np.maximum(counts[:, None], 1)

    cpu_p50 = _p50(cpu_run, max(3, iters // 4))
    ref = cpu_run()
    got = np.asarray(avg, dtype=np.float64)
    occ = np.asarray(counts) > 0
    np.testing.assert_allclose(got[occ], ref[occ], rtol=2e-4)
    _log(f"config3: n={n:,}x{fields}f dev={dev_p50*1e3:.2f}ms cpu={cpu_p50*1e3:.2f}ms")
    multi = _config3_engine_multifield(rows, cfg, bucket)
    return {"metric": f"TSBS devops-100 10-field GROUP BY host,time(5m), {n/1e6:.1f}M rows, p50",
            "value": round(dev_p50 * 1e3, 3), "unit": "ms",
            "vs_baseline": round(dev_p50 / cpu_p50, 4),
            **multi}


def _config3_engine_multifield(rows: int, cfg, bucket: int) -> dict:
    """ENGINE leg of config 3: the 10-field devops query through
    MetricEngine.query_downsample_multi, COLD, against the yardstick
    that actually matters — one single-field query over the SAME total
    row count.  Fields partition the data-table rows, so a well-built
    engine pays ~1x that yardstick for all 10 fields, not 10x (the
    redundancy factor reported below; pre-sidecar parquet decode made
    this ~10x)."""
    import asyncio

    import pyarrow as pa

    from horaedb_tpu.bench.tsbs import CPU_FIELDS, TsbsConfig, \
        generate_cpu_arrays
    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage.types import TimeRange

    import time as _t

    fields = cfg.num_fields
    hosts = cfg.num_hosts
    ticks = max(1, rows // hosts // fields)
    ecfg = TsbsConfig(num_hosts=hosts, num_fields=fields,
                      interval_ms=cfg.interval_ms,
                      span_ms=ticks * cfg.interval_ms)
    cols = generate_cpu_arrays(ecfg, shuffle=False)
    n = len(cols["ts"])
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])

    async def go():
        e = await MetricEngine.open("cfg3", MemoryObjectStore(),
                                    segment_ms=2 * 3600 * 1000)
        try:
            for f in range(fields):
                await e.write_arrow(
                    "cpu", ["host"],
                    _host_record_batch(names, cols["host_id"], cols["ts"],
                                       cols[CPU_FIELDS[f]]),
                    field=CPU_FIELDS[f])
            rng_q = TimeRange.new(ecfg.start_ms,
                                  ecfg.start_ms + ecfg.span_ms)
            _clear_scan_tiers(e.tables["data"])
            t0 = _t.perf_counter()
            multi = await e.query_downsample_multi(
                "cpu", [], rng_q, bucket_ms=bucket,
                fields=list(CPU_FIELDS[:fields]), aggs=("avg",))
            multi_s = _t.perf_counter() - t0
            assert all(len(multi[f]["tsids"]) == hosts
                       for f in CPU_FIELDS[:fields])
            return multi_s
        finally:
            await e.close()

    async def go_single():
        # yardstick: ONE field holding the same TOTAL rows (ticks x
        # fields), queried once — the no-redundancy floor
        scfg = TsbsConfig(num_hosts=hosts, num_fields=1,
                          interval_ms=max(1, cfg.interval_ms // fields),
                          span_ms=ticks * cfg.interval_ms)
        scols = generate_cpu_arrays(scfg, shuffle=False)
        e = await MetricEngine.open("cfg3s", MemoryObjectStore(),
                                    segment_ms=2 * 3600 * 1000)
        try:
            await e.write_arrow(
                "cpu", ["host"],
                _host_record_batch(names, scols["host_id"], scols["ts"],
                                   scols[CPU_FIELDS[0]]))
            rng_q = TimeRange.new(scfg.start_ms,
                                  scfg.start_ms + scfg.span_ms)
            _clear_scan_tiers(e.tables["data"])
            t0 = _t.perf_counter()
            out = await e.query_downsample("cpu", [], rng_q,
                                           bucket_ms=bucket, aggs=("avg",))
            single_s = _t.perf_counter() - t0
            assert len(out["tsids"]) == hosts
            return single_s, len(scols["ts"])
        finally:
            await e.close()

    multi_s = asyncio.run(go())
    single_s, single_rows = asyncio.run(go_single())
    redundancy = (multi_s / single_s) if single_s else float("inf")
    _log(f"config3 engine: {fields} fields x {n:,} rows cold in "
         f"{multi_s * 1e3:.1f} ms vs one-field/{single_rows:,}-row "
         f"yardstick {single_s * 1e3:.1f} ms — redundancy factor "
         f"{redundancy:.2f}x (1.0 = no per-field re-read)")
    return {
        "engine_multi_field_cold_ms": round(multi_s * 1e3, 3),
        "engine_single_pass_equiv_ms": round(single_s * 1e3, 3),
        "engine_multi_field_redundancy": round(redundancy, 2),
        "engine_rows": n * fields,
    }


# ---------------------------------------------------------------------------
# config 4: multi-SST merge-scan through the real engine, top-k by max(cpu)
# ---------------------------------------------------------------------------


def run_config4(rows: int, iters: int, num_ssts: int = 64) -> dict:
    import pyarrow as pa

    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.read import ScanRequest
    from horaedb_tpu.storage.storage import CloudObjectStorage, WriteRequest
    from horaedb_tpu.storage.types import TimeRange

    hosts = 100
    rng = np.random.default_rng(0)
    per_sst = max(1, rows // num_ssts)
    span = 3_000_000
    T0 = (1_700_000_000_000 // 3_600_000) * 3_600_000  # segment-aligned
    schema = pa.schema([("host", pa.string()), ("ts", pa.int64()),
                       ("cpu", pa.float64())])

    # keep the exact written rows for the CPU baseline + cross-check
    all_h = np.empty(per_sst * num_ssts, dtype=np.int64)
    all_ts = np.empty(per_sst * num_ssts, dtype=np.int64)
    all_v = np.empty(per_sst * num_ssts, dtype=np.float64)

    async def setup():
        cfg = from_dict(StorageConfig, {"scheduler": {"schedule_interval": "1h"}})
        s = await CloudObjectStorage.open("bench", 3_600_000,
                                         MemoryObjectStore(), schema, 2, cfg)
        names = np.array([f"host_{i}" for i in range(hosts)], dtype=object)
        for i in range(num_ssts):
            h = rng.integers(0, hosts, per_sst)
            ts = T0 + rng.integers(0, span, per_sst)
            v = rng.random(per_sst) * 100
            sl = slice(i * per_sst, (i + 1) * per_sst)
            all_h[sl], all_ts[sl], all_v[sl] = h, ts, v
            batch = pa.record_batch(
                [pa.array(names[h]), pa.array(ts, type=pa.int64()),
                 pa.array(v, type=pa.float64())],
                schema=schema)
            await s.write(WriteRequest(batch, TimeRange.new(T0, T0 + span)))
        return s

    async def query_once(s):
        """Full device pipeline via the composed QueryPlan: scan
        (parquet decode + device merge-dedup) -> downsample grids ->
        TopK stage, merge windows staying device-resident (no Arrow
        round trip).  This is what the metric times."""
        from horaedb_tpu.storage.plan import TopKSpec
        from horaedb_tpu.storage.read import AggregateSpec

        spec = AggregateSpec(group_col="host", ts_col="ts",
                             value_col="cpu", range_start=T0,
                             bucket_ms=span, num_buckets=1,
                             which=("max",))
        qp = await s.plan_query(
            ScanRequest(range=TimeRange.new(T0, T0 + span)), spec=spec,
            top_k=TopKSpec(k=10, by="max"))
        values, grids = await s.execute_plan(qp)
        return values, grids

    async def check_counts(s):
        """Dedup-count cross-check needs the UN-sliced grids: one
        aggregate without the TopK stage, outside the timed loop."""
        from horaedb_tpu.storage.read import AggregateSpec

        spec = AggregateSpec(group_col="host", ts_col="ts",
                             value_col="cpu", range_start=T0,
                             bucket_ms=span, num_buckets=1,
                             which=("max",))
        _values, grids = await s.scan_aggregate(
            ScanRequest(range=TimeRange.new(T0, T0 + span)), spec)
        return int(np.asarray(grids["count"]).sum())

    async def bench():
        s = await setup()
        try:
            top_hosts, _ = await query_once(s)  # warm/compile
            n_out = await check_counts(s)
            times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                top_hosts, _grids = await query_once(s)
                times.append(time.perf_counter() - t0)
            return float(np.percentile(times, 50)), n_out, top_hosts
        finally:
            await s.close()

    dev_p50, n_out, top_hosts = asyncio.run(bench())

    # CPU baseline on THE SAME rows: in-memory lexsort+dedup+top-k.  Note
    # this is conservative in the device's disfavor: the CPU side skips
    # the parquet read the device pipeline pays for.
    def cpu_run():
        order = np.lexsort((all_ts, all_h))
        hs, tss = all_h[order], all_ts[order]
        keep = np.ones(len(hs), dtype=bool)
        keep[1:] = (hs[1:] != hs[:-1]) | (tss[1:] != tss[:-1])
        # last-by-write-order wins: within equal keys keep the LAST original
        # row; lexsort is stable so take the final row of each dup run
        last_keep = np.ones(len(hs), dtype=bool)
        last_keep[:-1] = (hs[:-1] != hs[1:]) | (tss[:-1] != tss[1:])
        vs = all_v[order][last_keep]
        maxs = np.full(hosts, -np.inf)
        np.maximum.at(maxs, hs[last_keep], vs)
        return int(keep.sum()), set(np.argsort(maxs)[-10:].tolist())

    cpu_p50 = _p50(cpu_run, max(2, iters // 4))
    ref_n, ref_top = cpu_run()

    # cross-check: dedup count and top-k set must match numpy on same data
    assert n_out == ref_n, (n_out, ref_n)
    got_hosts = {str(h) for h in top_hosts}
    assert got_hosts == {f"host_{g}" for g in ref_top}, (got_hosts, ref_top)

    _log(f"config4: {num_ssts} SSTs, {len(all_h):,} rows in, {n_out:,} out; "
         f"full-pipeline dev={dev_p50*1e3:.1f}ms cpu-in-mem={cpu_p50*1e3:.1f}ms")
    # NOTE (r5): the timed spec computes which=("max",) — what the
    # top-k needs — where earlier rounds aggregated all six; numbers
    # are not comparable across that boundary
    return {"metric": f"multi-SST merge-scan top-k (max-only agg), {num_ssts} SSTs {len(all_h)/1e6:.1f}M rows, p50",
            "value": round(dev_p50 * 1e3, 3), "unit": "ms",
            "vs_baseline": round(dev_p50 / cpu_p50, 4)}


# ---------------------------------------------------------------------------
# config 5: compaction-path rollup 1s -> 1h over 30d, write-back
# ---------------------------------------------------------------------------


def run_config5(rows: int, iters: int) -> dict:
    import pyarrow as pa

    import jax

    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.ops.downsample import time_bucket_aggregate
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.storage import CloudObjectStorage, WriteRequest
    from horaedb_tpu.storage.types import TimeRange

    # 30d of 1s data, in SECONDS to fit int32 offsets; series count scales
    # with the requested row budget
    span_s = 30 * 24 * 3600
    num_series = max(1, rows // span_s)
    n = num_series * span_s if num_series * span_s <= rows * 2 else rows
    rng = np.random.default_rng(1)
    sid = np.repeat(np.arange(num_series, dtype=np.int32), span_s)[:n]
    ts_s = np.tile(np.arange(span_s, dtype=np.int64), num_series)[:n]
    vals = rng.random(n).astype(np.float32) * 100
    bucket_s = 3600
    num_buckets = span_s // bucket_s

    d_ts = jax.device_put(_pad_pow2(ts_s, np.int32))
    d_sid = jax.device_put(_pad_pow2(sid, np.int32))
    d_vals = jax.device_put(_pad_pow2(vals, np.float32))

    rollup_schema = pa.schema([
        ("series", pa.int64()), ("bucket_ts", pa.int64()),
        ("min", pa.float64()), ("max", pa.float64()), ("sum", pa.float64()),
        ("count", pa.float64()), ("avg", pa.float64()), ("last", pa.float64()),
    ])

    async def open_rollup_store():
        cfg = from_dict(StorageConfig,
                        {"scheduler": {"schedule_interval": "1h"}})
        return await CloudObjectStorage.open(
            "rollup", 10**9, MemoryObjectStore(), rollup_schema, 2, cfg)

    series_col = np.repeat(np.arange(num_series, dtype=np.int64),
                           num_buckets)
    bucket_col = np.tile(
        np.arange(num_buckets, dtype=np.int64) * bucket_s * 1000,
        num_series)

    async def write_back(s, aggs):
        arrays = [pa.array(series_col), pa.array(bucket_col)]
        for key in ("min", "max", "sum", "count", "avg", "last"):
            arrays.append(pa.array(
                np.nan_to_num(np.asarray(aggs[key], dtype=np.float64)
                              ).reshape(-1)))
        batch = pa.record_batch(arrays, schema=rollup_schema)
        await s.write(WriteRequest(
            batch, TimeRange.new(0, span_s * 1000), enable_check=False))
        return batch.num_rows

    def rollup():
        aggs = time_bucket_aggregate(d_ts, d_sid, d_vals, n, bucket_s,
                                     num_groups=num_series,
                                     num_buckets=num_buckets)
        jax.block_until_ready(aggs["avg"])
        return aggs

    # production rollups write into an EXISTING table: the store opens
    # once (one event loop — its background tasks stay loop-affine);
    # each timed iteration is aggregate + grid download + write (the
    # engine dedups the repeated keys last-wins, like re-rollups)
    async def bench():
        s = await open_rollup_store()
        try:
            out = rollup()  # compile
            wrote = await write_back(s, out)  # warm write path
            times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                out = rollup()
                await write_back(s, out)
                times.append(time.perf_counter() - t0)
            return wrote, float(np.percentile(times, 50)), out
        finally:
            await s.close()

    written, dev_p50, aggs = asyncio.run(bench())

    def cpu_run():
        cell = sid.astype(np.int64) * num_buckets + ts_s // bucket_s
        ncells = num_series * num_buckets
        counts = np.bincount(cell, minlength=ncells)
        sums = np.bincount(cell, weights=vals.astype(np.float64),
                           minlength=ncells)
        mins = np.full(ncells, np.inf)
        np.minimum.at(mins, cell, vals)
        maxs = np.full(ncells, -np.inf)
        np.maximum.at(maxs, cell, vals)
        return sums, counts, mins, maxs

    cpu_p50 = _p50(cpu_run, max(2, iters // 4))
    sums, counts, mins, maxs = cpu_run()
    np.testing.assert_allclose(
        np.asarray(aggs["sum"], dtype=np.float64).reshape(-1), sums, rtol=2e-4)
    _log(f"config5: {n:,} rows -> {written:,} rollup rows "
         f"(agg+writeback dev={dev_p50*1e3:.1f}ms, cpu agg-only={cpu_p50*1e3:.1f}ms)")
    return {"metric": f"compaction rollup 1s->1h 30d all aggs + write-back, {n/1e6:.1f}M rows, p50",
            "value": round(dev_p50 * 1e3, 3), "unit": "ms",
            "vs_baseline": round(dev_p50 / cpu_p50, 4)}


# ---------------------------------------------------------------------------
# config 6: manifest snapshot codec — the reference's OWN criterion
# benchmark (src/benchmarks/benches/bench.rs: 1000-record snapshot,
# 100 appends, encode+append+decode per iteration)
# ---------------------------------------------------------------------------


def run_config6(rows: int, iters: int) -> dict:
    import numpy as np

    from horaedb_tpu.native import RECORD_DTYPE
    from horaedb_tpu.storage.manifest.encoding import (
        HEADER_LENGTH,
        RECORD_LENGTH,
        Snapshot,
        SnapshotHeader,
        SnapshotRecord,
    )
    from horaedb_tpu.storage.sst import FileMeta, SstFile
    from horaedb_tpu.storage.types import TimeRange

    record_count = 1000  # the reference's BENCH config values
    append_count = 100
    base = np.zeros(record_count, dtype=RECORD_DTYPE)
    base["id"] = np.arange(record_count, dtype=np.uint64) + 1
    base["start"] = np.arange(record_count, dtype=np.int64) * 1000
    base["end"] = base["start"] + 1000
    base["size"] = 4096
    base["num_rows"] = 8192
    appends = [
        SstFile(record_count + i + 1,
                FileMeta(max_sequence=record_count + i + 1, num_rows=8192,
                         size=4096,
                         time_range=TimeRange.new(i * 1000, i * 1000 + 1000)))
        for i in range(append_count)
    ]

    def one_round() -> int:
        snap = Snapshot(base.copy())
        snap.add_records(appends)
        buf = snap.into_bytes()
        back = Snapshot.from_bytes(buf)
        return len(back)

    assert one_round() == record_count + append_count
    dev_p50 = _p50(one_round, iters)

    # baseline: the SAME encode+append(+dedup)+decode round through the
    # per-record spec-twin classes (the wire format's independent Python
    # statement) — what a non-vectorized host codec costs
    base_records = [
        SnapshotRecord(id=int(i + 1),
                       time_range=TimeRange.new(i * 1000, i * 1000 + 1000),
                       size=4096, num_rows=8192)
        for i in range(record_count)
    ]

    def py_round() -> int:
        by_id = {r.id: r for r in base_records}  # append = replace-by-id
        for f in appends:
            by_id[f.id] = SnapshotRecord(
                id=f.id, time_range=f.meta.time_range, size=f.meta.size,
                num_rows=f.meta.num_rows)
        records = list(by_id.values())
        body = b"".join(r.to_bytes() for r in records)
        buf = SnapshotHeader(length=len(body)).to_bytes() + body
        header = SnapshotHeader.from_bytes(buf)
        count = header.length // RECORD_LENGTH
        back = [SnapshotRecord.from_bytes(buf, HEADER_LENGTH + k * RECORD_LENGTH)
                for k in range(count)]
        return len(back)

    assert py_round() == record_count + append_count
    cpu_p50 = _p50(py_round, max(3, iters // 4))
    _log(f"config6: snapshot {record_count}+{append_count} records "
         f"codec={dev_p50*1e3:.3f}ms per-record-python={cpu_p50*1e3:.3f}ms")
    # pure host work: label it so it can never read as a device number
    return {"metric": ("manifest snapshot encode+append+decode, "
                       f"{record_count}+{append_count} records, p50"),
            "value": round(dev_p50 * 1e3, 3), "unit": "ms",
            "vs_baseline": round(dev_p50 / cpu_p50, 4),
            "backend": "host", "fallback": False}


# ---------------------------------------------------------------------------
# config 7: mixed read/write — sustained write load + compaction churn
# while serving varied-range downsample queries
# ---------------------------------------------------------------------------


def run_config7(rows: int, iters: int) -> dict:
    """Queries under churn: the reference's self-test write generator
    shape (1000-row random batches per interval,
    /root/reference/src/server/src/main.rs:187-233) runs CONCURRENTLY
    with rotating varied-range downsample queries and a 1s-interval
    compaction scheduler.  Reports query p50/p99 quiet vs mixed, cache
    hit rates and compaction count during the mixed phase.
    `vs_baseline` is mixed_p50/quiet_p50 — 1.0 means churn-proof."""
    import asyncio
    import time

    import pyarrow as pa

    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage import compaction as compaction_mod
    from horaedb_tpu.storage import scan_cache as scan_cache_mod
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.read import _REPLAY_HITS
    from horaedb_tpu.storage.types import TimeRange

    from horaedb_tpu.common.error import ensure

    hosts = 100
    interval = 10_000
    bucket = 60_000
    per_host = max(1, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(7)
    n = per_host * hosts
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])

    def batch_of(ts: np.ndarray, host_id: np.ndarray) -> pa.RecordBatch:
        return _host_record_batch(names, host_id, ts,
                                  rng.random(len(ts)) * 100)

    half = (span // 2 // bucket) * bucket
    ensure(half > 0, "config7 needs rows >= ~1200 for a non-empty "
                     "half-span query window")
    _check_i32_span(np.asarray([span]), "config7")
    step = max(bucket, (span - half) // 11 // bucket * bucket)
    starts = [T0 + i * step for i in range(12)
              if T0 + i * step + half <= T0 + span]
    # wall-clock floors scale with iters so smoke tests stay fast while
    # driver runs (iters=20) hold the churn phase open long enough for
    # the 1s compaction scheduler to fire repeatedly
    quiet_floor_s = min(2.0, 0.1 * iters)
    mixed_floor_s = min(5.0, 0.25 * iters)

    async def go():
        cfg = from_dict(StorageConfig, {
            "scheduler": {"schedule_interval": "1s"},
            "scan": {"cache_max_rows": rows * 4},
        })
        e = await MetricEngine.open("cfg7", MemoryObjectStore(),
                                    segment_ms=segment_ms, config=cfg)
        try:
            ts_all = T0 + np.repeat(
                np.arange(per_host, dtype=np.int64) * interval, hosts)
            hid_all = np.tile(np.arange(hosts, dtype=np.int32), per_host)
            chunk = max(1, 1_000_000 // hosts) * hosts
            for lo in range(0, n, chunk):
                hi = min(n, lo + chunk)
                await e.write_arrow("cpu", ["host"],
                                    batch_of(ts_all[lo:hi],
                                             hid_all[lo:hi]))

            async def q_phase(min_queries: int, min_seconds: float):
                lats = []
                t_phase = time.perf_counter()
                i = 0
                while (len(lats) < min_queries
                       or time.perf_counter() - t_phase < min_seconds):
                    s = starts[i % len(starts)]
                    i += 1
                    t0 = time.perf_counter()
                    await e.query_downsample(
                        "cpu", [], TimeRange.new(s, s + half),
                        bucket_ms=bucket, aggs=("avg",))
                    lats.append(time.perf_counter() - t0)
                return lats

            # warm + self-check + quiet phase
            first = await e.query_downsample(
                "cpu", [], TimeRange.new(starts[0], starts[0] + half),
                bucket_ms=bucket, aggs=("avg",))
            ensure(len(first["tsids"]) == hosts,
                   f"config7 self-check: expected {hosts} series, got "
                   f"{len(first['tsids'])}")
            await q_phase(len(starts), 0.0)
            quiet = await q_phase(max(iters, 2 * len(starts)),
                                  quiet_floor_s)

            # mixed phase: writer fires 1000-row batches every 100 ms
            # into a narrow 2-segment window (concentrates SST buildup
            # so the 1s compaction scheduler actually churns), while
            # the same varied queries keep running
            stop = asyncio.Event()
            writes = 0

            async def writer():
                nonlocal writes
                lo_seg = T0 + (span // 2 // segment_ms) * segment_ms
                while not stop.is_set():
                    ts_w = lo_seg + rng.integers(
                        0, min(2 * segment_ms, span), 1000).astype(np.int64)
                    await e.write_arrow(
                        "cpu", ["host"],
                        batch_of(np.sort(ts_w),
                                 rng.integers(0, hosts, 1000)))
                    writes += 1
                    await asyncio.sleep(0.1)

            h0 = scan_cache_mod._HITS.value
            m0 = scan_cache_mod._MISSES.value
            c0 = compaction_mod._COMPACTIONS.value
            r0 = _REPLAY_HITS.value
            w_task = asyncio.create_task(writer())
            try:
                mixed = await q_phase(max(iters, 2 * len(starts)),
                                      mixed_floor_s)
            finally:
                stop.set()
                await w_task
            hits = scan_cache_mod._HITS.value - h0
            misses = scan_cache_mod._MISSES.value - m0
            compactions = compaction_mod._COMPACTIONS.value - c0
            replays = _REPLAY_HITS.value - r0
            return quiet, mixed, writes, hits, misses, compactions, replays
        finally:
            await e.close()

    quiet, mixed, writes, hits, misses, compactions, replays = \
        asyncio.run(go())
    q50, q99 = np.percentile(quiet, [50, 99])
    m50, m99 = np.percentile(mixed, [50, 99])
    hit_rate = hits / max(1, hits + misses)
    _log(f"config7: quiet p50 {q50*1e3:.1f}/p99 {q99*1e3:.1f} ms; "
         f"under churn p50 {m50*1e3:.1f}/p99 {m99*1e3:.1f} ms "
         f"({len(mixed)} queries, {writes} writes, {compactions} "
         f"compactions, scan-cache hit rate {hit_rate:.2f})")
    return {
        "metric": (f"varied downsample p50 under write+compaction churn, "
                   f"{rows / 1e6:.1f}M rows preloaded"),
        "value": round(float(m50) * 1e3, 3), "unit": "ms",
        "vs_baseline": round(float(m50 / q50), 4),
        "quiet_p50_ms": round(float(q50) * 1e3, 3),
        "quiet_p99_ms": round(float(q99) * 1e3, 3),
        "churn_p99_ms": round(float(m99) * 1e3, 3),
        "mixed_queries": len(mixed),
        "writes_1k_batches": writes,
        "compactions": int(compactions),
        "scan_cache_hit_rate": round(hit_rate, 4),
        "replay_hits": int(replays),
    }


# ---------------------------------------------------------------------------
# config 8: durable ingest — WAL group commit vs one-SST-per-write
# ---------------------------------------------------------------------------


def run_config8(rows: int, iters: int) -> dict:
    """Acked-writes/s and p99 ack latency at batch size 1 under 32
    concurrent writers, on a REAL local filesystem (fsyncs included):
    the one-SST-per-write baseline (every ack pays parquet + object put
    + manifest delta) vs the WAL+memtable front end across group-commit
    coalescing windows.  vs_baseline here is wal_rate / baseline_rate —
    HIGHER is better (the ISSUE 3 acceptance floor is 5x).  `iters` is
    unused: each variant is one sustained run (`rows` scales the write
    count)."""
    import shutil
    import tempfile

    import pyarrow as pa

    from horaedb_tpu.common import ReadableDuration
    from horaedb_tpu.objstore import LocalObjectStore
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.storage import CloudObjectStorage, WriteRequest
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.wal import IngestStorage, WalConfig

    del iters
    seg_ms = 3_600_000
    schema = pa.schema([("k", pa.string()), ("ts", pa.int64()),
                        ("v", pa.float64())])
    n_writes = max(64, min(rows // 5000, 2000))
    concurrency = 32

    def storage_cfg():
        c = from_dict(StorageConfig, {
            "scheduler": {"schedule_interval": "1h"}})
        c.manifest.merge_interval = ReadableDuration.parse("1h")
        c.scrub.interval = ReadableDuration.parse("1h")
        return c

    async def drive(s, n):
        lat = []

        async def worker(w):
            for i in range(w, n, concurrency):
                ts = 10 + i
                b = pa.record_batch(
                    [pa.array([f"k{i % 97}"]),
                     pa.array([ts], type=pa.int64()),
                     pa.array([float(i)], type=pa.float64())],
                    schema=schema)
                t0 = time.perf_counter()
                await s.write(WriteRequest(b, TimeRange.new(ts, ts + 1)))
                lat.append(time.perf_counter() - t0)

        t_start = time.perf_counter()
        await asyncio.gather(*[worker(w) for w in range(concurrency)])
        elapsed = time.perf_counter() - t_start
        return n / elapsed, float(np.percentile(lat, 99) * 1e3)

    async def bench():
        out = {}
        tmp = tempfile.mkdtemp(prefix="ingest-bench-base-")
        try:
            s = await CloudObjectStorage.open(
                "db", seg_ms, LocalObjectStore(tmp), schema, 2,
                storage_cfg())
            # the baseline pays a full object-store round trip per ack;
            # a shorter sustained run measures the same steady state
            base_n = min(n_writes, 256)
            base_rate, base_p99 = await drive(s, base_n)
            await s.close()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        _log(f"config8 baseline: {base_rate:.0f} acked writes/s "
             f"(p99 ack {base_p99:.2f} ms, {base_n} writes)")
        out["baseline_writes_per_s"] = round(base_rate, 1)
        out["baseline_p99_ack_ms"] = round(base_p99, 3)

        best = None
        variants = {}
        for wait_ms in (0, 1, 4):
            tmp = tempfile.mkdtemp(prefix="ingest-bench-wal-")
            try:
                inner = await CloudObjectStorage.open(
                    "db", seg_ms,
                    LocalObjectStore(tmp + "/data"), schema, 2,
                    storage_cfg())
                wc = WalConfig(
                    enabled=True, dir=tmp + "/wal",
                    max_group_wait=ReadableDuration.from_millis(wait_ms),
                    flush_rows=1 << 30, flush_bytes=1 << 40,
                    flush_age=ReadableDuration.parse("1h"),
                    flush_interval=ReadableDuration.parse("1h"))
                s = await IngestStorage.open(inner, wc.dir, wc)
                rate, p99 = await drive(s, n_writes)
                # the final flush drains outside the timed region
                await s.close()
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            _log(f"config8 wal group_wait={wait_ms}ms: {rate:.0f} acked "
                 f"writes/s (p99 ack {p99:.2f} ms, {n_writes} writes)")
            variants[f"group_wait_{wait_ms}ms"] = {
                "writes_per_s": round(rate, 1),
                "p99_ack_ms": round(p99, 3)}
            if best is None or rate > best[0]:
                best = (rate, p99, wait_ms)
        out["variants"] = variants
        out["best_group_wait_ms"] = best[2]
        out["p99_ack_ms"] = round(best[1], 3)
        out["writes"] = n_writes
        out["concurrency"] = concurrency
        return out, best[0]

    out, wal_rate = asyncio.run(bench())
    return {
        "metric": (f"durable ingest: acked writes/s at batch size 1, "
                   f"WAL group commit vs one-SST-per-write, "
                   f"{concurrency} writers"),
        "value": round(wal_rate, 1),
        "unit": "writes/s",
        # higher is better for THIS config (throughput multiple)
        "vs_baseline": round(wal_rate / out["baseline_writes_per_s"], 2),
        **out,
    }


# ---------------------------------------------------------------------------
# config 9: tiered scan cache — post-flush / HBM-evicted / true-cold
# ---------------------------------------------------------------------------


def run_config9(rows: int, iters: int) -> dict:
    """The cold-scan tier ladder: ONE downsample workload measured at
    every cache tier of the read path.

      cached      tier-1 hit (HBM-resident post-merge windows)
      post_flush  a WAL flush just changed one segment's SST set —
                  tier-1 misses that segment, tier-2 + write-through
                  admission rebuild it without any object-store read
      tier2_cold  tier-1 fully evicted, tier-2 (host-RAM encoded
                  parts) warm — the restart-adjacent / cache-pressure
                  shape
      true_cold   both tiers cleared — the full object-store read
      true_cold_tier2_off  same, on an engine with [scan.cache]
                  tier2_max_bytes = 0 — proves disabling the tier
                  reproduces the pre-tiering behavior

    The done-bars (ISSUE 4): post_flush within 2x cached, tier2_cold
    >= 5x faster than true_cold, stage profile showing near-zero
    sidecar bytes on the tier2 leg."""
    import os
    import shutil
    import tempfile

    import pyarrow as pa

    from horaedb_tpu.common import ReadableDuration
    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import (
        FaultInjectingStore,
        MemoryObjectStore,
        WrappedObjectStore,
    )
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.read import plan_stage_snapshot
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.wal import WalConfig

    class DataGetCounter(WrappedObjectStore):
        """Counts data-plane reads (.sst/.enc get + get_range) — the
        hard per-leg evidence that a tier served without store IO."""

        def __init__(self, inner):
            super().__init__(inner)
            self.data_gets = 0

        async def _call(self, op: str, *args):
            if op in ("get", "get_range") and str(args[0]).endswith(
                    (".sst", ".enc")):
                self.data_gets += 1
            return await super()._call(op, *args)

    # seeded per-op store latency models a REAL object store (an
    # in-memory GET is a memcpy, which no cache can beat); 25 ms is an
    # S3-class GET time-to-first-byte, 0 disables
    lat_s = float(os.environ.get("BENCH_STORE_LATENCY_MS", "25")) / 1e3

    hosts = 100
    interval = 10_000
    bucket_ms = 60_000
    per_host = max(60, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(9)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])
    _check_i32_span(np.asarray([span]), "config9")
    k_cold = max(3, iters // 3)

    def cfg_of(tier2: bool):
        return from_dict(StorageConfig, {
            "scheduler": {"schedule_interval": "1h"},
            "scan": {"cache_max_rows": n * 4,
                     "cache": {"tier2_max_bytes":
                               (1 << 30) if tier2 else 0}},
        })

    async def ingest(e):
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await e.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))

    async def query(e):
        return await e.query_downsample(
            "cpu", [], TimeRange.new(T0, T0 + span),
            bucket_ms=bucket_ms, aggs=("avg",))

    async def timed(e, reps: int, reset=None, profile: bool = False):
        times, prof = [], {}
        for i in range(reps):
            if reset is not None:
                reset()
            before = plan_stage_snapshot() if profile and i == 0 else None
            t0 = time.perf_counter()
            await query(e)
            times.append(time.perf_counter() - t0)
            if before is not None:
                after = plan_stage_snapshot()
                prof = {kk: round(after[kk] - before[kk], 4)
                        for kk in after if after[kk] != before[kk]}
        return float(np.percentile(times, 50)), prof

    async def go():
        out = {}
        store = DataGetCounter(FaultInjectingStore(
            MemoryObjectStore(), seed=9,
            latency_range=(lat_s, lat_s)))
        out["store_latency_ms"] = lat_s * 1e3
        # ingest once, tier-2 on, no WAL (bulk load path)
        e = await MetricEngine.open("cfg9", store,
                                    segment_ms=segment_ms,
                                    config=cfg_of(True))
        try:
            await ingest(e)
        finally:
            await e.close()

        gets_mark = store.data_gets

        def leg_gets() -> int:
            nonlocal gets_mark
            prev, gets_mark = gets_mark, store.data_gets
            return gets_mark - prev

        wal_dir = tempfile.mkdtemp(prefix="cfg9-wal-")
        try:
            wc = WalConfig(
                enabled=True, dir=wal_dir,
                flush_rows=1 << 30, flush_bytes=1 << 40,
                flush_age=ReadableDuration.parse("1h"),
                flush_interval=ReadableDuration.parse("1h"))
            e = await MetricEngine.open("cfg9", store,
                                        segment_ms=segment_ms,
                                        config=cfg_of(True),
                                        wal_config=wc)
            try:
                table = e.tables["data"]
                await query(e)  # compile + first read (warms both tiers)
                leg_gets()  # flush the warmup's reads from the mark
                cached, _ = await timed(e, iters)
                out["cached_p50_ms"] = round(cached * 1e3, 3)
                out["data_gets_cached"] = leg_gets()

                # HBM evicted, host windows retained: under the default
                # host_perm merge the scan cache's windows live in host
                # RAM while the stacks/replay/memos are the
                # HBM-resident state — drop exactly those and re-derive
                # from the kept windows (no re-read, no re-merge)
                hbm, _ = await timed(e, k_cold,
                                     reset=table.reader.drop_hbm_state)
                out["hbm_evicted_p50_ms"] = round(hbm * 1e3, 3)
                out["data_gets_hbm_evicted"] = leg_gets()

                # post-flush: a tiny write lands in segment 0's range,
                # the WAL flusher drains it to an SST (write-through
                # admission), and the very next query re-merges that
                # segment from tier-2 — no object-store read
                flush_times = []
                for i in range(iters):
                    await e.write_arrow("cpu", ["host"], pa.record_batch({
                        "host": pa.DictionaryArray.from_arrays(
                            pa.array(np.arange(hosts, dtype=np.int32)),
                            names),
                        "timestamp": pa.array(
                            np.full(hosts, T0 + 1 + i, dtype=np.int64),
                            type=pa.int64()),
                        "value": pa.array(np.full(hosts, float(i)),
                                          type=pa.float64()),
                    }))
                    await e.flush()
                    t0 = time.perf_counter()
                    await query(e)
                    flush_times.append(time.perf_counter() - t0)
                post_flush = float(np.percentile(flush_times, 50))
                out["post_flush_p50_ms"] = round(post_flush * 1e3, 3)
                # the headline guarantee: a flush just changed the SST
                # set every iteration, yet the queries read NOTHING
                # from the store (write-through + tier-2 re-merge)
                out["data_gets_post_flush"] = leg_gets()

                tier2, prof2 = await timed(
                    e, k_cold, reset=table.reader.scan_cache.clear,
                    profile=True)
                out["tier2_cold_p50_ms"] = round(tier2 * 1e3, 3)
                out["stage_profile_tier2"] = prof2
                out["data_gets_tier2"] = leg_gets()

                true_cold, prof0 = await timed(
                    e, k_cold,
                    reset=lambda: _clear_scan_tiers(table),
                    profile=True)
                out["true_cold_p50_ms"] = round(true_cold * 1e3, 3)
                out["stage_profile_true_cold"] = prof0
                out["data_gets_true_cold"] = leg_gets()
                out["encoded_cache"] = table.reader.encoded_cache.stats()
            finally:
                await e.close()
        finally:
            shutil.rmtree(wal_dir, ignore_errors=True)

        # the disabled-tier control: [scan.cache] tier2_max_bytes = 0
        # reproduces the pre-tiering cold path on the same data
        e = await MetricEngine.open("cfg9", store,
                                    segment_ms=segment_ms,
                                    config=cfg_of(False))
        try:
            table = e.tables["data"]
            await query(e)  # compile
            off, _ = await timed(e, k_cold,
                                 reset=table.reader.scan_cache.clear)
            out["true_cold_tier2_off_p50_ms"] = round(off * 1e3, 3)
        finally:
            await e.close()
        return out

    out = asyncio.run(go())
    cached = out["cached_p50_ms"]
    post_flush = out["post_flush_p50_ms"]
    hbm = out["hbm_evicted_p50_ms"]
    tier2 = out["tier2_cold_p50_ms"]
    true_cold = out["true_cold_p50_ms"]
    out["post_flush_vs_cached"] = round(post_flush / cached, 3)
    out["hbm_evicted_speedup_vs_true_cold"] = round(true_cold / hbm, 2)
    out["tier2_speedup_vs_true_cold"] = round(true_cold / tier2, 2)
    _log(f"config9: cached {cached:.1f} ms | post-flush {post_flush:.1f}"
         f" ms ({out['post_flush_vs_cached']}x cached) | hbm-evicted "
         f"{hbm:.1f} ms ({out['hbm_evicted_speedup_vs_true_cold']}x "
         f"faster than true-cold) | tier2-cold {tier2:.1f} ms "
         f"({out['tier2_speedup_vs_true_cold']}x) | true-cold "
         f"{true_cold:.1f} ms | tier2-off "
         f"{out['true_cold_tier2_off_p50_ms']:.1f} ms")
    return {
        "metric": (f"tiered scan cache ladder: post-flush query p50, "
                   f"{n / 1e6:.1f}M rows, WAL flush changing one "
                   f"segment's SST set per query"),
        "value": post_flush,
        "unit": "ms",
        # done-bar: post-flush within 2x of cached (lower is better)
        "vs_baseline": out["post_flush_vs_cached"],
        "rows": n,
        **out,
    }


def run_config10(rows: int, iters: int) -> dict:
    """Tracing overhead: ONE cached downsample workload measured with

      off        [trace] enabled = false — the baseline
      unsampled  tracing on, sample_rate = 0 (id minting only — every
                 request pays the sampling draw and header, no spans)
      on         sample_rate = 1.0: full span recording, per-trace
                 stage/cache/objstore attribution, ring insert

    The done-bar (ISSUE 5): `on` throughput within 2% of `off`, so
    production keeps tracing on.  The CACHED path is measured because
    it is the worst case for relative overhead — a cold scan's store
    I/O would hide any instrumentation cost."""
    import pyarrow as pa

    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.utils import tracing

    hosts = 100
    interval = 10_000
    bucket_ms = 60_000
    per_host = max(60, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(10)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])
    _check_i32_span(np.asarray([span]), "config10")

    async def go():
        e = await MetricEngine.open("cfg10", MemoryObjectStore(),
                                    segment_ms=segment_ms)
        try:
            chunk = max(1, 1_000_000 // hosts) * hosts
            for lo in range(0, n, chunk):
                hi = min(n, lo + chunk)
                await e.write_arrow("cpu", ["host"], pa.record_batch({
                    "host": pa.DictionaryArray.from_arrays(
                        pa.array(host_id[lo:hi]), names),
                    "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                    "value": pa.array(vals[lo:hi], type=pa.float64()),
                }))

            async def query():
                return await e.query_downsample(
                    "cpu", [], TimeRange.new(T0, T0 + span),
                    bucket_ms=bucket_ms, aggs=("avg",))

            async def one(enabled: bool, sample_rate: float) -> float:
                """One query exactly as the server middleware drives
                it: recorder.start / trace_scope / finish into the
                ring."""
                tracing.recorder.configure(enabled=enabled,
                                           sample_rate=sample_rate)
                t0 = time.perf_counter()
                trace = tracing.recorder.start("/query")
                if trace is not None:
                    with tracing.trace_scope(trace):
                        await query()
                    tracing.recorder.finish(trace)
                else:
                    await query()
                return time.perf_counter() - t0

            legs = {"off": (False, 1.0), "unsampled": (True, 0.0),
                    "on": (True, 1.0)}
            reps = max(30, iters * 3)
            for _ in range(5):  # warm the scan caches + JIT
                await one(False, 1.0)
            # interleave at the single-query level AND compare via
            # per-rep PAIRED deltas (each rep runs off/unsampled/on
            # back to back): machine drift over the run — thermal,
            # allocator, page cache — moves whole triples together and
            # cancels in the difference, where a leg-vs-leg p50
            # comparison was observed to swing ±6% from drift alone
            acc = {k: [] for k in legs}
            order_rng = np.random.default_rng(0xC10)
            names_ = list(legs)
            for _ in range(reps):
                # randomized within-triple order: a fixed order was
                # observed to bias whichever leg always ran first
                for k in order_rng.permutation(names_):
                    en, sr = legs[k]
                    acc[k].append(await one(en, sr))
            out = {}
            for k, v in acc.items():
                out[f"{k}_p50_ms"] = round(
                    float(np.percentile(v, 50)) * 1e3, 4)
            off = np.asarray(acc["off"])
            for k in ("unsampled", "on"):
                delta = float(np.median(np.asarray(acc[k]) - off))
                out[f"{k}_overhead_us"] = round(delta * 1e6, 1)
                out[f"{k}_overhead_pct"] = round(
                    delta / float(np.median(off)) * 100, 3)
            return out
        finally:
            tracing.recorder.configure(enabled=True, sample_rate=1.0)
            await e.close()

    out = asyncio.run(go())
    _log(f"config10 tracing overhead: {out}")
    return {
        "metric": (f"config 10: traced downsample p50, cached path, "
                   f"{n / 1e6:.1f}M rows (tracing on, sample 1.0)"),
        "value": out["on_p50_ms"],
        "unit": "ms",
        # done-bar: tracing-on within 2% of tracing-off (1.0 = free)
        "vs_baseline": round(out["on_p50_ms"] / out["off_p50_ms"], 4),
        "rows": n,
        **out,
    }


def run_config11(rows: int, iters: int) -> dict:
    """Dashboard-mix workload: standing rollups vs the raw scan path
    (ISSUE 6).  One engine holds `rows` of TSBS-shaped data behind a
    seeded-latency object store; a standing (cpu, value) rollup is
    registered and backfilled, then a dashboard mix — rotating 6h @ 1m
    zoom windows plus full-span @ 1h overviews — is measured twice:

      rollup leg  engine routing through the rollup tiers (steady
                  state; the tier tables' HBM cache is dropped every
                  iteration so the number is not a replay artifact)
      raw leg     the same queries forced down the raw path with the
                  data table's BOTH cache tiers cleared per iteration
                  — the cold-scan cost every dashboard refresh would
                  pay without rollups

    Done-bars: rollup-served mix p50 at least 5x faster than the raw
    cold leg, ZERO object-store data-plane reads on the rollup leg,
    and a bit-identical cross-check of one query per shape."""
    import os

    import pyarrow as pa

    from horaedb_tpu.common import ReadableDuration
    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import (
        FaultInjectingStore,
        MemoryObjectStore,
        WrappedObjectStore,
    )
    from horaedb_tpu.rollup import RollupConfig
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.types import TimeRange

    class DataGetCounter(WrappedObjectStore):
        def __init__(self, inner):
            super().__init__(inner)
            self.data_gets = 0

        async def _call(self, op: str, *args):
            if op in ("get", "get_range") and str(args[0]).endswith(
                    (".sst", ".enc")):
                self.data_gets += 1
            return await super()._call(op, *args)

    lat_s = float(os.environ.get("BENCH_STORE_LATENCY_MS", "25")) / 1e3
    hosts = 100
    interval = 10_000
    per_host = max(2160, rows // hosts)  # >= one 6h zoom window
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    _check_i32_span(np.asarray([span]), "config11")
    rng = np.random.default_rng(11)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])

    zoom_ms = 6 * 3600 * 1000
    hour = 3600 * 1000
    over_span = (span // hour) * hour
    zoom_starts = [T0 + k * ((span - zoom_ms) // 11 // hour * hour)
                   for k in range(12)] if span > zoom_ms else [T0]

    cfg = from_dict(StorageConfig, {
        "scheduler": {"schedule_interval": "1h"},
        "scan": {"cache_max_rows": n * 4,
                 "cache": {"tier2_max_bytes": 2 << 30}},
    })
    rollup_cfg = RollupConfig(enabled=True, tiers=["1m", "1h"],
                              specs=["cpu"],
                              roll_interval=ReadableDuration.parse("1h"))

    async def ingest(e):
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await e.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))

    def mix_queries(e, use_rollup: bool):
        """The dashboard mix as (shape, coroutine-factory) pairs."""
        def zoom(k):
            s = zoom_starts[k % len(zoom_starts)]
            return e.query_downsample(
                "cpu", [], TimeRange.new(s, s + min(zoom_ms, over_span)),
                bucket_ms=60_000, aggs=("avg",), use_rollup=use_rollup)

        def over(_k):
            return e.query_downsample(
                "cpu", [], TimeRange.new(T0, T0 + over_span),
                bucket_ms=hour, aggs=("avg",), use_rollup=use_rollup)

        return [("zoom", zoom), ("overview", over)]

    async def timed_mix(e, use_rollup: bool, reps: int, reset=None):
        times: dict[str, list] = {"zoom": [], "overview": []}
        shapes = mix_queries(e, use_rollup)
        for i in range(reps):
            for shape, q in shapes:
                if reset is not None:
                    reset()
                t0 = time.perf_counter()
                await q(i)
                times[shape].append(time.perf_counter() - t0)
        return times

    async def go():
        out: dict = {"store_latency_ms": lat_s * 1e3}
        store = DataGetCounter(FaultInjectingStore(
            MemoryObjectStore(), seed=11, latency_range=(lat_s, lat_s)))
        e = await MetricEngine.open("cfg11", store, segment_ms=segment_ms,
                                    config=cfg, rollup_config=rollup_cfg)
        try:
            t0 = time.perf_counter()
            await ingest(e)
            out["ingest_s"] = round(time.perf_counter() - t0, 1)
            t0 = time.perf_counter()
            rolled = await e.rollups.roll_now()
            out["backfill_roll_s"] = round(time.perf_counter() - t0, 1)
            out["backfill_segments"] = rolled["cpu:value"]
            st = (await e.rollups.stats())["specs"]["cpu:value"]
            out["lag_seqs_after_roll"] = st["lag_seqs"]
            out["coverage_after_roll"] = st["coverage"]

            # bit-identical cross-check, one query per dashboard shape
            for shape, q in mix_queries(e, True):
                a = await q(0)
                b_fns = dict(mix_queries(e, False))
                b = await b_fns[shape](0)
                assert a["tsids"] == b["tsids"], shape
                for k in b["aggs"]:
                    assert (np.asarray(a["aggs"][k]).tobytes()
                            == np.asarray(b["aggs"][k]).tobytes()), \
                        (shape, k)

            gets_mark = store.data_gets

            def leg_gets() -> int:
                nonlocal gets_mark
                prev, gets_mark = gets_mark, store.data_gets
                return gets_mark - prev

            data_reader = e.tables["data"].reader

            def drop_tier_hbm():
                for t in e.rollups.tiers.values():
                    t.reader.scan_cache.clear()

            def drop_data_tiers():
                data_reader.scan_cache.clear()
                data_reader.encoded_cache.clear()

            # rollup-served leg: tier HBM dropped per query so the
            # number is a real cell read, not a replay artifact
            roll_times = await timed_mix(e, True, max(iters, 10),
                                         reset=drop_tier_hbm)
            out["data_gets_rollup_leg"] = leg_gets()
            # raw cold leg: both data-table cache tiers cleared per
            # query — the no-rollup dashboard-refresh cost
            k_cold = max(3, iters // 3)
            raw_times = await timed_mix(e, False, k_cold,
                                        reset=drop_data_tiers)
            out["data_gets_raw_cold_leg"] = leg_gets()
            served = e.rollups.specs[("cpu", "value")].served_queries
            out["rollup_served_queries"] = served
            for shape in ("zoom", "overview"):
                rt, ct = roll_times[shape], raw_times[shape]
                out[f"rollup_{shape}_p50_ms"] = round(
                    float(np.percentile(rt, 50)) * 1e3, 3)
                out[f"rollup_{shape}_p99_ms"] = round(
                    float(np.percentile(rt, 99)) * 1e3, 3)
                out[f"raw_cold_{shape}_p50_ms"] = round(
                    float(np.percentile(ct, 50)) * 1e3, 3)
                out[f"raw_cold_{shape}_p99_ms"] = round(
                    float(np.percentile(ct, 99)) * 1e3, 3)
                out[f"{shape}_speedup_p50"] = round(
                    np.percentile(ct, 50) / np.percentile(rt, 50), 2)
            mix_roll = roll_times["zoom"] + roll_times["overview"]
            mix_raw = raw_times["zoom"] + raw_times["overview"]
            out["rollup_mix_p50_ms"] = round(
                float(np.percentile(mix_roll, 50)) * 1e3, 3)
            out["rollup_mix_p99_ms"] = round(
                float(np.percentile(mix_roll, 99)) * 1e3, 3)
            out["raw_cold_mix_p50_ms"] = round(
                float(np.percentile(mix_raw, 50)) * 1e3, 3)
            out["raw_cold_mix_p99_ms"] = round(
                float(np.percentile(mix_raw, 99)) * 1e3, 3)
            out["mix_speedup_p50"] = round(
                out["raw_cold_mix_p50_ms"] / out["rollup_mix_p50_ms"], 2)
        finally:
            await e.close()
        return out

    out = asyncio.run(go())
    _log(f"config11: rollup mix p50 {out['rollup_mix_p50_ms']:.1f} ms "
         f"(p99 {out['rollup_mix_p99_ms']:.1f}) vs raw cold "
         f"{out['raw_cold_mix_p50_ms']:.1f} ms "
         f"({out['mix_speedup_p50']}x) | rollup-leg data GETs "
         f"{out['data_gets_rollup_leg']} | backfill "
         f"{out['backfill_segments']} segs in {out['backfill_roll_s']}s")
    return {
        "metric": (f"dashboard mix (6h@1m zooms + full-span@1h "
                   f"overview) served from standing rollups, "
                   f"{n / 1e6:.1f}M rows, p50"),
        "value": out["rollup_mix_p50_ms"],
        "unit": "ms",
        # done-bar: raw cold p50 / rollup p50 >= 5 (higher is better)
        "vs_baseline": out["mix_speedup_p50"],
        "rows": n,
        **out,
    }


def run_config12(rows: int, iters: int) -> dict:
    """Background-plane observability overhead (ISSUE 7): ONE cached
    downsample workload measured with the whole PR-7 plane

      off   watchdog sweeps disabled, meta-ingest paused (its loop
            still wakes and checks the flag — the parked tick is paid
            by BOTH legs, so the paired delta isolates the real work)
      on    watchdog sweeping at 100 ms, meta-ingest scraping the full
            registry + writing through the WAL/memtable path every
            100 ms (flush_age 1 s keeps flushes firing), op traces
            recording for every wal_commit / flush round

    Intervals are 10-100x more aggressive than the production defaults
    (1 s watchdog, 10 s meta) — a deliberate worst case.  Same paired-
    delta methodology as config 10: randomized within-pair order,
    median of per-rep deltas, because leg-vs-leg p50 swings more from
    machine drift than the effect size.  Done-bar: `on` within 2% of
    `off` on the cached query path."""
    import tempfile

    import pyarrow as pa

    from horaedb_tpu.common import ReadableDuration
    from horaedb_tpu.common.loops import loops
    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.metric_engine.meta import MetaConfig, MetaIngest
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.wal.config import WalConfig

    hosts = 100
    interval = 10_000
    bucket_ms = 60_000
    per_host = max(60, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(12)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])
    _check_i32_span(np.asarray([span]), "config12")

    async def go():
        with tempfile.TemporaryDirectory(prefix="cfg12-wal-") as waldir:
            e = await MetricEngine.open(
                "cfg12", MemoryObjectStore(), segment_ms=segment_ms,
                wal_config=WalConfig(
                    enabled=True, dir=waldir,
                    flush_age=ReadableDuration.parse("1s"),
                    flush_interval=ReadableDuration.parse("200ms")))
            meta = MetaIngest(e, MetaConfig(
                enabled=True,
                interval=ReadableDuration.parse("100ms"),
                rollup=False))
            await meta.start()
            try:
                chunk = max(1, 1_000_000 // hosts) * hosts
                for lo in range(0, n, chunk):
                    hi = min(n, lo + chunk)
                    await e.write_arrow("cpu", ["host"], pa.record_batch({
                        "host": pa.DictionaryArray.from_arrays(
                            pa.array(host_id[lo:hi]), names),
                        "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                        "value": pa.array(vals[lo:hi], type=pa.float64()),
                    }))
                await e.flush()

                async def query():
                    return await e.query_downsample(
                        "cpu", [], TimeRange.new(T0, T0 + span),
                        bucket_ms=bucket_ms, aggs=("avg",))

                def set_leg(on: bool) -> None:
                    loops.configure(enabled=on, interval_s=0.1)
                    meta.paused = not on

                async def one(on: bool) -> float:
                    set_leg(on)
                    t0 = time.perf_counter()
                    await query()
                    return time.perf_counter() - t0

                set_leg(False)
                for _ in range(5):  # warm the scan caches + JIT
                    await one(False)
                reps = max(30, iters * 3)
                acc = {"off": [], "on": []}
                order_rng = np.random.default_rng(0xC12)
                for _ in range(reps):
                    # randomized within-pair order (config 10's lesson:
                    # a fixed order biases whichever leg runs first)
                    for k in order_rng.permutation(["off", "on"]):
                        acc[k].append(await one(k == "on"))
                        # let the background plane actually fire between
                        # queries on BOTH legs (same wall-time shape)
                        await asyncio.sleep(0.005)
                out = {}
                for k, v in acc.items():
                    out[f"{k}_p50_ms"] = round(
                        float(np.percentile(v, 50)) * 1e3, 4)
                off = np.asarray(acc["off"])
                delta = float(np.median(np.asarray(acc["on"]) - off))
                out["on_overhead_us"] = round(delta * 1e6, 1)
                out["on_overhead_pct"] = round(
                    delta / float(np.median(off)) * 100, 3)
                # evidence the on-leg plane actually ran
                from horaedb_tpu.utils import recorder, registry
                out["meta_scrapes"] = int(registry.counter(
                    "meta_scrapes_total",
                    "meta-ingest scrape passes written").value)
                out["op_traces_sample"] = sorted(
                    {t["op"] for t in recorder.list(50, kind="op")})
                out["loops_registered"] = len(loops.handles())
                return out
            finally:
                loops.configure(enabled=True, interval_s=1.0)
                meta.paused = False
                await meta.stop()
                await e.close()

    out = asyncio.run(go())
    _log(f"config12 background-plane overhead: {out}")
    return {
        "metric": (f"config 12: cached downsample p50 with watchdog + "
                   f"op tracing + meta-ingest ON, {n / 1e6:.1f}M rows"),
        "value": out["on_p50_ms"],
        "unit": "ms",
        # done-bar: the full background plane within 2% of off
        "vs_baseline": round(out["on_p50_ms"] / out["off_p50_ms"], 4),
        "rows": n,
        **out,
    }


def run_config13(rows: int, iters: int) -> dict:
    """Cold-scan pipeline ladder (ISSUE 8): the config-9 workload and
    25 ms-latency seeded fault store, measured with the pipelined cold
    path against the `[scan.pipeline] enabled = false` control —
    everything else identical.

      cached          tier-1 hit (the denominator for cold_vs_cached)
      tier2_cold      tier-1 evicted, tier-2 encoded parts warm —
                      fetch serves from host RAM, pipeline overlaps
                      decode with device rounds
      true_cold       both tiers cleared: the full-latency object
                      store read, pipelined (fetch depth hides the
                      per-segment round trips)
      true_cold_pipeline_off   the control: same store, same data,
                      pipeline disabled (the pre-change pump)
      tier2_cold_pipeline_off  decode/device control without store IO

    Done-bars: true_cold >= 2.5x faster than the pipeline-off control;
    cold within 3x of cached (or the measured gap + blocking cause
    recorded in ROADMAP item 1).  Data-plane GETs per leg prove which
    tier served."""
    import os

    import pyarrow as pa

    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import (
        FaultInjectingStore,
        MemoryObjectStore,
        WrappedObjectStore,
    )
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.read import plan_stage_snapshot
    from horaedb_tpu.storage.types import TimeRange

    class DataGetCounter(WrappedObjectStore):
        def __init__(self, inner):
            super().__init__(inner)
            self.data_gets = 0

        async def _call(self, op: str, *args):
            if op in ("get", "get_range") and str(args[0]).endswith(
                    (".sst", ".enc")):
                self.data_gets += 1
            return await super()._call(op, *args)

    lat_s = float(os.environ.get("BENCH_STORE_LATENCY_MS", "25")) / 1e3
    hosts = 100
    interval = 10_000
    bucket_ms = 60_000
    per_host = max(60, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(13)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])
    _check_i32_span(np.asarray([span]), "config13")
    k_cold = max(3, iters // 3)

    def cfg_of(pipelined: bool):
        return from_dict(StorageConfig, {
            "scheduler": {"schedule_interval": "1h"},
            "scan": {"cache_max_rows": n * 4,
                     "cache": {"tier2_max_bytes": 1 << 30},
                     "pipeline": {"enabled": pipelined}},
        })

    async def ingest(e):
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await e.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))

    async def query(e):
        return await e.query_downsample(
            "cpu", [], TimeRange.new(T0, T0 + span),
            bucket_ms=bucket_ms, aggs=("avg",))

    async def timed(e, reps: int, reset=None, profile: bool = False):
        times, prof = [], {}
        for i in range(reps):
            if reset is not None:
                reset()
            before = plan_stage_snapshot() if profile and i == 0 else None
            t0 = time.perf_counter()
            await query(e)
            times.append(time.perf_counter() - t0)
            if before is not None:
                after = plan_stage_snapshot()
                prof = {kk: round(after[kk] - before[kk], 4)
                        for kk in after if after[kk] != before[kk]}
        return float(np.percentile(times, 50)), prof

    async def go():
        out = {"store_latency_ms": lat_s * 1e3}
        store = DataGetCounter(FaultInjectingStore(
            MemoryObjectStore(), seed=13,
            latency_range=(lat_s, lat_s)))
        e = await MetricEngine.open("cfg13", store,
                                    segment_ms=segment_ms,
                                    config=cfg_of(True))
        try:
            await ingest(e)
        finally:
            await e.close()

        gets_mark = store.data_gets

        def leg_gets() -> int:
            nonlocal gets_mark
            prev, gets_mark = gets_mark, store.data_gets
            return gets_mark - prev

        for label, pipelined in (("", True), ("_pipeline_off", False)):
            e = await MetricEngine.open("cfg13", store,
                                        segment_ms=segment_ms,
                                        config=cfg_of(pipelined))
            try:
                table = e.tables["data"]
                await query(e)  # compile + warm both tiers
                leg_gets()
                if pipelined:
                    cached, _ = await timed(e, iters)
                    out["cached_p50_ms"] = round(cached * 1e3, 3)
                    out["data_gets_cached"] = leg_gets()
                tier2, prof2 = await timed(
                    e, k_cold, reset=table.reader.scan_cache.clear,
                    profile=pipelined)
                out[f"tier2_cold{label}_p50_ms"] = round(tier2 * 1e3, 3)
                out[f"data_gets_tier2{label}"] = leg_gets()
                if pipelined:
                    out["stage_profile_tier2"] = prof2
                cold, prof0 = await timed(
                    e, k_cold,
                    reset=lambda t=table: _clear_scan_tiers(t),
                    profile=pipelined)
                out[f"true_cold{label}_p50_ms"] = round(cold * 1e3, 3)
                out[f"data_gets_true_cold{label}"] = leg_gets()
                if pipelined:
                    out["stage_profile_true_cold"] = prof0
                    out["pipeline_high_water_mb"] = round(
                        table.reader._pipeline_high_water / 2**20, 1)
            finally:
                await e.close()
        return out

    out = asyncio.run(go())
    cached = out["cached_p50_ms"]
    cold = out["true_cold_p50_ms"]
    off = out["true_cold_pipeline_off_p50_ms"]
    out["pipeline_speedup_true_cold"] = round(off / cold, 2)
    out["pipeline_speedup_tier2"] = round(
        out["tier2_cold_pipeline_off_p50_ms"]
        / out["tier2_cold_p50_ms"], 2)
    out["cold_vs_cached"] = round(cold / cached, 2)
    _log(f"config13: cached {cached:.1f} ms | tier2-cold "
         f"{out['tier2_cold_p50_ms']:.1f} ms "
         f"({out['pipeline_speedup_tier2']}x vs off) | true-cold "
         f"{cold:.1f} ms ({out['pipeline_speedup_true_cold']}x vs "
         f"off {off:.1f} ms) | cold/cached {out['cold_vs_cached']}x")
    return {
        "metric": (f"pipelined cold scan: true-cold downsample p50 over "
                   f"a seeded {out['store_latency_ms']:.0f}ms-latency "
                   f"store, {n / 1e6:.1f}M rows"),
        "value": out["true_cold_p50_ms"],
        "unit": "ms",
        # done-bar: pipelined true-cold >= 2.5x the disabled control
        "vs_baseline": out["pipeline_speedup_true_cold"],
        "rows": n,
        **out,
    }


def run_config14(rows: int, iters: int) -> dict:
    """Output-grid cliff ladder (ISSUE 9): the high-cardinality
    full-span downsample — the shape whose combine/finalize went 4.4x
    superlinear on the r5 scale ladder — measured with the sparse
    combine against the `[scan.combine] mode = "dense"` control, plus
    the two pushdown legs:

      cold_full_span      hosts x buckets grid, every tier + the parts
                          memo cleared per rep; sparse vs dense p50
      topk                query_topk k=5 through the pushdown —
                          materialized output cells must equal
                          k x buckets x aggs (O(k x buckets),
                          independent of host cardinality) while the
                          would-be dense grid is hosts x buckets
      range_refine        full-span query records per-segment partials;
                          narrowed/refined ranges (the dashboard
                          zoom/pan shape) re-serve them — memo-served
                          segment fraction and refine p50 vs a
                          memo-off control

    Done-bars: dense/sparse >= the ISSUE-14 factor at the 200M rung
    (vs_baseline is that ratio), the top-k bound holds exactly, the
    refine leg serves >= 50% of partials from the memo — and every
    leg's grids are bit-identical to the dense control."""
    import os

    import pyarrow as pa

    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage import combine as combine_mod
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.types import TimeRange

    # the r5 scale-ladder shape (hosts x a LONG bucket axis, every
    # window carrying all hosts) — the exact grid that went 4.4x
    # superlinear; hosts = rows/200k matches the ladder's cardinality
    # scaling at each rung
    hosts = int(os.environ.get("BENCH_HOSTS", max(100, rows // 200_000)))
    interval = 10_000
    bucket_ms = 60_000
    # spans are kept bucket-aligned (ticks a multiple of 6) and >= 4
    # segments so the engine takes the ts-leaf-free aligned path on
    # every leg — the dashboard shape the delta memo serves (a
    # ts-bounded predicate is part of the memo key, so unaligned
    # ranges safely never match)
    per_host = -(-max(2880, rows // hosts) // 6) * 6
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(14)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:05d}" for i in range(hosts)])
    _check_i32_span(np.asarray([span]), "config14")
    aggs = ("avg", "max")
    k_cold = max(3, iters // 3)
    num_buckets = -(-span // bucket_ms)

    def cfg():
        return from_dict(StorageConfig, {
            "scheduler": {"schedule_interval": "1h"},
            "scan": {"cache_max_rows": n * 4,
                     "cache": {"tier2_max_bytes": 1 << 30},
                     # hold every segment's partials at the 200M rung
                     # so the refine leg measures the memo, not its
                     # eviction policy
                     "combine": {"memo_max_bytes": 1 << 29}},
        })

    async def ingest(e):
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await e.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))

    def grid_bytes(out: dict) -> bytes:
        return b"".join(np.asarray(out["aggs"][a]).tobytes()
                        for a in sorted(out["aggs"])) + \
            np.asarray(out["tsids"]).tobytes()

    async def go():
        out = {"hosts": hosts, "num_buckets": num_buckets,
               "grid_cells": hosts * num_buckets}
        e = await MetricEngine.open("cfg14", MemoryObjectStore(),
                                    segment_ms=segment_ms, config=cfg())
        try:
            await ingest(e)
            table = e.tables["data"]
            reader = table.reader
            full = TimeRange.new(T0, T0 + span)

            async def full_span():
                return await e.query_downsample(
                    "cpu", [], full, bucket_ms=bucket_ms, aggs=aggs,
                    use_rollup=False)

            def true_cold():
                _clear_scan_tiers(table)
                reader.parts_memo.clear()

            await full_span()  # compile warm-up
            # --- leg 1: cold full-span, sparse vs dense control ---
            # reps interleave modes so allocator/page-cache drift
            # cannot bias one leg (2-core boxes showed ~25% rep
            # variance when the legs ran back-to-back)
            legs, times = {}, {"sparse": [], "dense": []}
            for _ in range(k_cold):
                for mode in ("sparse", "dense"):
                    reader.config.scan.combine.mode = mode
                    true_cold()
                    t0 = time.perf_counter()
                    legs[mode] = await full_span()
                    times[mode].append(time.perf_counter() - t0)
            for mode, ts_ in times.items():
                out[f"cold_full_span_{mode}_p50_ms"] = round(
                    float(np.percentile(ts_, 50)) * 1e3, 3)
            reader.config.scan.combine.mode = "sparse"
            assert grid_bytes(legs["sparse"]) == grid_bytes(
                legs["dense"]), "sparse vs dense grids diverged"
            out["bit_identical_full_span"] = True

            # --- leg 2: top-k pushdown output bound ---
            true_cold()
            k = 5
            m0 = combine_mod._MATERIALIZED.value
            g0 = combine_mod._GRID.value
            t0 = time.perf_counter()
            top = await e.query_topk("cpu", [], full, bucket_ms, k=k,
                                     by="max", aggs=aggs,
                                     use_rollup=False)
            out["topk_p50_ms"] = round((time.perf_counter() - t0) * 1e3,
                                       3)
            out["topk_materialized_cells"] = int(
                combine_mod._MATERIALIZED.value - m0)
            out["topk_grid_cells"] = int(combine_mod._GRID.value - g0)
            want_cells = k * num_buckets * 3  # count, avg, max
            assert out["topk_materialized_cells"] == want_cells, \
                (out["topk_materialized_cells"], want_cells)
            out["topk_bound_ok"] = True
            # bit-identity vs the host-side dense rank
            reader.config.scan.combine.mode = "dense"
            true_cold()
            top_dense = await e.query_topk("cpu", [], full, bucket_ms,
                                           k=k, by="max", aggs=aggs,
                                           use_rollup=False)
            reader.config.scan.combine.mode = "sparse"
            assert top["tsids"] == top_dense["tsids"]
            assert grid_bytes(top) == grid_bytes(top_dense)

            # --- leg 3: range refine (delta-summation memo) ---
            def refine_ranges():
                # zoom/pan refinements: bucket-aligned, >= one segment
                # (the engine's aligned fast path — no ts leaf in the
                # predicate, so the memo key matches the recording)
                qspan = max(segment_ms,
                            (span // 2 // bucket_ms) * bucket_ms)
                for frac in (1 / 4, 1 / 3, 1 / 2, 2 / 5):
                    lo = T0 + (int(span * frac) // bucket_ms) * bucket_ms
                    hi = min(T0 + span, lo + qspan)
                    yield TimeRange.new(lo, hi)

            async def refine_leg(memo_on: bool):
                true_cold()
                await full_span()  # records per-segment partials
                h0 = reader.parts_memo.stats()["hits"]
                mm0 = reader.parts_memo.stats()["misses"]
                times = []
                for r in refine_ranges():
                    # scan tiers cold, memo per the leg (NOT the
                    # _clear_scan_tiers helper, which drops the memo)
                    reader.scan_cache.clear()
                    reader.encoded_cache.clear()
                    if not memo_on:
                        reader.parts_memo.clear()
                    t0 = time.perf_counter()
                    res = await e.query_downsample(
                        "cpu", [], r, bucket_ms=bucket_ms, aggs=aggs,
                        use_rollup=False)
                    times.append(time.perf_counter() - t0)
                st = reader.parts_memo.stats()
                return (float(np.percentile(times, 50)),
                        st["hits"] - h0,
                        (st["hits"] - h0) + (st["misses"] - mm0), res)

            p50_on, hits, probes, last_on = await refine_leg(True)
            p50_off, _h, _p, last_off = await refine_leg(False)
            out["refine_p50_ms"] = round(p50_on * 1e3, 3)
            out["refine_memo_off_p50_ms"] = round(p50_off * 1e3, 3)
            out["refine_memo_hit_segments"] = hits
            out["refine_probe_segments"] = probes
            out["refine_memo_fraction"] = round(hits / max(1, probes), 3)
            assert grid_bytes(last_on) == grid_bytes(last_off), \
                "memo-served refine diverged from recompute"
            out["bit_identical_refine"] = True
        finally:
            await e.close()
        return out

    # the legs measure storage/combine.py (parts-path combine, top-k
    # pushdown, delta memo); on accelerator backends the fused device
    # aggregate would serve every query WITHOUT entering combine — the
    # counters would read 0 and the A/B would time the fused path twice.
    # Force the parts path so the asserts measure what they claim.
    prev_fused = os.environ.get("HORAEDB_FUSED_AGG")
    os.environ["HORAEDB_FUSED_AGG"] = "0"
    try:
        out = asyncio.run(go())
    finally:
        if prev_fused is None:
            os.environ.pop("HORAEDB_FUSED_AGG", None)
        else:
            os.environ["HORAEDB_FUSED_AGG"] = prev_fused
    sparse = out["cold_full_span_sparse_p50_ms"]
    dense = out["cold_full_span_dense_p50_ms"]
    out["combine_speedup_full_span"] = round(dense / sparse, 3)
    out["refine_speedup"] = round(
        out["refine_memo_off_p50_ms"] / out["refine_p50_ms"], 2)
    _log(f"config14: cold full-span sparse {sparse:.1f} ms vs dense "
         f"{dense:.1f} ms ({out['combine_speedup_full_span']}x) | "
         f"top-k materialized {out['topk_materialized_cells']} cells "
         f"vs grid {out['topk_grid_cells']} | refine memo fraction "
         f"{out['refine_memo_fraction']} "
         f"({out['refine_speedup']}x vs memo off)")
    return {
        "metric": (f"sparse combine: cold full-span downsample p50, "
                   f"{out['hosts']} hosts x {out['num_buckets']} "
                   f"buckets, {n / 1e6:.1f}M rows"),
        "value": sparse,
        "unit": "ms",
        # done-bar: dense-control / sparse on the cold full-span leg
        "vs_baseline": out["combine_speedup_full_span"],
        "rows": n,
        **out,
    }


def run_config15(rows: int, iters: int) -> dict:
    """Multi-tenant isolation under overload (ISSUE 10): an OPEN-LOOP
    load harness — arrivals fire on a precomputed Poisson schedule
    regardless of completions, because a closed-loop driver throttles
    itself exactly when the server overloads and hides the damage —
    over a real HTTP server, N simulated tenants mixing writes, cached
    dashboards, and heavy scans:

      dash1/dash2   compliant: steady cached downsample dashboards on
                    a small table
      writer        compliant: steady small write batches (WAL path)
      abuser        floods heavy full-span scans of the big table plus
                    oversized write batches

    Three legs on the SAME engine (caches warm, only policy changes):
      baseline      [tenants] on, no abuser  -> per-tenant p99 floor
      protected     [tenants] on, abuser on  -> weighted-fair admission
                    + WAL rate quota confine the damage
      unprotected   [tenants] off (global FIFO admission — the
                    pre-change behavior), abuser on -> the control

    Done-bar: worst compliant p99 in `protected` < 1.25x its
    `baseline`, while `unprotected` records the collapse the global
    queue produces.  iters scales the per-leg duration."""
    import os
    import random as random_mod
    import tempfile

    import aiohttp
    import pyarrow as pa
    from aiohttp import web

    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import FaultInjectingStore, MemoryObjectStore
    from horaedb_tpu.server.config import (AdmissionConfig, ServerConfig,
                                           ReadableDuration)
    from horaedb_tpu.server.main import ServerState, build_app
    from horaedb_tpu.common.tenant import tenants_from_dict
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.wal.config import WalConfig

    lat_s = float(os.environ.get("BENCH_STORE_LATENCY_MS", "20")) / 1e3
    hosts = 100
    interval = 10_000
    per_host = max(60, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    n = per_host * hosts
    _check_i32_span(np.asarray([span]), "config15")
    leg_seconds = max(4.0, min(30.0, float(iters)))
    seed = int(os.environ.get("TENANT_BENCH_SEED", "15"))

    # the abuser's ad-hoc target: a long-tail historical slice spread
    # thin across many segments, so a cold scan is store-round-trip
    # -bound (the overload shape quotas exist for), not a 2-core CPU
    # burn whose collateral no admission policy could prevent
    seg_ad = 60
    n_ad = min(100_000, max(20_000, rows))
    T_AD0 = T0 - 90 * segment_ms
    span_ad = seg_ad * segment_ms

    heavy_q = {"metric": "adhoc", "filters": {}, "start": T_AD0,
               "end": T_AD0 + span_ad, "bucket_ms": 3_600_000}
    dash_q = {"metric": "app", "filters": {}, "start": T0,
              "end": T0 + min(span, 3_600_000), "bucket_ms": 300_000}

    def small_write(i: int) -> dict:
        # the writer ingests into the OPEN segment ahead of the
        # dashboards' completed window: a dashboard aggregate then
        # never pre-flushes the writer's fresh memtable rows, so its
        # latency is the cached query, not a synchronous SST write —
        # dashboards watching a lagged window is the realistic mix
        return {"samples": [
            {"name": "app_ingest", "labels": {"host": f"w{j:02d}"},
             "timestamp": T0 + 3 * segment_ms + i * 1000 + j,
             "value": float(j)}
            for j in range(50)]}

    def big_write(i: int) -> dict:
        # the flood lands a DAY behind the dashboards' range: a
        # dashboard query's aggregate pre-flush only drains (and only
        # barriers on) memtables overlapping its own range, so the
        # abuser's buffered junk is flushed on the abuser's dime
        return {"samples": [
            {"name": "junk", "labels": {"host": f"x{j:03d}"},
             "timestamp": T0 - 86_400_000 + i * 1000 + j,
             "value": float(j)}
            for j in range(400)]}

    def admission() -> AdmissionConfig:
        return AdmissionConfig(
            max_concurrent_queries=4, max_queued=128,
            queue_timeout=ReadableDuration.parse("6s"),
            query_timeout=ReadableDuration.parse("10s"))

    def tenants_cfg(enabled: bool):
        # the abuser is a low-priority ad-hoc class: one query slot,
        # a short queue, and a WAL rate cap — the operator's policy
        # for tenants with no latency SLO
        return tenants_from_dict({
            "enabled": enabled,
            "tenant": {
                "dash1": {"weight": 4.0},
                "dash2": {"weight": 4.0},
                "writer": {"weight": 2.0},
                "abuser": {"weight": 1.0, "max_in_flight": 1,
                           "max_queued": 3,
                           "max_query_time": "1s",
                           "scan_bytes_per_s": "512kb",
                           "scan_burst_bytes": "2MiB",
                           "wal_bytes_per_s": "256kb",
                           "wal_burst_bytes": "1mb"},
            }})

    def schedule(rng, include_abuser: bool):
        """(at_s, tenant, path, payload) arrivals, time-sorted."""
        events = []

        def poisson(tenant, rate, make):
            t = 0.0
            for i in range(int(leg_seconds * rate)):
                t += rng.expovariate(rate)
                events.append((t, tenant) + make(i))

        for dash in ("dash1", "dash2"):
            poisson(dash, 5.0, lambda i: ("/query", dash_q))
        poisson("writer", 3.0, lambda i: ("/write", small_write(i)))
        if include_abuser:
            # ad-hoc shapes: each scan starts at a different segment so
            # nothing upstream can memoize the flood away
            poisson("abuser", 6.0, lambda i: (
                "/query", dict(heavy_q,
                               start=T_AD0 + (i % 12) * segment_ms)))
            if not os.environ.get("TENANT_BENCH_NO_ABUSE_WRITES"):
                poisson("abuser", 4.0, lambda i: ("/write", big_write(i)))
        events.sort(key=lambda e: e[0])
        return events

    async def run_leg(engine, enabled: bool, include_abuser: bool,
                      rng) -> dict:
        cfg = ServerConfig()
        cfg.admission = admission()
        cfg.tenants = tenants_cfg(enabled)
        state = ServerState(engine, cfg)
        app = build_app(state)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        base = f"http://127.0.0.1:{port}"
        lat: dict = {}
        codes: dict = {}
        # unbounded connector: the default 100-connection pool would
        # queue arrivals CLIENT-side exactly in the collapsing leg —
        # partially re-closing the open loop the Poisson schedule
        # exists to keep open
        session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
            timeout=aiohttp.ClientTimeout(total=30))

        async def fire(tenant, path, payload):
            t0 = time.perf_counter()
            try:
                r = await session.post(  # noqa: session-wide 30s timeout
                    base + path, json=payload,
                    headers={"X-Tenant": tenant})
                status = r.status
                await r.release()
            except asyncio.TimeoutError:
                status = -1
            except aiohttp.ClientError:
                # a collapsing leg can drop keep-alive connections
                # mid-request; that is a data point (failure code),
                # not a reason to abort the whole recorded run
                status = -2
            dt = time.perf_counter() - t0
            lat.setdefault((tenant, path), []).append(dt)
            k = (tenant, path)
            codes.setdefault(k, {})
            codes[k][status] = codes[k].get(status, 0) + 1

        try:
            # unmeasured preamble: one of each request shape, so leg
            # -local compiles / first-touch flushes don't poison the
            # open-loop backlog (an early multi-second stall never
            # drains when arrivals keep their schedule)
            for tenant, path, payload in (
                    ("dash1", "/query", dash_q),
                    ("dash2", "/query", dash_q),
                    ("writer", "/write", small_write(0)),
                    ("abuser", "/write", big_write(0)),
                    ("abuser", "/query", heavy_q)):
                r = await session.post(  # noqa: session-wide timeout
                    base + path, json=payload,
                    headers={"X-Tenant": tenant})
                await r.release()
            lat.clear()
            codes.clear()
            tasks = []
            start = time.perf_counter()
            for at, tenant, path, payload in schedule(
                    rng, include_abuser):
                delay = start + at - time.perf_counter()
                if delay > 0:
                    await asyncio.sleep(delay)
                tasks.append(asyncio.create_task(
                    fire(tenant, path, payload)))
            await asyncio.gather(*tasks)
        finally:
            await session.close()
            await runner.cleanup()
        out = {}
        for (tenant, path), ls in sorted(lat.items()):
            ok = codes[(tenant, path)].get(200, 0)
            kind = "query" if path == "/query" else "write"
            arr = np.asarray(ls) * 1e3
            out[f"{tenant}_{kind}"] = {
                "n": len(ls),
                "p50_ms": round(float(np.percentile(arr, 50)), 1),
                "p99_ms": round(float(np.percentile(arr, 99)), 1),
                "ok": ok,
                "codes": {str(k): v for k, v in sorted(
                    codes[(tenant, path)].items())},
            }
        return out

    async def go():
        store = FaultInjectingStore(MemoryObjectStore(), seed=seed,
                                    latency_range=(lat_s, lat_s))
        wal_dir = tempfile.mkdtemp(prefix="tenant-bench-wal-")
        rng_np = np.random.default_rng(seed)
        # bulk ingest WAL-free (the serving legs exercise the WAL; the
        # fixture load should not), then reopen with the WAL front end
        engine = await MetricEngine.open("cfg15", store,
                                         segment_ms=segment_ms)
        ts = T0 + np.repeat(
            np.arange(per_host, dtype=np.int64) * interval, hosts)
        host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
        vals = (rng_np.random(n) * 100).astype(np.float64)
        names = pa.array([f"host_{i:03d}" for i in range(hosts)])
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await engine.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))
        # ...the small table the dashboards watch
        m = 20 * 360
        await engine.write_arrow("app", ["host"], pa.record_batch({
            "host": pa.array([f"app_{i % 20:02d}" for i in range(m)]),
            "timestamp": pa.array(
                T0 + np.arange(m, dtype=np.int64) * 10_000 % span,
                type=pa.int64()),
            "value": pa.array(rng_np.random(m), type=pa.float64()),
        }))
        # ...and the long-tail historical slice the abuser hammers:
        # n_ad rows spread evenly across seg_ad two-hour segments
        ad_hosts = 50
        ad_per_host = n_ad // ad_hosts
        ad_ts = T_AD0 + np.repeat(
            np.arange(ad_per_host, dtype=np.int64)
            * (span_ad // ad_per_host), ad_hosts)
        ad_ids = np.tile(np.arange(ad_hosts, dtype=np.int32),
                         ad_per_host)
        ad_names = pa.array([f"svc_{i:02d}" for i in range(ad_hosts)])
        await engine.write_arrow("adhoc", ["host"], pa.record_batch({
            "host": pa.DictionaryArray.from_arrays(
                pa.array(ad_ids), ad_names),
            "timestamp": pa.array(ad_ts, type=pa.int64()),
            "value": pa.array(rng_np.random(len(ad_ts)),
                              type=pa.float64()),
        }))
        await engine.close()
        # serving config: the historical slice overwhelms the HBM
        # windows budget, tier-2 and the parts memo are off, and the
        # scan pipeline is off — the abuser's ad-hoc scans pay the
        # seeded store latency segment by segment, every time, while
        # the small dashboard table stays cache-resident
        serving_cfg = from_dict(StorageConfig, {
            "scan": {"cache_max_rows": 20_000,
                     "cache": {"tier2_max_bytes": 0},
                     "combine": {"memo_max_bytes": 0},
                     "pipeline": {"enabled": False}},
        })
        engine = await MetricEngine.open(
            "cfg15", store, segment_ms=segment_ms, config=serving_cfg,
            wal_config=WalConfig(enabled=True, dir=wal_dir))
        try:
            # warm both query shapes (compile + the dashboard cache)
            # so every leg sees the same steady state
            from horaedb_tpu.storage.types import TimeRange

            await engine.query_downsample(
                "adhoc", [], TimeRange.new(T_AD0, T_AD0 + span_ad),
                bucket_ms=3_600_000, aggs=("avg",))
            await engine.query_downsample(
                "app", [], TimeRange.new(T0, T0 + min(span, 3_600_000)),
                bucket_ms=300_000, aggs=("avg",))

            out = {"rows": n, "leg_seconds": leg_seconds,
                   "store_latency_ms": lat_s * 1e3}
            # a FRESH rng per leg: protected and unprotected must
            # replay the IDENTICAL Poisson arrival realization, or the
            # A/B compares different workloads
            _log("config15: leg baseline (tenants on, no abuse)")
            out["baseline"] = await run_leg(
                engine, True, False, random_mod.Random(seed))
            _log("config15: leg protected (tenants on, abuse)")
            out["protected"] = await run_leg(
                engine, True, True, random_mod.Random(seed))
            _log("config15: leg unprotected (tenants off, abuse)")
            out["unprotected"] = await run_leg(
                engine, False, True, random_mod.Random(seed))
            return out
        finally:
            await engine.close()

    out = asyncio.run(go())

    compliant = ("dash1_query", "dash2_query", "writer_write")
    degr = {}
    for leg in ("protected", "unprotected"):
        worst = 0.0
        for k in compliant:
            base = out["baseline"][k]["p99_ms"]
            now = out[leg][k]["p99_ms"]
            if base > 0:
                worst = max(worst, now / base)
        degr[leg] = round(worst, 3)
    out["protected_p99_degradation"] = degr["protected"]
    out["unprotected_p99_degradation"] = degr["unprotected"]
    out["bar_relative_ok"] = degr["protected"] < 1.25
    # the STATED SLO bar — absolute, the form production SLOs take:
    # compliant dashboards answer < 500 ms p99 and compliant writes
    # ack < 1 s p99 WITH the abuser flooding, every request served
    # (no compliant sheds).  The relative (<1.25x) bar is recorded
    # too, but on a 2-core host a p99 ratio against a ~15 ms baseline
    # measures GIL/event-loop sharing and fsync variance more than
    # admission policy — the honest blocking-cause note rides the
    # recorded JSON.
    out["slo_query_p99_ms"] = 500.0
    out["slo_write_p99_ms"] = 1000.0
    out["bar_slo_ok"] = all(
        out["protected"][k]["p99_ms"]
        < (out["slo_write_p99_ms"] if k.endswith("_write")
           else out["slo_query_p99_ms"])
        and out["protected"][k]["codes"].get("200", 0)
        == out["protected"][k]["n"]
        for k in compliant)
    out["slo_unprotected_ok"] = all(
        out["unprotected"][k]["p99_ms"]
        < (out["slo_write_p99_ms"] if k.endswith("_write")
           else out["slo_query_p99_ms"])
        for k in compliant)
    out["control_shows_damage"] = (degr["unprotected"]
                                   > degr["protected"])
    abuser = out["protected"].get("abuser_query", {})
    out["abuser_sheds_protected"] = (abuser.get("codes", {})
                                     .get("429", 0))
    worst_ms = max(out["protected"][k]["p99_ms"] for k in compliant)
    _log(f"config15: compliant SLO under abuse "
         f"{'MET' if out['bar_slo_ok'] else 'MISSED'} (worst p99 "
         f"{worst_ms:.0f} ms) vs unprotected SLO "
         f"{'met' if out['slo_unprotected_ok'] else 'blown'} | "
         f"p99 degradation protected {degr['protected']}x vs "
         f"unprotected {degr['unprotected']}x | abuser 429s "
         f"{out['abuser_sheds_protected']}")
    return {
        "metric": (f"multi-tenant isolation: worst compliant p99 under "
                   f"abuse with weighted-fair admission + quotas, "
                   f"{n / 1e6:.1f}M rows, open-loop"),
        "value": worst_ms,
        "unit": "ms",
        # done-bar context: how much worse the unprotected control
        # degrades compliant tenants than the protected plane does
        "vs_baseline": round(
            degr["unprotected"] / max(degr["protected"], 1e-9), 2),
        **out,
    }


def run_config16(rows: int, iters: int) -> dict:
    """Device-native decode A/B (ISSUE 12): the config-13 cold-scan
    workload and seeded 25 ms-latency fault store, measured with
    `[scan.decode] mode = "device"` against TWO host-decode controls —
    everything else identical:

      host      the CPU-default control (numpy f64 window partials):
                what a CPU deployment actually runs today;
      xla_host  the accelerator-SHAPED control (host decode feeding
                the same XLA window kernel the fused dispatch calls,
                HORAEDB_HOST_AGG=0): kernel cost held equal, so the
                delta isolates WHERE decode/merge/filter ran — the
                comparison that transfers to accelerator backends;
      device    the fused dispatch ([scan.decode] mode="device").

    Legs per control: cached (sanity — decode never touches it),
    tier2_cold (scan cache + parts memo evicted, tier-2 encoded parts
    warm: pure decode cost, zero store I/O), true_cold (all tiers
    cleared, pipelined), plus device-leg pipeline-off twins that
    re-grade the parked config-13 2.5x cold-overlap bar and the r6
    10M-rung pipeline-overhead caveat with host decode off the
    critical path.

    Each pipelined leg diffs plan_stage_snapshot for per-stage seconds
    + STALL counts (PR 8's 137:1 device-starved-on-decode profile is
    the number under attack — note the stall COUNTS saturate at one
    per segment once the consumer has nothing left to compute, so the
    starvation evidence is the device-stage occupancy collapse and
    the per-stage seconds, recorded alongside the raw counts) and
    records encoded-bytes-uploaded (stage device_decode) vs
    host-decoded window bytes.  An in-bench byte-identity assert runs
    device vs host under HORAEDB_HOST_AGG=0 on one cold query (the
    chaos suite's comparability convention).  The device leg's
    fallback-counter deltas are recorded (decode_fallbacks) — a
    silently ineligible leg would otherwise time the host path twice
    and read as a no-op win."""
    import os

    import pyarrow as pa

    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import (
        FaultInjectingStore,
        MemoryObjectStore,
        WrappedObjectStore,
    )
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.read import plan_stage_snapshot
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.utils import registry

    class DataGetCounter(WrappedObjectStore):
        def __init__(self, inner):
            super().__init__(inner)
            self.data_gets = 0

        async def _call(self, op: str, *args):
            if op in ("get", "get_range") and str(args[0]).endswith(
                    (".sst", ".enc")):
                self.data_gets += 1
            return await super()._call(op, *args)

    lat_s = float(os.environ.get("BENCH_STORE_LATENCY_MS", "25")) / 1e3
    hosts = 100
    interval = 10_000
    bucket_ms = 60_000
    per_host = max(60, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(16)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])
    _check_i32_span(np.asarray([span]), "config16")
    k_cold = max(3, iters // 3)

    def cfg_of(mode: str, pipelined: bool = True):
        return from_dict(StorageConfig, {
            "scheduler": {"schedule_interval": "1h"},
            "scan": {"cache_max_rows": n * 4,
                     "cache": {"tier2_max_bytes": 1 << 30},
                     "pipeline": {"enabled": pipelined},
                     "decode": {"mode": mode}},
        })

    async def ingest(e):
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await e.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))

    async def query(e):
        return await e.query_downsample(
            "cpu", [], TimeRange.new(T0, T0 + span),
            bucket_ms=bucket_ms, aggs=("avg",))

    def fallbacks() -> dict:
        fam = registry.family("scan_decode_fallback_total")
        return ({} if fam is None else
                {c._labels[0][1]: int(c.value)
                 for c in fam._snapshot_children()})

    async def timed(e, reps: int, reset=None, profile: bool = False):
        times, prof = [], {}
        for i in range(reps):
            if reset is not None:
                reset()
            before = plan_stage_snapshot() if profile and i == 0 else None
            t0 = time.perf_counter()
            await query(e)
            times.append(time.perf_counter() - t0)
            if before is not None:
                after = plan_stage_snapshot()
                prof = {kk: round(after[kk] - before[kk], 4)
                        for kk in after if after[kk] != before[kk]}
        return float(np.percentile(times, 50)), prof

    def stall_ratio(prof: dict) -> float:
        # device-starved-on-decode: consumer stalls per decode-stage
        # stall (PR 8 measured 137:1 with host decode)
        return round(prof.get("pipeline_stalls_device", 0)
                     / max(1, prof.get("pipeline_stalls_decode", 0)), 2)

    async def go():
        out = {"store_latency_ms": lat_s * 1e3}
        raw = MemoryObjectStore()
        store = DataGetCounter(FaultInjectingStore(
            raw, seed=16, latency_range=(lat_s, lat_s)))
        e = await MetricEngine.open("cfg16", store,
                                    segment_ms=segment_ms,
                                    config=cfg_of("host"))
        try:
            await ingest(e)
        finally:
            await e.close()

        gets_mark = store.data_gets

        def leg_gets() -> int:
            nonlocal gets_mark
            prev, gets_mark = gets_mark, store.data_gets
            return gets_mark - prev

        # byte-identity gate before any timing: one cold query per
        # mode under HORAEDB_HOST_AGG=0 (both paths then share the XLA
        # window kernel; chaos-suite comparability convention)
        os.environ["HORAEDB_HOST_AGG"] = "0"
        try:
            grids = {}
            for mode in ("device", "host"):
                e = await MetricEngine.open("cfg16", store,
                                            segment_ms=segment_ms,
                                            config=cfg_of(mode))
                try:
                    _clear_scan_tiers(e.tables["data"])
                    grids[mode] = await query(e)
                finally:
                    await e.close()
            dv, hv = grids["device"], grids["host"]
            assert np.array_equal(dv["tsids"], hv["tsids"]), \
                "tsid sets differ"
            for kk in dv["aggs"]:
                assert np.asarray(dv["aggs"][kk]).tobytes() == \
                    np.asarray(hv["aggs"][kk]).tobytes(), \
                    f"grid {kk} differs"
            out["bit_identity"] = "byte-equal (HORAEDB_HOST_AGG=0)"
        finally:
            os.environ.pop("HORAEDB_HOST_AGG", None)

        # three legs: the true CPU-default control (numpy f64 window
        # partials — what a CPU deployment actually runs), the
        # accelerator-shaped control (host decode + the same XLA
        # window kernel the fused dispatch calls, HORAEDB_HOST_AGG=0 —
        # isolates WHERE decode ran with kernel cost held equal), and
        # the device-decode leg (kernel-agnostic: it never enters the
        # window-aggregate path)
        for mode, leg, host_agg in (("host", "host", None),
                                    ("host", "xla_host", "0"),
                                    ("device", "device", None)):
            fb0 = fallbacks()
            if host_agg is not None:
                os.environ["HORAEDB_HOST_AGG"] = host_agg
            e = await MetricEngine.open("cfg16", store,
                                        segment_ms=segment_ms,
                                        config=cfg_of(mode))
            try:
                table = e.tables["data"]
                await query(e)  # compile + warm both tiers
                leg_gets()
                cached, _ = await timed(e, iters)
                out[f"{leg}_cached_p50_ms"] = round(cached * 1e3, 3)

                def tier2_reset(t=table):
                    # drop HBM windows AND the parts memo but KEEP the
                    # tier-2 encoded parts: the leg must measure pure
                    # decode (zero store I/O), not the memo tier
                    t.reader.scan_cache.clear()
                    t.reader.parts_memo.clear()

                tier2, prof2 = await timed(e, k_cold, reset=tier2_reset,
                                           profile=True)
                out[f"{leg}_tier2_cold_p50_ms"] = round(tier2 * 1e3, 3)
                out[f"{leg}_stage_profile_tier2"] = prof2
                cold, prof0 = await timed(
                    e, k_cold,
                    reset=lambda t=table: _clear_scan_tiers(t),
                    profile=True)
                out[f"{leg}_true_cold_p50_ms"] = round(cold * 1e3, 3)
                out[f"{leg}_data_gets_true_cold"] = leg_gets()
                out[f"{leg}_stage_profile_true_cold"] = prof0
                out[f"{leg}_stall_ratio_true_cold"] = stall_ratio(prof0)
                # GIL-bound host decode on the critical path: the
                # seconds spent in per-row host work (merge + window
                # planning + group prep inside encode_merge).  THE
                # number the fused dispatch exists to remove — its
                # own stage is pad + upload + XLA, no per-row Python
                out[f"{leg}_host_decode_s_per_cold_query"] = round(
                    prof0.get("encode_merge_s", 0.0), 4)
            finally:
                await e.close()
                if host_agg is not None:
                    os.environ.pop("HORAEDB_HOST_AGG", None)
            if leg == "device":
                fb1 = fallbacks()
                out["decode_fallbacks"] = {
                    k: v - fb0.get(k, 0) for k, v in fb1.items()
                    if v != fb0.get(k, 0)}

        # pipeline-off device legs: re-grade the parked config-13 2.5x
        # cold-overlap bar and the r6 10M-rung pipeline-overhead caveat
        # with host decode off the critical path
        e = await MetricEngine.open("cfg16", store,
                                    segment_ms=segment_ms,
                                    config=cfg_of("device",
                                                  pipelined=False))
        try:
            table = e.tables["data"]
            await query(e)

            def tier2_reset_off(t=table):
                t.reader.scan_cache.clear()
                t.reader.parts_memo.clear()

            tier2_off, _ = await timed(e, k_cold, reset=tier2_reset_off)
            out["device_tier2_cold_pipeline_off_p50_ms"] = round(
                tier2_off * 1e3, 3)
            cold_off, _ = await timed(
                e, k_cold, reset=lambda t=table: _clear_scan_tiers(t))
            out["device_true_cold_pipeline_off_p50_ms"] = round(
                cold_off * 1e3, 3)
        finally:
            await e.close()

        # zero-latency-store legs (same objects, the raw memory store
        # underneath the fault wrapper): the r6 10M-rung caveat was
        # [scan.pipeline] overhead measured with NOTHING to hide —
        # re-grade it with host decode on vs off the critical path
        for mode in ("host", "device"):
            for pipelined in (True, False):
                e = await MetricEngine.open(
                    "cfg16", raw, segment_ms=segment_ms,
                    config=cfg_of(mode, pipelined=pipelined))
                try:
                    table = e.tables["data"]
                    await query(e)
                    cold0, _ = await timed(
                        e, k_cold,
                        reset=lambda t=table: _clear_scan_tiers(t))
                    key = (f"{mode}_true_cold_zero_latency"
                           f"{'' if pipelined else '_pipeline_off'}"
                           "_p50_ms")
                    out[key] = round(cold0 * 1e3, 3)
                finally:
                    await e.close()
        return out

    out = asyncio.run(go())
    dev_cold = out["device_true_cold_p50_ms"]
    host_cold = out["host_true_cold_p50_ms"]
    xla_cold = out["xla_host_true_cold_p50_ms"]
    out["decode_speedup_true_cold_vs_cpu_default"] = round(
        host_cold / dev_cold, 2)
    out["decode_speedup_true_cold_vs_xla_control"] = round(
        xla_cold / dev_cold, 2)
    out["decode_speedup_tier2_vs_xla_control"] = round(
        out["xla_host_tier2_cold_p50_ms"]
        / out["device_tier2_cold_p50_ms"], 2)
    out["regrade_pipeline_speedup_device"] = round(
        out["device_true_cold_pipeline_off_p50_ms"] / dev_cold, 2)
    out["regrade_tier2_pipeline_overhead_device"] = round(
        out["device_tier2_cold_p50_ms"]
        / out["device_tier2_cold_pipeline_off_p50_ms"], 2)
    # the r6 10M-rung caveat re-grade: pipeline overhead over a
    # zero-latency store (>1.0 = the pipeline costs wall with nothing
    # to hide), host decode vs device decode on the critical path
    out["regrade_r6_zero_latency_pipeline_overhead_host"] = round(
        out["host_true_cold_zero_latency_p50_ms"]
        / out["host_true_cold_zero_latency_pipeline_off_p50_ms"], 2)
    out["regrade_r6_zero_latency_pipeline_overhead_device"] = round(
        out["device_true_cold_zero_latency_p50_ms"]
        / out["device_true_cold_zero_latency_pipeline_off_p50_ms"], 2)
    prof_d = out["device_stage_profile_true_cold"]
    prof_h = out["host_stage_profile_true_cold"]
    out["encoded_bytes_uploaded_per_cold_query"] = int(
        prof_d.get("device_decode_bytes", 0))
    out["host_decoded_window_bytes_per_cold_query"] = int(
        prof_h.get("pipeline_decode_bytes", 0))
    out["host_decode_removed"] = (
        f"{out['host_host_decode_s_per_cold_query']}s GIL-bound "
        f"encode/merge per cold query on the host legs -> "
        f"{out['device_host_decode_s_per_cold_query']}s on the device "
        f"leg (pad+upload only)")
    _log(f"config16: true-cold device {dev_cold:.1f} ms vs cpu-default "
         f"host {host_cold:.1f} ms "
         f"({out['decode_speedup_true_cold_vs_cpu_default']}x) vs "
         f"xla-control {xla_cold:.1f} ms "
         f"({out['decode_speedup_true_cold_vs_xla_control']}x) | "
         f"stall ratio device {out['device_stall_ratio_true_cold']} vs "
         f"host {out['host_stall_ratio_true_cold']} vs xla "
         f"{out['xla_host_stall_ratio_true_cold']} | pipeline re-grade "
         f"{out['regrade_pipeline_speedup_device']}x")
    return {
        "metric": (f"device-native decode: true-cold downsample p50 "
                   f"over a seeded {out['store_latency_ms']:.0f}ms"
                   f"-latency store, {n / 1e6:.1f}M rows, device vs "
                   f"host decode"),
        "value": out["device_true_cold_p50_ms"],
        "unit": "ms",
        # done-bar: decode-starvation reduced vs the accelerator-shaped
        # control (the CPU-default control's numpy twin is faster than
        # XLA-CPU kernels — the documented backend trade; see notes)
        "vs_baseline": out["decode_speedup_true_cold_vs_xla_control"],
        "rows": n,
        **out,
    }


def run_config17(rows: int, iters: int) -> dict:
    """Near-data scan agents (ISSUE 13): the cold dashboard mix over a
    seeded 25 ms-latency object store, agent-served partials vs the
    direct scan.

    Legs:
      off          no router — every covered segment's parquet/sidecar
                   bytes ship to the coordinator (the control)
      agent        [scanagent] routes every segment to an agent
                   colocated with the store (raw inner store: near the
                   data there is no WAN hop) — the coordinator's
                   data-plane bytes become O(groups x buckets x aggs)
                   partials
      agent_killed the agent dies mid-run — queries complete through
                   the per-segment fallback (direct reads), accounted
      disk         a LocalObjectStore-backed rung: the coordinator
                   issues ZERO segment reads on the agent path (no
                   segment is ever resident there), and the dead-agent
                   fallback STREAMS whole SSTs chunk-wise
                   (get_stream -> file-backed mmap) instead of
                   buffering them in RSS

    Done-bar: coordinator data-plane bytes (store bytes + received
    partial bytes) reduced >= 5x on the agent leg, grids byte-identical
    with the off leg (asserted in-bench)."""
    import os
    import shutil
    import tempfile

    import pyarrow as pa

    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import (
        FaultInjectingStore,
        LocalObjectStore,
        MemoryObjectStore,
        WrappedObjectStore,
    )
    from horaedb_tpu.scanagent import (
        AgentService,
        AgentSpec,
        ScanAgentConfig,
    )
    from horaedb_tpu.scanagent import client as sa_client
    from horaedb_tpu.storage import parquet_io
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.types import TimeRange

    class DataByteCounter(WrappedObjectStore):
        """Coordinator-side data-plane accounting: bytes and ops of
        the DATA table's .sst/.enc reads (index/series/metrics lookups
        are identical across legs and not segment shipping), buffered
        AND streamed.  Hides local_path so the disk rung's fallback
        reads go through the countable get/get_stream surface."""

        def __init__(self, inner, prefix: str):
            super().__init__(inner)
            self.prefix = prefix
            self.data_bytes = 0
            self.data_gets = 0
            self.stream_ops = 0

        def _is_data(self, path) -> bool:
            p = str(path)
            return p.startswith(self.prefix) \
                and p.endswith((".sst", ".enc"))

        async def _call(self, op: str, *args):
            out = await super()._call(op, *args)
            if op in ("get", "get_range") and self._is_data(args[0]):
                self.data_gets += 1
                self.data_bytes += len(out)
            return out

        async def _stream(self, op: str, path: str, chunk_size: int):
            counted = self._is_data(path)
            if counted:
                self.data_gets += 1
                self.stream_ops += 1
            async for chunk in self.inner.get_stream(path, chunk_size):
                if counted:
                    self.data_bytes += len(chunk)
                yield chunk

    lat_s = float(os.environ.get("BENCH_STORE_LATENCY_MS", "25")) / 1e3
    hosts = 100
    interval = 10_000
    per_host = max(60, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(17)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])
    _check_i32_span(np.asarray([span]), "config17")
    reps = max(2, iters // 3)

    cfg = from_dict(StorageConfig, {
        "scheduler": {"schedule_interval": "1h"},
        "scan": {"cache_max_rows": n * 4},
    })

    async def ingest(e):
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await e.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))

    zoom_ms = min(span, 6 * 3600 * 1000)

    async def mix(e, rep: int) -> list:
        """The cold dashboard mix: one full-span 1h overview + two
        rotating zooms at 1m resolution.  Returns the grids for the
        bit-identity cross-check."""
        out = [await e.query_downsample(
            "cpu", [], TimeRange.new(T0, T0 + span),
            bucket_ms=3_600_000, aggs=("avg",))]
        for z in range(2):
            lo = T0 + ((rep * 2 + z) * zoom_ms) % max(1, span - zoom_ms + 1)
            out.append(await e.query_downsample(
                "cpu", [], TimeRange.new(lo, lo + zoom_ms),
                bucket_ms=60_000, aggs=("avg", "max")))
        return out

    def grids_bytes(results: list) -> bytes:
        buf = bytearray()
        for r in results:
            buf += np.asarray(r["tsids"], dtype=np.uint64).tobytes()
            for k in sorted(r["aggs"]):
                buf += np.asarray(r["aggs"][k]).tobytes()
        return bytes(buf)

    async def timed_mix(e, counter, reset, label: str) -> dict:
        times = []
        partials0 = sa_client._PARTIAL_BYTES.value
        bytes0, gets0 = counter.data_bytes, counter.data_gets
        grids = None
        for rep in range(reps):
            reset()
            t0 = time.perf_counter()
            got = await mix(e, rep)
            times.append(time.perf_counter() - t0)
            if grids is None:
                grids = grids_bytes(got)
        leg = {
            "p50_ms": round(float(np.percentile(times, 50)) * 1e3, 3),
            "store_data_bytes": counter.data_bytes - bytes0,
            "store_data_gets": counter.data_gets - gets0,
            "partial_bytes":
                int(sa_client._PARTIAL_BYTES.value - partials0),
        }
        leg["coordinator_bytes"] = (leg["store_data_bytes"]
                                    + leg["partial_bytes"])
        _log(f"config17 {label}: p50 {leg['p50_ms']}ms, "
             f"store {leg['store_data_bytes']}B "
             f"({leg['store_data_gets']} gets) + partials "
             f"{leg['partial_bytes']}B")
        return {"leg": leg, "grids": grids}

    async def go():
        out: dict = {"store_latency_ms": lat_s * 1e3, "rows": n,
                     "reps_per_leg": reps}
        inner = MemoryObjectStore()
        coord_store = DataByteCounter(FaultInjectingStore(
            inner, seed=17, latency_range=(lat_s, lat_s)),
            prefix="cfg17/data/")
        # ingest once (direct engine, no router)
        e = await MetricEngine.open("cfg17", coord_store,
                                    segment_ms=segment_ms, config=cfg)
        try:
            await ingest(e)
            data = e.tables["data"]
            # ---- off: the direct-scan control ------------------------
            off = await timed_mix(
                e, coord_store, lambda: _clear_scan_tiers(data), "off")
            out["off"] = off["leg"]
        finally:
            await e.close()

        # ---- agent: near-data routing via [scanagent] ----------------
        agent = AgentService(inner)  # colocated: raw store, no WAN hop
        url = await agent.start()
        sa_cfg = ScanAgentConfig(
            mode="on", num_slots=1,
            agents=(AgentSpec("shard0", url, (0,)),))
        e = await MetricEngine.open("cfg17", coord_store,
                                    segment_ms=segment_ms, config=cfg,
                                    scanagent_config=sa_cfg)
        try:
            data = e.tables["data"]
            served = await timed_mix(
                e, coord_store, lambda: _clear_scan_tiers(data),
                "agent")
            out["agent"] = served["leg"]
            assert served["grids"] == off["grids"], \
                "agent-served grids differ from the direct scan"
            out["bit_identical"] = True
            reduction = (off["leg"]["coordinator_bytes"]
                         / max(1, served["leg"]["coordinator_bytes"]))
            out["bytes_reduction_x"] = round(reduction, 2)
            out["bar_bytes_reduction"] = ">=5x"
            out["bar_bytes_reduction_met"] = bool(reduction >= 5.0)

            # ---- agent_killed: fallback correctness + accounting -----
            fb0 = sa_client._FALLBACKS.total
            await agent.close()
            killed = await timed_mix(
                e, coord_store, lambda: _clear_scan_tiers(data),
                "agent_killed")
            out["agent_killed"] = killed["leg"]
            out["agent_killed"]["fallback_segments"] = \
                int(sa_client._FALLBACKS.total - fb0)
            assert killed["grids"] == off["grids"], \
                "fallback grids differ from the direct scan"
        finally:
            await e.close()
            await agent.close()

        # ---- disk rung: nothing resident on the coordinator ----------
        tmp = tempfile.mkdtemp(prefix="cfg17-disk-")
        disk_agent = None
        try:
            local = LocalObjectStore(tmp)
            disk_store = DataByteCounter(local, prefix="cfg17d/data/")
            e = await MetricEngine.open("cfg17d", disk_store,
                                        segment_ms=segment_ms,
                                        config=cfg)
            try:
                await ingest(e)
            finally:
                await e.close()
            disk_agent = AgentService(local)  # mmap-fast shard reads
            url = await disk_agent.start()
            sa_cfg = ScanAgentConfig(
                mode="on", num_slots=1,
                agents=(AgentSpec("shard0", url, (0,)),))
            e = await MetricEngine.open("cfg17d", disk_store,
                                        segment_ms=segment_ms,
                                        config=cfg,
                                        scanagent_config=sa_cfg)
            try:
                data = e.tables["data"]
                disk = await timed_mix(
                    e, disk_store, lambda: _clear_scan_tiers(data),
                    "disk")
                out["disk"] = disk["leg"]
                # the near-data claim, literally: the coordinator read
                # zero segment objects — nothing to hold resident
                assert disk["leg"]["store_data_gets"] == 0, \
                    "coordinator read segments on the disk agent rung"
                out["disk"]["segments_resident_coordinator"] = 0

                # dead-agent fallback on disk STREAMS whole SSTs
                # (get_stream -> file-backed mmap, not a bytes buffer)
                await disk_agent.close()
                old_min = parquet_io.STREAM_FETCH_MIN_BYTES
                parquet_io.STREAM_FETCH_MIN_BYTES = 1
                try:
                    _clear_scan_tiers(data)
                    # sidecar fetches (.enc) still buffer — only SSTs
                    # take the parquet path; force it by dropping
                    # sidecar reads for this leg
                    data.config.scan.use_sidecar = False
                    t0 = time.perf_counter()
                    await mix(e, 0)
                    fb_ms = (time.perf_counter() - t0) * 1e3
                finally:
                    parquet_io.STREAM_FETCH_MIN_BYTES = old_min
                    data.config.scan.use_sidecar = True
                out["disk_fallback"] = {
                    "p50_ms": round(fb_ms, 3),
                    "streamed_sst_reads": disk_store.stream_ops,
                }
                assert disk_store.stream_ops > 0, \
                    "dead-agent disk fallback did not stream SSTs"
            finally:
                await e.close()
        finally:
            if disk_agent is not None:
                await disk_agent.close()
            shutil.rmtree(tmp, ignore_errors=True)
        return out

    out = asyncio.run(go())
    return {
        "metric": (f"near-data scan agents: cold dashboard mix over a "
                   f"seeded {out['store_latency_ms']:.0f}ms-latency "
                   f"store, {n / 1e6:.1f}M rows, agent partials vs "
                   f"shipped segments"),
        "value": out["agent"]["p50_ms"],
        "unit": "ms",
        # done-bar: coordinator data-plane bytes, off / agent
        "vs_baseline": out["bytes_reduction_x"],
        **out,
    }


def run_config18(rows: int, iters: int) -> dict:
    """Memory plane (ISSUE 14, common/memledger.py): two legs.

    ACCURACY — the config-9 cold-scan ladder shape (cached /
    hbm-evicted / tier2-cold / true-cold) with the memory ledger
    sampling around it: Σ accounts must TRACK the process RSS delta —
    the bytes the ladder makes resident (tier-2 parts, HBM windows,
    parts memo) land in accounts, not in the unattributed residue.
    Baseline RSS is sampled after ingest with every cache tier still
    EMPTY (write-through admission off for this leg — a cache whose
    pages were ever resident would refill from retained allocator
    arenas and the RSS delta would under-measure), so the ladder's
    cache fill is genuinely new RSS.  The residue the sampler cannot
    name (XLA compile arenas for the scan programs, allocator
    overhead) is the honest error term.  Bar: |unattributed_delta| <
    20% of the RSS delta at peak (asserted in-bench at >= 1M rows;
    tiny smoke runs record it only — allocator noise dominates a
    few-MB delta).

    OVERHEAD — config-10 paired-delta methodology on the CACHED query
    path (the worst case for relative overhead): ledger disabled vs
    enabled with the sampler racing at 100 ms + per-trace
    mem_account_delta attribution.  Bar: on_overhead_pct < 2."""
    import gc

    import pyarrow as pa

    from horaedb_tpu.common.memledger import ledger
    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.utils import tracing

    hosts = 100
    interval = 10_000
    bucket_ms = 60_000
    per_host = max(60, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(18)
    n = per_host * hosts
    ts = T0 + np.repeat(
        np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])
    _check_i32_span(np.asarray([span]), "config18")
    k_cold = max(2, iters // 3)

    async def ingest(e):
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await e.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))

    async def query(e):
        return await e.query_downsample(
            "cpu", [], TimeRange.new(T0, T0 + span),
            bucket_ms=bucket_ms, aggs=("avg",))

    async def timed(e, reps, reset=None):
        times = []
        for _ in range(reps):
            if reset is not None:
                reset()
            t0 = time.perf_counter()
            await query(e)
            times.append(time.perf_counter() - t0)
        return float(np.percentile(times, 50))

    async def accuracy() -> dict:
        from horaedb_tpu.objstore import WrappedObjectStore
        from horaedb_tpu.storage.config import StorageConfig, from_dict

        class CopyOnGetStore(WrappedObjectStore):
            """Model a REAL object store's memory behavior: a GET
            materializes a FRESH buffer (S3/disk reads do), so tier-2's
            pinned blobs are their own RSS.  The raw MemoryObjectStore
            returns its resident object zero-copy, which makes tier-2
            and objstore_memory legitimately share pages — real double
            counting the ledger correctly reports, but not the
            deployment shape this leg is meant to measure."""

            async def _call(self, op: str, *args):
                r = await super()._call(op, *args)
                if op in ("get", "get_range"):
                    return bytes(bytearray(r))
                return r

        out = {}
        store = CopyOnGetStore(MemoryObjectStore())
        # write_through OFF: ingest must not touch tier-2 — a cache
        # whose pages were EVER resident refills from retained
        # allocator arenas and the RSS delta under-measures (the first
        # recording of this leg measured exactly that: attributed
        # +235 MB vs RSS +54 MB through a warmed-then-cleared cache)
        cfg = from_dict(StorageConfig, {
            "scan": {"cache_max_rows": n * 4,
                     "cache": {"write_through": False}}})
        e = await MetricEngine.open("cfg18", store,
                                    segment_ms=segment_ms, config=cfg)
        try:
            table = e.tables["data"]
            await ingest(e)
            gc.collect()
            base = ledger.sample_once()
            out["baseline_rss_bytes"] = base["rss_bytes"]
            out["baseline_attributed_bytes"] = base["attributed_bytes"]
            await query(e)  # compile scan programs + warm both tiers
            out["cached_p50_ms"] = round(
                await timed(e, iters) * 1e3, 3)
            out["hbm_evicted_p50_ms"] = round(await timed(
                e, k_cold, reset=table.reader.drop_hbm_state) * 1e3, 3)
            out["tier2_cold_p50_ms"] = round(await timed(
                e, k_cold, reset=table.reader.scan_cache.clear) * 1e3, 3)
            out["true_cold_p50_ms"] = round(await timed(
                e, k_cold,
                reset=lambda: _clear_scan_tiers(table)) * 1e3, 3)
            await query(e)  # peak: every tier re-warmed + store resident
            gc.collect()
            peak = ledger.sample_once()
            out["peak_rss_bytes"] = peak["rss_bytes"]
            out["peak_attributed_bytes"] = peak["attributed_bytes"]
            out["peak_accounts"] = {
                k: v for k, v in sorted(peak["accounts"].items()) if v}
            out["peak_unattributed_bytes"] = peak["unattributed_bytes"]
            rss_delta = peak["rss_bytes"] - base["rss_bytes"]
            attr_delta = (peak["attributed_bytes"]
                          - base["attributed_bytes"])
            out["rss_delta_bytes"] = rss_delta
            out["attributed_delta_bytes"] = attr_delta
            out["unattributed_delta_fraction"] = (
                round(1.0 - attr_delta / rss_delta, 4)
                if rss_delta > 0 else None)
            out["unattributed_fraction_absolute"] = (
                round(peak["unattributed_bytes"] / peak["rss_bytes"], 4)
                if peak["rss_bytes"] else None)
        finally:
            await e.close()
        return out

    async def overhead() -> dict:
        e = await MetricEngine.open("cfg18b", MemoryObjectStore(),
                                    segment_ms=segment_ms)
        try:
            await ingest(e)

            async def one(enabled: bool) -> float:
                """One traced query exactly as the server drives it —
                tracing ON in both legs so the paired delta isolates
                the LEDGER's marginal cost (sampler + per-trace
                mem_account_delta attribution)."""
                ledger.configure(enabled=enabled)
                t0 = time.perf_counter()
                trace = tracing.recorder.start("/query")
                if trace is not None:
                    with tracing.trace_scope(trace):
                        await query(e)
                    tracing.recorder.finish(trace)
                else:
                    await query(e)
                return time.perf_counter() - t0

            # sampler racing at 100 ms during BOTH legs (it skips work
            # while disabled — that skip is part of what "off" costs)
            ledger.configure(interval_s=0.1)
            ledger.ensure_sampler()
            tracing.recorder.configure(enabled=True, sample_rate=1.0)
            reps = max(30, iters * 3)
            for _ in range(5):
                await one(True)
            acc = {"off": [], "on": []}
            order_rng = np.random.default_rng(0x18)
            for _ in range(reps):
                for k in order_rng.permutation(["off", "on"]):
                    acc[k].append(await one(k == "on"))
            out = {}
            for k, v in acc.items():
                out[f"{k}_p50_ms"] = round(
                    float(np.percentile(v, 50)) * 1e3, 4)
            off = np.asarray(acc["off"])
            delta = float(np.median(np.asarray(acc["on"]) - off))
            out["on_overhead_us"] = round(delta * 1e6, 1)
            out["on_overhead_pct"] = round(
                delta / float(np.median(off)) * 100, 3)
            return out
        finally:
            ledger.configure(enabled=True, interval_s=5.0)
            await e.close()

    async def go():
        return {"accuracy": await accuracy(), "overhead": await overhead()}

    out = asyncio.run(go())
    acc, ov = out["accuracy"], out["overhead"]
    frac = acc["unattributed_delta_fraction"]
    _log(f"config18: ladder rss delta "
         f"{acc['rss_delta_bytes'] / 1e6:.1f} MB, attributed "
         f"{acc['attributed_delta_bytes'] / 1e6:.1f} MB, unattributed "
         f"fraction {frac} [bar < 0.2] | cached overhead "
         f"{ov['on_overhead_pct']}% ({ov['on_overhead_us']}us) "
         f"[bar < 2%]")
    if n >= 1_000_000 and frac is not None:
        # the accuracy bar is asserted at real scale only: a few-MB
        # smoke delta is allocator noise, not attribution error.
        # Two-sided: a large POSITIVE residue is untracked growth, a
        # large NEGATIVE one is account over-charge — both are the
        # ledger losing the plot
        assert abs(frac) < 0.2, (
            f"memory ledger lost track of the ladder: unattributed "
            f"delta fraction {frac}, |bar| 0.2 (accounts "
            f"{acc['peak_accounts']})")
    return {
        "metric": ("memory ledger: unattributed fraction of the "
                   "cold-scan ladder's RSS delta + cached-path "
                   "overhead of the ledger (paired)"),
        "value": ov["on_p50_ms"],
        "unit": "ms",
        # the paired ratio: cached path with the full memory plane on
        # vs off (1.0 = free; bar < 1.02)
        "vs_baseline": round(ov["on_p50_ms"] / ov["off_p50_ms"], 4),
        "rows": n,
        **out,
    }


def run_config19(rows: int, iters: int) -> dict:
    """The 2-D mesh-scan A/B (ISSUE 15, `make multichip-mesh`): the
    [scan.mesh] segmented-reduction combine vs the single-chip control
    on the SAME data, both legs forced onto the XLA window kernel
    (HORAEDB_HOST_AGG=0 / HORAEDB_FUSED_AGG=0) so the A/B isolates
    WHERE the combine ran.

    Legs:
      control_cold / mesh_cold   full-span downsample, caches cleared
                                 per rep, grids byte-compared in-bench
      mesh_topk                  top-k by max through the device
                                 -scored winner-sliced path, egress
                                 cells counter-asserted at
                                 O(k x buckets x aggs) per run part

    The work-division evidence is structural on this box (windows per
    round ~= the time-axis width; per-chip grid state / series): the
    CPU virtual mesh shares 2 physical cores, so WALL parity is
    expected here and the wall claim re-grades on a real pod
    (tpu_verified discipline — the runner records backend labels)."""
    import os

    import pyarrow as pa

    from horaedb_tpu.common import ReadableDuration
    from horaedb_tpu.common import runtimes as runtimes_mod
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage import read as read_mod
    from horaedb_tpu.storage.config import (
        StorageConfig,
        ThreadsConfig,
        from_dict,
    )
    from horaedb_tpu.storage.plan import TopKSpec
    from horaedb_tpu.storage.read import AggregateSpec, ScanRequest
    from horaedb_tpu.storage.storage import CloudObjectStorage, WriteRequest
    from horaedb_tpu.storage.types import TimeRange

    import jax

    n_devices = len(jax.devices())
    want_devices = int(os.environ.get("MESH_BENCH_DEVICES", "0") or 0)
    if want_devices and n_devices < want_devices:
        _log(f"config19: only {n_devices} devices visible "
             f"(wanted {want_devices}) — the mesh will be smaller")

    hosts = 100
    segment_ms = 2 * 3600 * 1000
    segments = 16
    per_seg = max(hosts, rows // segments)
    bucket_ms = 60_000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    span = segments * segment_ms
    _check_i32_span(np.asarray([span]), "config19")
    schema = pa.schema([("host", pa.string()), ("ts", pa.int64()),
                        ("v", pa.float64())])
    rng = np.random.default_rng(19)

    def cfg_of(mesh: bool):
        scan: dict = {"cache_max_rows": rows * 4,
                      "combine": {"memo_max_bytes": 0},
                      "cache": {"tier2_max_bytes": 1 << 30}}
        if mesh:
            scan["mesh"] = {"enabled": True}
        cfg = from_dict(StorageConfig, {
            "scheduler": {"schedule_interval": "1h"}, "scan": scan})
        cfg.manifest.merge_interval = ReadableDuration.parse("1h")
        cfg.scrub.interval = ReadableDuration.parse("1h")
        return cfg

    forced = {}
    for key in ("HORAEDB_HOST_AGG", "HORAEDB_FUSED_AGG"):
        forced[key] = os.environ.get(key)
        os.environ[key] = "0"

    async def go():
        rt = runtimes_mod.from_config(ThreadsConfig())
        store = MemoryObjectStore()
        s_ctl = await CloudObjectStorage.open(
            "db", segment_ms, store, schema, 2, cfg_of(False),
            runtimes=rt)
        for seg in range(segments):
            ts = T0 + seg * segment_ms + rng.integers(
                0, segment_ms - 1000, per_seg).astype(np.int64)
            ts.sort()
            names = [f"host_{i:03d}" for i in
                     rng.integers(0, hosts, per_seg)]
            vals = rng.random(per_seg) * 100
            b = pa.record_batch(
                [pa.array(names), pa.array(ts),
                 pa.array(vals, type=pa.float64())], schema=schema)
            await s_ctl.write(WriteRequest(
                b, TimeRange.new(int(ts[0]), int(ts[-1]) + 1)))
        s_mesh = await CloudObjectStorage.open(
            "db", segment_ms, store, schema, 2, cfg_of(True),
            runtimes=rt)
        lo, hi = T0, T0 + span
        spec = AggregateSpec(
            group_col="host", ts_col="ts", value_col="v",
            range_start=lo, bucket_ms=bucket_ms,
            num_buckets=span // bucket_ms, which=("avg", "max"))
        req = ScanRequest(range=TimeRange.new(lo, hi))

        def clear(s):
            s.reader.scan_cache.clear()
            s.reader.encoded_cache.clear()
            s.reader.parts_memo.clear()
            s.reader._stack_cache.clear()
            s.reader._stack_cache_bytes = 0

        async def leg(s, tk=None, reps=max(3, iters // 3)):
            times, out = [], None
            for _ in range(reps):
                clear(s)
                t0 = time.perf_counter()
                out = await s.scan_aggregate(req, spec, top_k=tk)
                times.append(time.perf_counter() - t0)
            return float(np.median(times) * 1e3), out

        stages0 = read_mod.plan_stage_snapshot()
        ctl_ms, ctl_out = await leg(s_ctl)
        mesh_rounds0 = read_mod._MESH_ROUNDS.value
        mesh_parts0 = read_mod._MESH_PARTS.value
        mesh_ms, mesh_out = await leg(s_mesh)
        stages1 = read_mod.plan_stage_snapshot()
        rounds = int(read_mod._MESH_ROUNDS.value - mesh_rounds0)
        parts = int(read_mod._MESH_PARTS.value - mesh_parts0)
        assert rounds > 0, "mesh leg never dispatched a round"
        # in-bench bit-identity: the A/B is meaningless if legs differ
        assert np.array_equal(ctl_out[0], mesh_out[0])
        for k in ctl_out[1]:
            assert np.asarray(ctl_out[1][k]).tobytes() == \
                np.asarray(mesh_out[1][k]).tobytes(), k

        # top-k egress leg: device-scored winners only
        tk = TopKSpec(k=5, by="max")
        topk0_cells = read_mod._MESH_PART_CELLS.value
        topk0_served = read_mod._MESH_TOPK.value
        topk_ms, topk_out = await leg(s_mesh, tk=tk)
        clear(s_ctl)
        _ctl_topk_ms, ctl_topk = await leg(s_ctl, tk=tk, reps=1)
        assert np.array_equal(topk_out[0], ctl_topk[0])
        for k in ctl_topk[1]:
            assert np.asarray(ctl_topk[1][k]).tobytes() == \
                np.asarray(topk_out[1][k]).tobytes(), k
        topk_served = int(read_mod._MESH_TOPK.value - topk0_served)
        topk_cells = int(read_mod._MESH_PART_CELLS.value - topk0_cells)
        assert topk_served > 0, "top-k never took the mesh path"
        reps_topk = max(3, iters // 3)
        # the acceptance bound: per-run winner slices only — at most
        # k rows x run width (<= num_buckets) x 8 grid kinds per
        # segment run, NEVER hosts x buckets
        bound = reps_topk * segments * tk.k * spec.num_buckets * 8
        dense_cells = hosts * spec.num_buckets * reps_topk * 3
        assert topk_cells <= bound, (topk_cells, bound)
        mesh_stats = s_mesh.reader.mesh_stats()
        shape = mesh_stats["shape"]
        out = {
            "metric": (f"mesh scan: full-span avg/max downsample over "
                       f"{segments} segments, {per_seg * segments / 1e6:.1f}M "
                       f"rows, {shape['time']}x{shape['series']} mesh, "
                       f"cold p50"),
            "value": round(mesh_ms, 1),
            "unit": "ms",
            # mesh/control: < 1 means the mesh divides the scan wall;
            # ~1 on this 2-core box is expected (virtual devices share
            # the cores) — the structural division evidence is below
            "vs_baseline": round(mesh_ms / ctl_ms, 4),
            "rows": per_seg * segments,
            "control_cold_p50_ms": round(ctl_ms, 1),
            "mesh_cold_p50_ms": round(mesh_ms, 1),
            "mesh_topk_p50_ms": round(topk_ms, 1),
            "mesh_shape": shape,
            "mesh_rounds": rounds,
            "mesh_parts": parts,
            # windows per round ~= the time-axis width when the feed
            # keeps up: the scan's window work DIVIDES across the time
            # shards (and each part's resident grid across the series
            # shards) — the structural work-division evidence on a box
            # whose virtual devices share 2 physical cores
            "windows_per_round": round(
                segments * max(3, iters // 3) / rounds, 3),
            "mesh_aggregate_s": round(
                stages1["mesh_aggregate_s"]
                - stages0["mesh_aggregate_s"], 3),
            "control_device_aggregate_s": round(
                stages1["device_aggregate_s"]
                - stages0["device_aggregate_s"], 3),
            "topk_egress_cells": topk_cells,
            "topk_egress_bound": bound,
            "topk_dense_grid_cells": dense_cells,
            "topk_served": topk_served,
            "mesh_stalls": mesh_stats["stalls"],
            "mesh_fallbacks": mesh_stats["fallbacks"],
            "bit_identical": True,
            "note": ("CPU virtual-device rung: wall parity expected "
                     "(all shards share 2 physical cores); work "
                     "division is structural (windows_per_round, "
                     "series-sharded grid state, topk egress bound). "
                     "Re-grade walls on a real TPU pod — same command, "
                     "tpu_verified discipline."),
        }
        _log(f"config19: control {ctl_ms:.0f}ms vs mesh {mesh_ms:.0f}ms "
             f"({shape['time']}x{shape['series']} mesh, {rounds} rounds, "
             f"{parts} parts); topk egress {topk_cells} cells "
             f"(dense grid would be {dense_cells})")
        await s_mesh.close()
        await s_ctl.close()
        rt.close()
        return out

    try:
        return asyncio.run(go())
    finally:
        for key, old in forced.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


def run_config20(rows: int, iters: int) -> dict:
    """Failover SLO harness (ISSUE 16): the config-15-shaped OPEN-LOOP
    driver — arrivals fire on a precomputed Poisson schedule regardless
    of completions — over a real HTTP server with `[replication]` on,
    a follower mirroring the WAL over the /repl/wal/* plane, and the
    primary killed -9 at mid-leg:

      dash     compliant: steady cached downsample dashboards
      writer   compliant: steady small write batches (WAL + fence path)

    At leg/2 the harness takes the primary's compute plane down (HTTP
    listener gone, ingest loops aborted WITHOUT a final flush), drains
    the already-committed WAL tail into the mirror — modeling the
    Taurus split where the durable log plane survives compute death —
    then promotes the follower: lease acquired at a higher epoch once
    the dead primary's TTL lapses, mirror replayed, a fresh server
    serving the same shared-store SSTs.  Arrivals during the outage
    record their failure codes (that IS the failover damage); the
    remaining schedule routes to the promoted node.

    Recorded: failover_ms (kill -> promoted node serving, including
    the lease-TTL wait), acked_write_loss (every 200-acked write must
    be readable after promotion — MUST be 0), and compliant p99 per
    phase.  iters scales the leg duration."""
    import os
    import random as random_mod
    import tempfile

    import aiohttp
    import pyarrow as pa
    from aiohttp import web

    from horaedb_tpu.cluster.replication import (HttpWalSource,
                                                 LeaseManager,
                                                 LocalWalSource,
                                                 ReplicationConfig,
                                                 ReplicationError,
                                                 WalFollower, promote,
                                                 install_fence)
    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import FaultInjectingStore, MemoryObjectStore
    from horaedb_tpu.server.config import ReadableDuration, ServerConfig
    from horaedb_tpu.server.main import ServerState, build_app
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.wal.config import WalConfig

    lat_s = float(os.environ.get("BENCH_STORE_LATENCY_MS", "20")) / 1e3
    seed = int(os.environ.get("REPL_BENCH_SEED", "20"))
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    leg_seconds = max(4.0, min(30.0, float(iters)))
    kill_at = leg_seconds / 2.0
    lease_ttl_ms = 2_000
    n_fix = min(max(20_000, rows), 200_000)
    hosts = 50
    span = 3_600_000
    # the writer lands in the OPEN segment ahead of the dashboards'
    # completed window (the config-15 discipline: a dashboard
    # aggregate never pre-flushes the writer's fresh memtable rows)
    TW0 = T0 + 3 * segment_ms
    dash_q = {"metric": "app", "filters": {}, "start": T0,
              "end": T0 + span, "bucket_ms": 300_000}

    def write_req(i: int) -> dict:
        # unique (host, timestamp) per request, value = i: the
        # verification pass recomputes these from the acked index set
        return {"samples": [
            {"name": "ingest", "labels": {"host": f"w{i % 8:02d}"},
             "timestamp": TW0 + i * 1000, "value": float(i)}]}

    def schedule(rng):
        events = []

        def poisson(rate, make):
            t = 0.0
            for i in range(int(leg_seconds * rate)):
                t += rng.expovariate(rate)
                events.append((t,) + make(i))

        poisson(5.0, lambda i: ("/query", dash_q, -1))
        poisson(10.0, lambda i: ("/write", write_req(i), i))
        events.sort(key=lambda e: e[0])
        return events

    async def start_server(state):
        app = build_app(state)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        return runner, f"http://127.0.0.1:{port}"

    async def go():
        store = FaultInjectingStore(MemoryObjectStore(), seed=seed,
                                    latency_range=(lat_s, lat_s))
        wal_dir = tempfile.mkdtemp(prefix="repl-bench-wal-")
        mirror_dir = tempfile.mkdtemp(prefix="repl-bench-mirror-")
        rng_np = np.random.default_rng(seed)
        # fixture: a dashboard table plus a bulk table so promotion
        # replays a real manifest — ingested WAL-free, then reopened
        # with the WAL front end (the serving legs exercise the WAL)
        engine = await MetricEngine.open("metrics/region_0", store,
                                         segment_ms=segment_ms)
        per_host = n_fix // hosts
        ts = T0 + np.repeat(
            np.arange(per_host, dtype=np.int64)
            * max(1, span // max(per_host, 1)), hosts)
        ids = np.tile(np.arange(hosts, dtype=np.int32), per_host)
        names = pa.array([f"host_{i:03d}" for i in range(hosts)])
        await engine.write_arrow("cpu", ["host"], pa.record_batch({
            "host": pa.DictionaryArray.from_arrays(pa.array(ids), names),
            "timestamp": pa.array(ts, type=pa.int64()),
            "value": pa.array(rng_np.random(len(ts)), type=pa.float64()),
        }))
        m = 20 * 360
        await engine.write_arrow("app", ["host"], pa.record_batch({
            "host": pa.array([f"app_{i % 20:02d}" for i in range(m)]),
            "timestamp": pa.array(
                T0 + np.arange(m, dtype=np.int64) * 10_000 % span,
                type=pa.int64()),
            "value": pa.array(rng_np.random(m), type=pa.float64()),
        }))
        await engine.close()

        wal_template = WalConfig(enabled=True, dir=wal_dir)
        engine = await MetricEngine.open(
            "metrics/region_0", store, segment_ms=segment_ms,
            wal_config=wal_template)
        cfg = ServerConfig()
        cfg.replication.enabled = True
        cfg.replication.region = 0
        cfg.replication.holder = "bench-primary"
        cfg.replication.lease_ttl = ReadableDuration.from_millis(
            lease_ttl_ms)
        cfg.replication.renew_interval = ReadableDuration.from_millis(500)
        state = ServerState(engine, cfg)
        await state.start_replication(store)
        runner, base = await start_server(state)
        follower = WalFollower(
            HttpWalSource(base, "bench-follower", timeout_s=5.0),
            mirror_dir,
            ReplicationConfig(
                poll_interval=ReadableDuration.from_millis(50)),
            region=0)
        follower.start()

        target = {"base": base}
        lat: dict = {}
        session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
            timeout=aiohttp.ClientTimeout(total=10))
        acked: set = set()
        t_start = time.perf_counter()

        async def fire(at, path, payload, widx):
            t0 = time.perf_counter()
            try:
                r = await session.post(  # noqa: session-wide timeout
                    target["base"] + path, json=payload)
                status = r.status
                await r.release()
            except asyncio.TimeoutError:
                status = -1
            except aiohttp.ClientError:
                status = -2
            dt = time.perf_counter() - t0
            if status == 200 and widx >= 0:
                acked.add(widx)
            kind = "query" if path == "/query" else "write"
            lat.setdefault(kind, []).append((at, dt, status))

        fail = {}
        engine2 = lease2 = runner2 = None

        async def failover():
            nonlocal engine2, lease2, runner2
            await asyncio.sleep(kill_at)
            t_kill = time.perf_counter()
            # compute plane dies: listener gone, ingest loops aborted
            # with NO final flush — acked tail lives only in WAL bytes
            await runner.cleanup()
            await follower.close()
            # the durable log plane outlives the process: drain the
            # committed tail into the mirror before replay
            drain = WalFollower(LocalWalSource(state.repl,
                                               "bench-follower"),
                                mirror_dir, region=0)
            for _ in range(100):
                await drain.poll_once()
                if drain.lag() == 0:
                    break
            else:
                raise RuntimeError(
                    f"mirror failed to drain: lag {drain.lag()}")
            await drain.close()
            await state.stop_replication()  # renewals stop with it
            for t in engine.tables.values():
                abort = getattr(t, "abort", None)
                if abort is not None:
                    await abort()
            engine._runtimes.close()
            fail["drain_ms"] = round((time.perf_counter() - t_kill)
                                     * 1e3, 1)
            mgr = LeaseManager(store, "metrics")
            attempts = 0
            while True:
                attempts += 1
                try:
                    # config 21 is the self-driving variant; this
                    # manual retry loop is the CONTROL leg
                    engine2, lease2 = await promote(  # noqa: control leg
                        "metrics", store, 0, mgr, "bench-follower",
                        mirror_dir, wal_template,
                        segment_ms=segment_ms,
                        lease_ttl_ms=10_000, reason="primary_dead")
                    break
                except ReplicationError:
                    # the dead primary's lease has not expired yet
                    await asyncio.sleep(0.05)
            lease2.start_renewal(2.0, 10_000)
            state2 = ServerState(engine2, ServerConfig())
            runner2, base2 = await start_server(state2)
            target["base"] = base2
            fail["failover_ms"] = round((time.perf_counter() - t_kill)
                                        * 1e3, 1)
            fail["lease_acquire_attempts"] = attempts

        try:
            # unmeasured preamble: warm both request shapes
            for path, payload in (("/query", dash_q),
                                  ("/write", write_req(10**9))):
                r = await session.post(  # noqa: session-wide timeout
                    base + path, json=payload)
                await r.release()
            lat.clear()
            acked.clear()
            fo = asyncio.create_task(failover())
            tasks = []
            for at, path, payload, widx in schedule(
                    random_mod.Random(seed)):
                delay = t_start + at - time.perf_counter()
                if delay > 0:
                    await asyncio.sleep(delay)
                tasks.append(asyncio.create_task(
                    fire(at, path, payload, widx)))
            await asyncio.gather(*tasks)
            await fo

            # zero-acked-write-loss audit against the PROMOTED engine:
            # every 200-acked write must be readable with its value
            rng = TimeRange.new(TW0 - 1, TW0 + 10_000_000)
            got = {}
            for h in range(8):
                t = await engine2.query("ingest",
                                        [("host", f"w{h:02d}")], rng)
                for ts_v, v in zip(t.column("timestamp").to_pylist(),
                                   t.column("value").to_pylist()):
                    got[(h, ts_v)] = v
            lost = sum(
                1 for i in sorted(acked)
                if got.get((i % 8, TW0 + i * 1000)) != float(i))
            out = {"rows": n_fix, "leg_seconds": leg_seconds,
                   "store_latency_ms": lat_s * 1e3,
                   "lease_ttl_ms": lease_ttl_ms, **fail,
                   "acked_writes": len(acked),
                   "acked_write_loss": lost}
            for kind, ls in sorted(lat.items()):
                for phase, sel in (
                        ("pre_kill", [x for x in ls if x[0] < kill_at]),
                        ("post_kill", [x for x in ls
                                       if x[0] >= kill_at])):
                    oks = [dt for _, dt, s in sel if s == 200]
                    codes: dict = {}
                    for _, _, s in sel:
                        codes[str(s)] = codes.get(str(s), 0) + 1
                    out[f"{kind}_{phase}"] = {
                        "n": len(sel),
                        "ok": len(oks),
                        "p99_ms": (round(float(np.percentile(
                            np.asarray(oks) * 1e3, 99)), 1)
                            if oks else None),
                        "codes": codes,
                    }
            return out
        finally:
            await session.close()
            if runner2 is not None:
                await runner2.cleanup()
            if lease2 is not None:
                await lease2.stop_renewal()
            if engine2 is not None:
                install_fence(engine2, None)
                await engine2.close()

    out = asyncio.run(go())
    out["bar_zero_loss"] = out["acked_write_loss"] == 0
    # the outage window is visible as non-200 codes post-kill; the SLO
    # form: compliant p99 of SERVED requests stays bounded and every
    # acked write survived
    out["slo_query_p99_ms"] = 500.0
    out["slo_write_p99_ms"] = 1000.0
    served_ok = all(
        out[k]["p99_ms"] is not None
        and out[k]["p99_ms"] < (out["slo_write_p99_ms"]
                                if k.startswith("write")
                                else out["slo_query_p99_ms"])
        for k in ("query_pre_kill", "write_pre_kill",
                  "query_post_kill", "write_post_kill"))
    out["bar_slo_ok"] = served_ok and out["bar_zero_loss"]
    _log(f"config20: failover {out.get('failover_ms')} ms "
         f"(drain {out.get('drain_ms')} ms, "
         f"{out.get('lease_acquire_attempts')} lease attempts) | "
         f"acked {out['acked_writes']} lost {out['acked_write_loss']} | "
         f"served p99 bar {'MET' if out['bar_slo_ok'] else 'MISSED'}")
    # vs_baseline (config-7 form): served-query p99 degradation across
    # the failover — post-kill p99 over pre-kill p99, 1.0 = the
    # promoted node serves exactly like the dead primary did (phases
    # that served nothing fall back to 1.0: no served sample, no ratio)
    pre = out["query_pre_kill"]["p99_ms"]
    post = out["query_post_kill"]["p99_ms"]
    degradation = (round(post / pre, 3)
                   if pre and post else 1.0)
    return {
        "metric": ("replication failover: kill -9 at mid-leg, follower "
                   "promoted from WAL mirror, open-loop SLO"),
        "value": out.get("failover_ms"),
        "unit": "ms",
        "vs_baseline": degradation,
        **out,
    }


def run_config21(rows: int, iters: int) -> dict:
    """Self-driving failover SLO harness (ISSUE 17): the config-20
    drill with the promotion decision moved INTO the system.  The
    harness only kills — it never calls promote().  A StandbyMonitor
    tails the primary's lease record; when the lease sits expired past
    the jittered grace window, the monitor runs the election itself
    (fitness publish, sibling check, lease acquire at a higher epoch),
    replays its mirror, and the on_promoted hook brings up the new
    serving node.  Config 20 is the CONTROL leg (manual promote retry
    loop); the delta between the two failover_ms values is the price
    of self-driving detection + election.

    Recorded: failover_ms (kill -> promoted node serving — detection,
    grace, election, replay, server start), acked_write_loss (MUST be
    0), election attempts/outcome, and bar_failover_bound: failover_ms
    must stay under lease TTL + the worst-case grace window + a fixed
    slack for check ticks, fitness wait, replay, and listener start."""
    import os
    import random as random_mod
    import tempfile

    import aiohttp
    from aiohttp import web
    import pyarrow as pa

    from horaedb_tpu.cluster.replication import (FailoverConfig,
                                                 LeaseManager,
                                                 LocalWalSource,
                                                 ReplicationConfig,
                                                 ReplicationError,
                                                 StandbyMonitor,
                                                 WalFollower,
                                                 install_fence)
    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import FaultInjectingStore, MemoryObjectStore
    from horaedb_tpu.server.config import ReadableDuration, ServerConfig
    from horaedb_tpu.server.main import ServerState, build_app
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.wal.config import WalConfig

    lat_s = float(os.environ.get("BENCH_STORE_LATENCY_MS", "20")) / 1e3
    seed = int(os.environ.get("FAILOVER_BENCH_SEED",
                              os.environ.get("FAILOVER_SEED", "21")))
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    leg_seconds = max(4.0, min(30.0, float(iters)))
    kill_at = leg_seconds / 2.0
    lease_ttl_ms = 2_000
    grace_ms = 500
    jitter = 0.5
    n_fix = min(max(20_000, rows), 200_000)
    hosts = 50
    span = 3_600_000
    TW0 = T0 + 3 * segment_ms
    dash_q = {"metric": "app", "filters": {}, "start": T0,
              "end": T0 + span, "bucket_ms": 300_000}

    def write_req(i: int) -> dict:
        return {"samples": [
            {"name": "ingest", "labels": {"host": f"w{i % 8:02d}"},
             "timestamp": TW0 + i * 1000, "value": float(i)}]}

    def schedule(rng):
        events = []

        def poisson(rate, make):
            t = 0.0
            for i in range(int(leg_seconds * rate)):
                t += rng.expovariate(rate)
                events.append((t,) + make(i))

        poisson(5.0, lambda i: ("/query", dash_q, -1))
        poisson(10.0, lambda i: ("/write", write_req(i), i))
        events.sort(key=lambda e: e[0])
        return events

    async def start_server(state):
        app = build_app(state)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        return runner, f"http://127.0.0.1:{port}"

    async def go():
        store = FaultInjectingStore(MemoryObjectStore(), seed=seed,
                                    latency_range=(lat_s, lat_s))
        wal_dir = tempfile.mkdtemp(prefix="failover-bench-wal-")
        mirror_dir = tempfile.mkdtemp(prefix="failover-bench-mirror-")
        rng_np = np.random.default_rng(seed)
        engine = await MetricEngine.open("metrics/region_0", store,
                                         segment_ms=segment_ms)
        per_host = n_fix // hosts
        ts = T0 + np.repeat(
            np.arange(per_host, dtype=np.int64)
            * max(1, span // max(per_host, 1)), hosts)
        ids = np.tile(np.arange(hosts, dtype=np.int32), per_host)
        names = pa.array([f"host_{i:03d}" for i in range(hosts)])
        await engine.write_arrow("cpu", ["host"], pa.record_batch({
            "host": pa.DictionaryArray.from_arrays(pa.array(ids), names),
            "timestamp": pa.array(ts, type=pa.int64()),
            "value": pa.array(rng_np.random(len(ts)), type=pa.float64()),
        }))
        m = 20 * 360
        await engine.write_arrow("app", ["host"], pa.record_batch({
            "host": pa.array([f"app_{i % 20:02d}" for i in range(m)]),
            "timestamp": pa.array(
                T0 + np.arange(m, dtype=np.int64) * 10_000 % span,
                type=pa.int64()),
            "value": pa.array(rng_np.random(m), type=pa.float64()),
        }))
        await engine.close()

        wal_template = WalConfig(enabled=True, dir=wal_dir)
        engine = await MetricEngine.open(
            "metrics/region_0", store, segment_ms=segment_ms,
            wal_config=wal_template)
        cfg = ServerConfig()
        cfg.replication.enabled = True
        cfg.replication.region = 0
        cfg.replication.holder = "bench-primary"
        cfg.replication.lease_ttl = ReadableDuration.from_millis(
            lease_ttl_ms)
        cfg.replication.renew_interval = ReadableDuration.from_millis(500)
        state = ServerState(engine, cfg)
        await state.start_replication(store)
        runner, base = await start_server(state)
        # the standby tails the primary's DURABLE log plane in-process
        # (the Taurus split: the log outlives the compute that wrote it)
        follower = WalFollower(
            LocalWalSource(state.repl, "bench-standby"), mirror_dir,
            ReplicationConfig(
                poll_interval=ReadableDuration.from_millis(50)),
            region=0)

        target = {"base": base}
        lat: dict = {}
        fail: dict = {}
        session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
            timeout=aiohttp.ClientTimeout(total=10))
        acked: set = set()
        t_start = time.perf_counter()
        engine2 = lease2 = runner2 = None

        async def on_promoted(engine_p, lease_p):
            # the takeover hook IS the failover-time finish line: the
            # monitor won the election and replayed its mirror; bring
            # up the serving node and flip the routing target
            nonlocal engine2, lease2, runner2
            engine2, lease2 = engine_p, lease_p
            lease_p.start_renewal(2.0, 10_000)
            state2 = ServerState(engine_p, ServerConfig())
            runner2, base2 = await start_server(state2)
            target["base"] = base2
            fail["failover_ms"] = round(
                (time.perf_counter() - fail["_t_kill"]) * 1e3, 1)
            fail["epoch"] = lease_p.epoch

        monitor = StandbyMonitor(
            follower, LeaseManager(store, "metrics"), 0,
            "bench-standby",
            FailoverConfig(
                enabled=True,
                grace=ReadableDuration.from_millis(grace_ms),
                jitter=jitter,
                check_interval=ReadableDuration.from_millis(100),
                fitness_wait=ReadableDuration.from_millis(100),
                cooldown=ReadableDuration.from_millis(1000)),
            wal_template, segment_ms=segment_ms, lease_ttl_ms=10_000,
            on_promoted=on_promoted)
        monitor.start()

        # the steady-state ship loop is the harness's (serialized
        # against the kill-time drain; the monitor only polls inside
        # its own election)
        stop_ship = asyncio.Event()

        async def shipper():
            while not stop_ship.is_set():
                try:
                    await follower.poll_once()
                except ReplicationError:
                    return
                await asyncio.sleep(0.05)

        ship_task = asyncio.create_task(shipper())

        async def fire(at, path, payload, widx):
            t0 = time.perf_counter()
            try:
                r = await session.post(  # noqa: session-wide timeout
                    target["base"] + path, json=payload)
                status = r.status
                await r.release()
            except asyncio.TimeoutError:
                status = -1
            except aiohttp.ClientError:
                status = -2
            dt = time.perf_counter() - t0
            if status == 200 and widx >= 0:
                acked.add(widx)
            kind = "query" if path == "/query" else "write"
            lat.setdefault(kind, []).append((at, dt, status))

        async def kill():
            """The harness's ONLY failure action: compute plane down,
            log plane drained, renewals stopped.  No promote() —
            detection, election, and takeover are the monitor's job."""
            await asyncio.sleep(kill_at)
            t_kill = time.perf_counter()
            fail["_t_kill"] = t_kill
            await runner.cleanup()
            await state.lease.stop_renewal()
            stop_ship.set()
            await ship_task
            # the durable log plane outlives the process: drain the
            # already-committed tail into the mirror, then let the
            # compute die for real
            for _ in range(100):
                await follower.poll_once()
                if follower.lag() == 0:
                    break
            else:
                raise RuntimeError(
                    f"mirror failed to drain: lag {follower.lag()}")
            fail["drain_ms"] = round((time.perf_counter() - t_kill)
                                     * 1e3, 1)
            await state.stop_replication()
            for t in engine.tables.values():
                abort = getattr(t, "abort", None)
                if abort is not None:
                    await abort()
            engine._runtimes.close()

        try:
            for path, payload in (("/query", dash_q),
                                  ("/write", write_req(10**9))):
                r = await session.post(  # noqa: session-wide timeout
                    base + path, json=payload)
                await r.release()
            lat.clear()
            acked.clear()
            ko = asyncio.create_task(kill())
            tasks = []
            for at, path, payload, widx in schedule(
                    random_mod.Random(seed)):
                delay = t_start + at - time.perf_counter()
                if delay > 0:
                    await asyncio.sleep(delay)
                tasks.append(asyncio.create_task(
                    fire(at, path, payload, widx)))
            await asyncio.gather(*tasks)
            await ko
            # the monitor owns the rest: wait for its election to land
            # (failover_ms is stamped LAST in on_promoted, so seeing
            # it means the promoted node is serving)
            for _ in range(600):
                if "failover_ms" in fail:
                    break
                await asyncio.sleep(0.05)
            if "failover_ms" not in fail:
                raise RuntimeError(
                    "standby monitor never promoted: "
                    f"{monitor.election_state()}")

            rng = TimeRange.new(TW0 - 1, TW0 + 10_000_000)
            got = {}
            for h in range(8):
                t = await engine2.query("ingest",
                                        [("host", f"w{h:02d}")], rng)
                for ts_v, v in zip(t.column("timestamp").to_pylist(),
                                   t.column("value").to_pylist()):
                    got[(h, ts_v)] = v
            lost = sum(
                1 for i in sorted(acked)
                if got.get((i % 8, TW0 + i * 1000)) != float(i))
            fail.pop("_t_kill", None)
            out = {"rows": n_fix, "leg_seconds": leg_seconds,
                   "store_latency_ms": lat_s * 1e3,
                   "lease_ttl_ms": lease_ttl_ms,
                   "grace_ms": grace_ms, "jitter": jitter, **fail,
                   "harness_promote_calls": 0,
                   "election_attempts": monitor.attempts,
                   "election_outcome": (monitor.last_outcome or {}
                                        ).get("outcome"),
                   "acked_writes": len(acked),
                   "acked_write_loss": lost}
            for kind, ls in sorted(lat.items()):
                for phase, sel in (
                        ("pre_kill", [x for x in ls if x[0] < kill_at]),
                        ("post_kill", [x for x in ls
                                       if x[0] >= kill_at])):
                    oks = [dt for _, dt, s in sel if s == 200]
                    codes: dict = {}
                    for _, _, s in sel:
                        codes[str(s)] = codes.get(str(s), 0) + 1
                    out[f"{kind}_{phase}"] = {
                        "n": len(sel),
                        "ok": len(oks),
                        "p99_ms": (round(float(np.percentile(
                            np.asarray(oks) * 1e3, 99)), 1)
                            if oks else None),
                        "codes": codes,
                    }
            return out
        finally:
            await session.close()
            await monitor.close()
            await follower.close()
            if runner2 is not None:
                await runner2.cleanup()
            if lease2 is not None:
                await lease2.stop_renewal()
            if engine2 is not None:
                install_fence(engine2, None)
                await engine2.close()

    out = asyncio.run(go())
    out["bar_zero_loss"] = out["acked_write_loss"] == 0
    # detection + election + replay must land inside the lease TTL +
    # the worst-case jittered grace window + a fixed slack (two check
    # ticks, the fitness wait, mirror replay, listener start); a
    # self-driving failover that cannot beat this bound is worse than
    # the paged-operator path it replaces
    slack_ms = 3_000.0
    out["failover_bound_ms"] = (lease_ttl_ms
                                + grace_ms * (1.0 + out["jitter"])
                                + slack_ms)
    out["bar_failover_bound"] = (
        out.get("failover_ms") is not None
        and out["failover_ms"] <= out["failover_bound_ms"])
    out["slo_query_p99_ms"] = 500.0
    out["slo_write_p99_ms"] = 1000.0
    served_ok = all(
        out[k]["p99_ms"] is not None
        and out[k]["p99_ms"] < (out["slo_write_p99_ms"]
                                if k.startswith("write")
                                else out["slo_query_p99_ms"])
        for k in ("query_pre_kill", "write_pre_kill",
                  "query_post_kill", "write_post_kill"))
    out["bar_slo_ok"] = (served_ok and out["bar_zero_loss"]
                         and out["bar_failover_bound"])
    _log(f"config21: self-driving failover {out.get('failover_ms')} ms "
         f"(bound {out['failover_bound_ms']} ms, drain "
         f"{out.get('drain_ms')} ms, epoch {out.get('epoch')}, "
         f"{out['election_attempts']} election attempts, 0 harness "
         f"promotes) | acked {out['acked_writes']} lost "
         f"{out['acked_write_loss']} | bar "
         f"{'MET' if out['bar_slo_ok'] else 'MISSED'}")
    pre = out["query_pre_kill"]["p99_ms"]
    post = out["query_post_kill"]["p99_ms"]
    degradation = (round(post / pre, 3)
                   if pre and post else 1.0)
    return {
        "metric": ("self-driving failover: kill -9 at mid-leg, standby "
                   "monitor detects + elects + promotes on its own, "
                   "open-loop SLO"),
        "value": out.get("failover_ms"),
        "unit": "ms",
        "vs_baseline": degradation,
        **out,
    }


def run_config22(rows: int, iters: int) -> dict:
    """The mesh-placed fused-decode A/B (ISSUE 19, `make
    multichip-mesh`): one device program from stored bytes to ranked
    answer — per-round shard_map dispatches fed RAW ENCODED sidecar
    buffers (leaf-filter + k-way merge-dedup + bucket-aggregate +
    segmented combine in one jit) vs the PR 15 mesh over host-decoded
    windows vs the single-chip control, all on the SAME data and all
    forced onto the XLA window kernel (HORAEDB_HOST_AGG=0 /
    HORAEDB_FUSED_AGG=0) so the A/B isolates decode+combine placement.

    Legs (cold = caches cleared per rep, grids byte-compared in-bench):
      control_cold     no mesh, host decode (single-chip)
      mesh_cold        [scan.mesh] rounds, host decode (PR 15)
      meshdecode_cold  [scan.mesh] rounds from encoded bytes (ISSUE 19)
      additive top-k   count-ranked winners through the compensated
                       (hi, lo) device score plane — egress cells
                       counter-asserted at O(k x buckets x aggs) per
                       run part, at TWO group cardinalities (100 and
                       800 hosts) so the bound provably does not scale
                       with the group count

    Half the segments get a second overlapping write so multi-SST
    interleaved segments ride the device k-way merge (route="kway"
    asserted, the full device lax.sort asserted NEVER paid).

    The wall claim is honest per the recorded note: on this CPU
    virtual-device rung all shards share 2 physical cores, so the XLA
    single-chip control leg is the meaningful wall reference and the
    pod-scale wall re-grades on real chips (tpu_verified discipline)."""
    import os

    import pyarrow as pa

    from horaedb_tpu.common import ReadableDuration
    from horaedb_tpu.common import runtimes as runtimes_mod
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.ops import device_decode as dd_mod
    from horaedb_tpu.storage import read as read_mod
    from horaedb_tpu.storage.config import (
        StorageConfig,
        ThreadsConfig,
        from_dict,
    )
    from horaedb_tpu.storage.plan import TopKSpec
    from horaedb_tpu.storage.read import AggregateSpec, ScanRequest
    from horaedb_tpu.storage.storage import CloudObjectStorage, WriteRequest
    from horaedb_tpu.storage.types import TimeRange

    import jax

    n_devices = len(jax.devices())
    want_devices = int(os.environ.get("MESH_BENCH_DEVICES", "0") or 0)
    if want_devices and n_devices < want_devices:
        _log(f"config22: only {n_devices} devices visible "
             f"(wanted {want_devices}) — the mesh will be smaller")

    hosts = 100
    hosts_big = 800
    segment_ms = 2 * 3600 * 1000
    segments = 16
    per_seg = max(hosts, rows // segments)
    bucket_ms = 60_000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    span = segments * segment_ms
    _check_i32_span(np.asarray([span]), "config22")
    schema = pa.schema([("host", pa.string()), ("ts", pa.int64()),
                        ("v", pa.float64())])
    rng = np.random.default_rng(22)

    def cfg_of(mesh: bool, decode: str):
        scan: dict = {"cache_max_rows": rows * 4,
                      "combine": {"memo_max_bytes": 0},
                      "cache": {"tier2_max_bytes": 1 << 30},
                      "decode": {"mode": decode}}
        if mesh:
            scan["mesh"] = {"enabled": True}
        cfg = from_dict(StorageConfig, {
            "scheduler": {"schedule_interval": "1h"}, "scan": scan})
        cfg.manifest.merge_interval = ReadableDuration.parse("1h")
        cfg.scrub.interval = ReadableDuration.parse("1h")
        return cfg

    forced = {}
    for key in ("HORAEDB_HOST_AGG", "HORAEDB_FUSED_AGG"):
        forced[key] = os.environ.get(key)
        os.environ[key] = "0"

    async def fill(s, n_hosts, n_rows_per, overlap=True):
        for seg in range(segments):
            passes = [n_rows_per]
            if overlap and seg % 2:
                # second overlapping SST: the k-way merge's territory
                passes.append(max(n_hosts, n_rows_per // 8))
            for n in passes:
                ts = T0 + seg * segment_ms + rng.integers(
                    0, segment_ms - 1000, n).astype(np.int64)
                ts.sort()
                names = [f"host_{i:03d}" for i in
                         rng.integers(0, n_hosts, n)]
                vals = rng.random(n) * 100
                b = pa.record_batch(
                    [pa.array(names), pa.array(ts),
                     pa.array(vals, type=pa.float64())], schema=schema)
                await s.write(WriteRequest(
                    b, TimeRange.new(int(ts[0]), int(ts[-1]) + 1)))

    async def go():
        rt = runtimes_mod.from_config(ThreadsConfig())
        store = MemoryObjectStore()
        s_ctl = await CloudObjectStorage.open(
            "db", segment_ms, store, schema, 2, cfg_of(False, "host"),
            runtimes=rt)
        await fill(s_ctl, hosts, per_seg)
        s_mesh = await CloudObjectStorage.open(
            "db", segment_ms, store, schema, 2, cfg_of(True, "host"),
            runtimes=rt)
        s_dec = await CloudObjectStorage.open(
            "db", segment_ms, store, schema, 2, cfg_of(True, "device"),
            runtimes=rt)
        lo, hi = T0, T0 + span
        spec = AggregateSpec(
            group_col="host", ts_col="ts", value_col="v",
            range_start=lo, bucket_ms=bucket_ms,
            num_buckets=span // bucket_ms, which=("avg", "max"))
        req = ScanRequest(range=TimeRange.new(lo, hi))

        def clear(s):
            s.reader.scan_cache.clear()
            s.reader.encoded_cache.clear()
            s.reader.parts_memo.clear()
            s.reader._stack_cache.clear()
            s.reader._stack_cache_bytes = 0

        reps = max(3, iters // 3)

        async def leg(s, tk=None, sp=None, rq=None, n=reps):
            times, out = [], None
            for _ in range(n):
                clear(s)
                t0 = time.perf_counter()
                out = await s.scan_aggregate(rq or req, sp or spec,
                                             top_k=tk)
                times.append(time.perf_counter() - t0)
            return float(np.median(times) * 1e3), out

        def same(a, b, ctx):
            assert np.array_equal(a[0], b[0]), ctx
            for k in a[1]:
                assert np.asarray(a[1][k]).tobytes() == \
                    np.asarray(b[1][k]).tobytes(), (ctx, k)

        ctl_ms, ctl_out = await leg(s_ctl)
        rounds0 = read_mod._MESH_ROUNDS.value
        mesh_ms, mesh_out = await leg(s_mesh)
        mesh_rounds = int(read_mod._MESH_ROUNDS.value - rounds0)
        rounds0 = read_mod._MESH_ROUNDS.value
        kway0 = dd_mod._SORT_SKIPPED["kway"].value
        sorted0 = dd_mod._SORT_RAN.value
        drows0 = dd_mod._STAGE_ROWS.value
        dec_ms, dec_out = await leg(s_dec)
        dec_rounds = int(read_mod._MESH_ROUNDS.value - rounds0)
        kway_skips = int(dd_mod._SORT_SKIPPED["kway"].value - kway0)
        full_sorts = int(dd_mod._SORT_RAN.value - sorted0)
        dec_rows = int(dd_mod._STAGE_ROWS.value - drows0)
        assert mesh_rounds > 0, "mesh leg never dispatched a round"
        assert dec_rounds > 0, \
            "fused-decode leg never dispatched a mesh round"
        assert dec_rows > 0, "fused-decode leg never decoded on device"
        # the k-way routing evidence: overlapped segments merged their
        # presorted runs on device, the full lax.sort never paid
        assert kway_skips > 0, "no segment took the k-way merge route"
        assert full_sorts == 0, \
            f"{full_sorts} dispatches paid the full device sort"
        # in-bench bit-identity across ALL THREE legs
        same(ctl_out, mesh_out, "control vs mesh")
        same(ctl_out, dec_out, "control vs mesh+decode")

        # additive top-k egress at two group cardinalities (count is
        # admissible against any agg set; decode stays host on this
        # leg — the topk_decode gate keeps mixed provenance out of
        # device scoring by design)
        tk = TopKSpec(k=5, by="count")

        async def additive_leg(s, sp, rq):
            clear(s)
            served0 = read_mod._MESH_TOPK.value
            cells0 = read_mod._MESH_PART_CELLS.value
            tk_ms, tk_out = await leg(s, tk=tk, sp=sp, rq=rq, n=1)
            assert read_mod._MESH_TOPK.value == served0 + 1, \
                "additive top-k not device-served"
            return tk_ms, tk_out, int(
                read_mod._MESH_PART_CELLS.value - cells0)

        topk_ms, topk_out, cells_small = await additive_leg(
            s_mesh, spec, req)
        _ctl_ms, ctl_topk = await leg(s_ctl, tk=tk, n=1)
        same(ctl_topk, topk_out, "control vs additive topk")
        # cardinality 2: same segments/span/k, 8x the hosts
        store2 = MemoryObjectStore()
        s2_ctl = await CloudObjectStorage.open(
            "db", segment_ms, store2, schema, 2, cfg_of(False, "host"),
            runtimes=rt)
        await fill(s2_ctl, hosts_big, max(hosts_big, per_seg // 4),
                   overlap=False)
        s2_mesh = await CloudObjectStorage.open(
            "db", segment_ms, store2, schema, 2, cfg_of(True, "host"),
            runtimes=rt)
        _ms2, topk2_out, cells_big = await additive_leg(
            s2_mesh, spec, req)
        _c2, ctl2_topk = await leg(s2_ctl, tk=tk, n=1)
        same(ctl2_topk, topk2_out, "control vs additive topk (800)")
        # parts x k x run width x grid kinds; parts = 16 + 8 overlap
        # runs on the small store, 16 on the big one
        bound = 24 * tk.k * spec.num_buckets * 8
        assert cells_small <= bound, (cells_small, bound)
        assert cells_big <= bound, (cells_big, bound)
        # THE additive acceptance bound: winner egress must not scale
        # with the group count (the score vector is counted
        # separately) — 8x the hosts, same ceiling
        assert cells_big <= cells_small * 2, (cells_small, cells_big)

        mesh_stats = s_dec.reader.mesh_stats()
        shape = mesh_stats["shape"]
        out = {
            "metric": (f"mesh fused decode: full-span avg/max "
                       f"downsample over {segments} segments "
                       f"(8 multi-SST), "
                       f"{per_seg * segments / 1e6:.1f}M rows, "
                       f"{shape['time']}x{shape['series']} mesh, "
                       f"stored-bytes-to-answer cold p50"),
            "value": round(dec_ms, 1),
            "unit": "ms",
            "vs_baseline": round(dec_ms / ctl_ms, 4),
            "rows": per_seg * segments,
            "control_cold_p50_ms": round(ctl_ms, 1),
            "mesh_cold_p50_ms": round(mesh_ms, 1),
            "meshdecode_cold_p50_ms": round(dec_ms, 1),
            "meshdecode_vs_mesh": round(dec_ms / mesh_ms, 4),
            "additive_topk_p50_ms": round(topk_ms, 1),
            "mesh_shape": shape,
            "mesh_rounds": mesh_rounds,
            "meshdecode_rounds": dec_rounds,
            "device_decoded_rows": dec_rows,
            "kway_merge_dispatches": kway_skips,
            "full_device_sorts": full_sorts,
            "additive_topk_cells_100": cells_small,
            "additive_topk_cells_800": cells_big,
            "additive_topk_bound": bound,
            "additive_topk_dense_cells_800": (
                hosts_big * spec.num_buckets * 2),
            "mesh_stalls": mesh_stats["stalls"],
            "mesh_fallbacks": mesh_stats["fallbacks"],
            "bit_identical": True,
            "note": ("CPU virtual-device rung — wall caveat: the "
                     "multichip_r02 271ms cold-p50 bar is NOT met "
                     "here and cannot be on this box. All shards "
                     "share 2 physical cores, and XLA-on-CPU runs "
                     "the fused decode kernels interpreted-slow: "
                     "bench_results/device_decode_r01.json already "
                     "measured plain device decode ~3x the host "
                     "decode wall on this rung (device_true_cold "
                     "3379ms vs host 1206ms), which bounds every "
                     "from-stored-bytes leg below. The single-chip "
                     "XLA control leg recorded alongside is the "
                     "honest wall reference; decode placement, k-way "
                     "routing, zero full sorts, bit-identity, and "
                     "the additive egress bound are structural and "
                     "hold regardless. Re-grade walls on a real TPU "
                     "pod — same command, tpu_verified discipline."),
        }
        _log(f"config22: control {ctl_ms:.0f}ms vs mesh {mesh_ms:.0f}ms "
             f"vs mesh+decode {dec_ms:.0f}ms "
             f"({shape['time']}x{shape['series']} mesh, "
             f"{dec_rounds} fused rounds, {kway_skips} kway merges, "
             f"{full_sorts} full sorts); additive topk egress "
             f"{cells_small} -> {cells_big} cells at 100 -> 800 hosts")
        for s in (s2_mesh, s2_ctl, s_dec, s_mesh, s_ctl):
            await s.close()
        rt.close()
        return out

    try:
        return asyncio.run(go())
    finally:
        for key, old in forced.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


def run_config23(rows: int, iters: int) -> dict:
    """Device-profiler cost + attribution (ISSUE 20,
    docs/observability.md device plane): the profiler must be cheap
    enough to stay on AND actually explain the cold query it watches.

    Legs:
      overhead     ONE cached device-decode aggregate measured with
                   the profiler off vs on, config-10 methodology
                   (randomized within-pair order, per-rep PAIRED
                   deltas so machine drift cancels).  Done-bar: on
                   within 2% of off.
      dispatch     hot-loop micro twin: the ProfiledJit wrapper vs
                   its inner jitted function on a cached call — the
                   per-dispatch ledger cost in microseconds (the
                   worst case the cached leg dilutes).
      attribution  a true cold fused mesh-decode scan traced with the
                   profiler on: the compile + dispatch + exec +
                   transfer attribution it recorded must cover >= 80%
                   of the measured device-stage wall (asserted
                   in-bench) — a ledger that cannot explain the cold
                   query is decoration, not observability."""
    import os

    import pyarrow as pa

    from horaedb_tpu.common import ReadableDuration
    from horaedb_tpu.common import runtimes as runtimes_mod
    from horaedb_tpu.common.deviceprof import profiler as dp
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage.config import (
        StorageConfig,
        ThreadsConfig,
        from_dict,
    )
    from horaedb_tpu.storage.read import AggregateSpec, ScanRequest
    from horaedb_tpu.storage.storage import CloudObjectStorage, WriteRequest
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.utils import tracing

    import jax.numpy as jnp

    hosts = 100
    segment_ms = 2 * 3600 * 1000
    segments = 8
    per_seg = max(hosts, rows // segments)
    bucket_ms = 60_000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    span = segments * segment_ms
    _check_i32_span(np.asarray([span]), "config23")
    schema = pa.schema([("host", pa.string()), ("ts", pa.int64()),
                        ("v", pa.float64())])
    rng = np.random.default_rng(23)

    # the attribution leg isolates WHERE device wall went, so the
    # aggregate must actually run the XLA window kernel (the decode
    # tests' bit-identity convention)
    forced = os.environ.get("HORAEDB_HOST_AGG")
    os.environ["HORAEDB_HOST_AGG"] = "0"

    cfg = from_dict(StorageConfig, {
        "scheduler": {"schedule_interval": "1h"},
        "scan": {"cache_max_rows": rows * 4,
                 "cache": {"tier2_max_bytes": 1 << 30},
                 "mesh": {"enabled": True},
                 "decode": {"mode": "device"}},
    })
    cfg.manifest.merge_interval = ReadableDuration.parse("1h")
    cfg.scrub.interval = ReadableDuration.parse("1h")

    async def go():
        rt = runtimes_mod.from_config(ThreadsConfig())
        s = await CloudObjectStorage.open(
            "db", segment_ms, MemoryObjectStore(), schema, 2, cfg,
            runtimes=rt)
        for seg in range(segments):
            ts = T0 + seg * segment_ms + rng.integers(
                0, segment_ms - 1000, per_seg).astype(np.int64)
            ts.sort()
            names = [f"host_{i:03d}" for i in
                     rng.integers(0, hosts, per_seg)]
            vals = rng.random(per_seg) * 100
            b = pa.record_batch(
                [pa.array(names), pa.array(ts),
                 pa.array(vals, type=pa.float64())], schema=schema)
            await s.write(WriteRequest(
                b, TimeRange.new(int(ts[0]), int(ts[-1]) + 1)))
        lo, hi = T0, T0 + span
        spec = AggregateSpec(
            group_col="host", ts_col="ts", value_col="v",
            range_start=lo, bucket_ms=bucket_ms,
            num_buckets=span // bucket_ms, which=("avg", "max"))
        req = ScanRequest(range=TimeRange.new(lo, hi))

        def clear():
            s.reader.scan_cache.clear()
            s.reader.encoded_cache.clear()
            s.reader.parts_memo.clear()
            s.reader._stack_cache.clear()
            s.reader._stack_cache_bytes = 0

        # ---- attribution: one true cold fused-decode scan, traced --
        dp.configure(enabled=True)
        dp.clear()
        clear()
        tracing.recorder.configure(enabled=True, sample_rate=1.0)
        trace = tracing.recorder.start("/scan-cold")
        t0 = time.perf_counter()
        with tracing.trace_scope(trace):
            await s.scan_aggregate(req, spec)
        cold_ms = (time.perf_counter() - t0) * 1e3
        tracing.recorder.finish(trace)
        c = trace.counters
        xfer = (dp.transfer["h2d"]["seconds"]
                + dp.transfer["d2h"]["seconds"]) * 1e3
        attributed = {
            "compile_ms": round(c.get("stage_device_compile_ms", 0.0), 2),
            "dispatch_ms": round(
                c.get("stage_device_dispatch_ms", 0.0), 2),
            "exec_ms": round(c.get("stage_device_exec_ms", 0.0), 2),
            "transfer_ms": round(xfer, 2),
        }
        device_stage_ms = float(c.get("stage_device_ms", 0.0))
        assert device_stage_ms > 0, \
            "cold scan never entered the device decode stage"
        ratio = sum(attributed.values()) / device_stage_ms
        # THE attribution acceptance bar: the ledger explains >= 80%
        # of the device-stage wall it claims to profile
        assert ratio >= 0.8, (ratio, attributed, device_stage_ms)

        # ---- overhead: cached path, profiler off vs on, paired -----
        async def one(enabled: bool) -> float:
            dp.configure(enabled=enabled)
            t0 = time.perf_counter()
            await s.scan_aggregate(req, spec)
            return time.perf_counter() - t0

        for _ in range(5):  # warm the scan caches
            await one(True)
        reps = max(30, iters * 3)
        acc = {"off": [], "on": []}
        order_rng = np.random.default_rng(0xC23)
        for _ in range(reps):
            for k in order_rng.permutation(list(acc)):
                acc[k].append(await one(k == "on"))
        dp.configure(enabled=True)
        off = np.asarray(acc["off"])
        on = np.asarray(acc["on"])
        delta = float(np.median(on - off))
        out_overhead = {
            "off_p50_ms": round(float(np.percentile(off, 50)) * 1e3, 4),
            "on_p50_ms": round(float(np.percentile(on, 50)) * 1e3, 4),
            "on_overhead_us": round(delta * 1e6, 1),
            "on_overhead_pct": round(
                delta / float(np.median(off)) * 100, 3),
        }

        # ---- per-dispatch wrapper cost: hot micro twin -------------
        f = dp.jit(lambda x: x + 1.0, name="cfg23_hot")
        x = jnp.zeros(4096, dtype=jnp.float32)
        f(x).block_until_ready()  # compile outside the timed loops
        inner = f._jitted
        n_hot = 2000
        t0 = time.perf_counter()
        for _ in range(n_hot):
            inner(x)
        inner(x).block_until_ready()
        bare_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n_hot):
            f(x)
        f(x).block_until_ready()
        prof_s = time.perf_counter() - t0
        dispatch_overhead_us = (prof_s - bare_s) / n_hot * 1e6

        snap = dp.snapshot()
        out = {
            "metric": (f"device profiler: cached device-decode scan "
                       f"p50 with every jitted seam profiled, "
                       f"{per_seg * segments / 1e6:.1f}M rows"),
            "value": out_overhead["on_p50_ms"],
            "unit": "ms",
            # done-bar: profiler-on within 2% of off (1.0 = free)
            "vs_baseline": round(
                out_overhead["on_p50_ms"]
                / max(out_overhead["off_p50_ms"], 1e-9), 4),
            "rows": per_seg * segments,
            **out_overhead,
            "dispatch_wrapper_overhead_us": round(
                dispatch_overhead_us, 2),
            "cold_wall_ms": round(cold_ms, 1),
            "cold_device_stage_ms": round(device_stage_ms, 1),
            "cold_attributed": attributed,
            "cold_attribution_ratio": round(ratio, 4),
            "cold_compiles": sum(r["compiles"] for r in snap["fns"]),
            "transfer_bytes": {d: t["bytes"]
                               for d, t in snap["transfer"].items()},
            "mesh_rounds_recorded": len(snap["rounds"]),
        }
        _log(f"config23: cached off {out_overhead['off_p50_ms']}ms vs "
             f"on {out_overhead['on_p50_ms']}ms "
             f"({out_overhead['on_overhead_pct']}%), wrapper "
             f"{dispatch_overhead_us:.2f}us/dispatch; cold "
             f"{cold_ms:.0f}ms = {attributed} over device stage "
             f"{device_stage_ms:.0f}ms (ratio {ratio:.2f})")
        await s.close()
        rt.close()
        return out

    try:
        return asyncio.run(go())
    finally:
        tracing.recorder.configure(enabled=True, sample_rate=1.0)
        if forced is None:
            os.environ.pop("HORAEDB_HOST_AGG", None)
        else:
            os.environ["HORAEDB_HOST_AGG"] = forced


RUNNERS = {2: run_config2, 3: run_config3, 4: run_config4, 5: run_config5,
           6: run_config6, 7: run_config7, 8: run_config8, 9: run_config9,
           10: run_config10, 11: run_config11, 12: run_config12,
           13: run_config13, 14: run_config14, 15: run_config15,
           16: run_config16, 17: run_config17, 18: run_config18,
           19: run_config19, 20: run_config20, 21: run_config21,
           22: run_config22, 23: run_config23}


def main() -> None:
    parser = argparse.ArgumentParser("horaedb-tpu bench suite")
    parser.add_argument("--config", type=int, required=True,
                        choices=sorted(RUNNERS))
    parser.add_argument("--rows", type=int, default=2_000_000)
    parser.add_argument("--iters", type=int, default=10)
    args = parser.parse_args()
    result = RUNNERS[args.config](args.rows, args.iters)
    for k, v in provenance().items():
        result.setdefault(k, v)  # a config's own labels win
    print(json.dumps(result))


if __name__ == "__main__":
    main()
