"""TSBS-style devops data generation (numpy-vectorized).

Models the TSBS `cpu-only` / `devops` workloads BASELINE.md configs use:
N hosts (with region/datacenter tags), F cpu fields, one point per host
per interval.  Generation is pure numpy so benches can build 10M+ rows
in seconds — no per-row Python objects.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import pyarrow as pa

CPU_FIELDS = [
    "usage_user", "usage_system", "usage_idle", "usage_nice", "usage_iowait",
    "usage_irq", "usage_softirq", "usage_steal", "usage_guest",
    "usage_guest_nice",
]

REGIONS = ["us-east-1", "us-west-1", "us-west-2", "eu-west-1", "eu-central-1",
           "ap-southeast-1", "ap-southeast-2", "ap-northeast-1",
           "sa-east-1"]


@dataclass
class TsbsConfig:
    num_hosts: int = 100
    num_fields: int = 1
    interval_ms: int = 10_000
    start_ms: int = 1_700_000_000_000
    span_ms: int = 3_600_000
    seed: int = 42


def host_names(n: int) -> list[str]:
    return [f"host_{i}" for i in range(n)]


def region_of_hosts(n: int) -> np.ndarray:
    """Region tag per host, round-robin like TSBS's host generator."""
    return np.array([REGIONS[i % len(REGIONS)] for i in range(n)], dtype=object)


def generate_cpu_arrays(cfg: TsbsConfig, shuffle: bool = False) -> dict[str, np.ndarray]:
    """Columns for the flat storage-bench schema:
    host_id int32 (dict code), ts int64, usage_* float64 per field.

    Row order is host-major then time by default (the best case for
    sort/dedup paths); pass shuffle=True for TSBS's interleaved scrape
    order, the realistic ingest case.
    """
    rng = np.random.default_rng(cfg.seed)
    n_steps = cfg.span_ms // cfg.interval_ms
    n = cfg.num_hosts * n_steps
    host_id = np.repeat(np.arange(cfg.num_hosts, dtype=np.int32), n_steps)
    ts = np.tile(
        cfg.start_ms + np.arange(n_steps, dtype=np.int64) * cfg.interval_ms,
        cfg.num_hosts)
    cols: dict[str, np.ndarray] = {"host_id": host_id, "ts": ts}
    # TSBS cpu usage: random walk clipped to [0, 100]
    for f in range(cfg.num_fields):
        walk = rng.normal(0, 1, n).cumsum() % 100.0
        cols[CPU_FIELDS[f]] = np.abs(walk)
    if shuffle:
        perm = rng.permutation(n)
        cols = {k: v[perm] for k, v in cols.items()}
    return cols


def cpu_record_batch(cfg: TsbsConfig, include_region: bool = False,
                     shuffle: bool = False) -> pa.RecordBatch:
    """Arrow batch with a string host tag — the storage engine's user
    schema shape (host[, region], ts, fields...)."""
    cols = generate_cpu_arrays(cfg, shuffle=shuffle)
    names = host_names(cfg.num_hosts)
    host = pa.array(np.array(names, dtype=object)[cols["host_id"]])
    arrays = [host]
    fields = [("host", pa.string())]
    if include_region:
        arrays.append(pa.array(region_of_hosts(cfg.num_hosts)[cols["host_id"]]))
        fields.append(("region", pa.string()))
    arrays.append(pa.array(cols["ts"], type=pa.int64()))
    fields.append(("ts", pa.int64()))
    for f in range(cfg.num_fields):
        name = CPU_FIELDS[f]
        arrays.append(pa.array(cols[name], type=pa.float64()))
        fields.append((name, pa.float64()))
    return pa.record_batch(arrays, schema=pa.schema(fields))
