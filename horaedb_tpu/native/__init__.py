"""ctypes bindings for the C++ host-path kernels (native/horaedb_native.cpp).

The library is built on demand with the in-image g++ toolchain and cached
next to the source; every entry point has a numpy fallback, so the
framework works (slower) if no compiler is present.  `available()` reports
which path is active.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhoraedb_native.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False
_lock = threading.Lock()

# Single source of truth for the snapshot wire format (mirrored by
# SnapshotRecordC in native/horaedb_native.cpp and cross-checked by the
# spec-twin classes in storage/manifest/encoding.py + golden tests).
SNAPSHOT_MAGIC = 0xCAFE_1234
SNAPSHOT_VERSION = 1
RECORD_DTYPE = np.dtype(
    [("id", "<u8"), ("start", "<i8"), ("end", "<i8"),
     ("size", "<u4"), ("num_rows", "<u4")], align=False)

_HEADER_LEN = 14
_RECORD_LEN = RECORD_DTYPE.itemsize
assert _RECORD_LEN == 32


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        # always run make: it is a no-op when the .so is newer than the
        # source, and rebuilds automatically after source edits
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as e:
            logger.warning("native build failed: %s", e)
            if not os.path.exists(_LIB_PATH):
                logger.warning("using numpy fallbacks")
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.warning("native load failed, using numpy fallbacks: %s", e)
            return None
        try:
            _bind(lib)
        except AttributeError as e:
            # a stale prebuilt .so missing newer symbols must degrade to
            # the numpy/Python fallbacks, not crash the first caller
            logger.warning("native library out of date (%s); "
                           "using fallbacks", e)
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
        lib.snapshot_encode.restype = ctypes.c_longlong
        lib.snapshot_encode.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                        ctypes.c_void_p, ctypes.c_size_t]
        lib.snapshot_decode.restype = ctypes.c_longlong
        lib.snapshot_decode.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                        ctypes.c_void_p, ctypes.c_size_t]
        lib.run_starts_i64.restype = None
        lib.run_starts_i64.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                                       ctypes.c_int, ctypes.c_size_t,
                                       ctypes.c_void_p]
        lib.run_last_indices.restype = ctypes.c_size_t
        lib.run_last_indices.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                         ctypes.c_void_p]
        lib.seahash64.restype = ctypes.c_uint64
        lib.seahash64.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.seahash64_batch.restype = None
        lib.seahash64_batch.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                        ctypes.c_size_t, ctypes.c_void_p]
        lib.chunk_batch_capacity.restype = ctypes.c_longlong
        lib.chunk_batch_capacity.argtypes = [ctypes.c_void_p,
                                             ctypes.c_void_p,
                                             ctypes.c_size_t]
        lib.chunk_batch_decode.restype = ctypes.c_longlong
        lib.chunk_batch_decode.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_size_t, ctypes.c_void_p,
                                           ctypes.c_void_p, ctypes.c_void_p]


def available() -> bool:
    return _load() is not None


def is_loaded() -> bool:
    """True iff the library is ALREADY loaded — never triggers a build.
    Request-path callers (single-key hash64) gate on this so the first
    hash of a process cannot block behind a synchronous compile."""
    return _lib is not None


# ---------------------------------------------------------------------------
# snapshot codec
# ---------------------------------------------------------------------------


def snapshot_encode(records: np.ndarray) -> bytes:
    """records: RECORD_DTYPE structured array -> snapshot bytes."""
    records = np.ascontiguousarray(records, dtype=RECORD_DTYPE)
    n = len(records)
    if n == 0:
        # Empty bytes, not a header-only buffer: the reference decodes
        # empty bytes as the default snapshot but REJECTS header-only
        # buffers (encoding.rs requires record_total_length > 0) — its
        # own empty into_bytes() is unreadable, a quirk we don't copy.
        return b""
    lib = _load()
    out = np.empty(_HEADER_LEN + n * _RECORD_LEN, dtype=np.uint8)
    if lib is not None:
        written = lib.snapshot_encode(
            records.ctypes.data_as(ctypes.c_void_p), n,
            out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
        assert written == out.nbytes
        return out.tobytes()
    # numpy fallback: header + raw little-endian struct bytes (the dtype
    # layout IS the wire layout)
    import struct

    header = struct.pack("<IBBQ", SNAPSHOT_MAGIC, SNAPSHOT_VERSION, 0,
                         n * _RECORD_LEN)
    return header + records.tobytes()


def snapshot_decode(buf: bytes) -> np.ndarray:
    """snapshot bytes -> RECORD_DTYPE structured array (validates header)."""
    from horaedb_tpu.common.error import Error, ensure

    if not buf:
        return np.empty(0, dtype=RECORD_DTYPE)
    lib = _load()
    n_max = max(0, (len(buf) - _HEADER_LEN)) // _RECORD_LEN
    if lib is not None:
        out = np.empty(n_max, dtype=RECORD_DTYPE)
        src = np.frombuffer(buf, dtype=np.uint8)
        n = lib.snapshot_decode(src.ctypes.data_as(ctypes.c_void_p), len(buf),
                                out.ctypes.data_as(ctypes.c_void_p), n_max)
        if n == -2:
            raise Error("invalid bytes to convert to header")
        if n == -5:
            raise Error(f"snapshot version is newer than supported "
                        f"{SNAPSHOT_VERSION}")
        if n == -6:
            raise Error("snapshot body is empty (header-only buffer); "
                        "an empty snapshot is encoded as zero bytes")
        ensure(n >= 0, f"snapshot decode failed (code {n}): length mismatch")
        return out[:n]
    import struct

    ensure(len(buf) >= _HEADER_LEN, "snapshot header truncated")
    magic, ver, _flag, length = struct.unpack_from("<IBBQ", buf)
    ensure(magic == SNAPSHOT_MAGIC, "invalid bytes to convert to header")
    ensure(ver <= SNAPSHOT_VERSION,
           f"snapshot version {ver} is newer than supported "
           f"{SNAPSHOT_VERSION}")
    body = buf[_HEADER_LEN:]
    ensure(length > 0, "snapshot body is empty (header-only buffer); "
           "an empty snapshot is encoded as zero bytes")
    ensure(length == len(body) and length % _RECORD_LEN == 0,
           f"snapshot length mismatch: header={length}, body={len(body)}")
    return np.frombuffer(body, dtype=RECORD_DTYPE).copy()


# ---------------------------------------------------------------------------
# run detection (host merge fallback)
# ---------------------------------------------------------------------------


def run_starts_i64(cols: list[np.ndarray]) -> np.ndarray:
    """Run-start mask over sorted int64 key columns."""
    n = len(cols[0]) if cols else 0
    if n == 0:
        return np.zeros(0, dtype=bool)
    lib = _load()
    if lib is not None:
        c_cols = [np.ascontiguousarray(c, dtype=np.int64) for c in cols]
        ptrs = (ctypes.c_void_p * len(c_cols))(
            *[c.ctypes.data_as(ctypes.c_void_p).value for c in c_cols])
        out = np.zeros(n, dtype=np.uint8)
        lib.run_starts_i64(ptrs, len(c_cols), n,
                           out.ctypes.data_as(ctypes.c_void_p))
        return out.astype(bool)
    starts = np.zeros(n, dtype=bool)
    starts[0] = True
    for c in cols:
        c = np.asarray(c)
        starts[1:] |= c[1:] != c[:-1]
    return starts


def run_last_indices(starts: np.ndarray) -> np.ndarray:
    """Last row index per run from a run-start mask."""
    n = len(starts)
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    lib = _load()
    if lib is not None:
        starts_u8 = np.ascontiguousarray(starts, dtype=np.uint8)
        out = np.empty(n, dtype=np.int64)
        k = lib.run_last_indices(starts_u8.ctypes.data_as(ctypes.c_void_p), n,
                                 out.ctypes.data_as(ctypes.c_void_p))
        return out[:k]
    idx = np.nonzero(starts)[0]
    return np.append(idx[1:] - 1, n - 1)


# ---------------------------------------------------------------------------
# SeaHash (metric/series id hashing)
# ---------------------------------------------------------------------------


def seahash64(buf: bytes) -> Optional[int]:
    """Native SeaHash of one key; None when the library is unavailable
    (callers fall back to the Python spec twin in common/seahash)."""
    lib = _load()
    if lib is None:
        return None
    return int(lib.seahash64(buf, len(buf)))


def seahash64_batch(keys: list[bytes]) -> Optional[np.ndarray]:
    """Hash many keys in ONE FFI call (high-cardinality ingest hashes a
    key per unique series).  Returns uint64 hashes, or None when the
    native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    lens = np.fromiter((len(k) for k in keys), dtype=np.int64,
                       count=len(keys))
    offsets = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    buf = b"".join(keys)
    out = np.empty(len(keys), dtype=np.uint64)
    lib.seahash64_batch(buf, offsets.ctypes.data_as(ctypes.c_void_p),
                        len(keys), out.ctypes.data_as(ctypes.c_void_p))
    return out


# ---------------------------------------------------------------------------
# chunk codec batch decode (metric_engine/chunks.py is the spec twin)
# ---------------------------------------------------------------------------


def chunk_decode_batch(payloads):
    """Decode MANY chunk payloads (one per (series, field) row) in one
    FFI call: per payload, all chunks decode + stable-sort + last-wins
    timestamp dedup — bit-identical to chunks.decode_chunks.

    `payloads` is a pyarrow binary Array (zero-copy: the C call reads
    the array's own offsets + data buffers) or a list of bytes.
    Returns (ts int64, values f64, counts int64-per-payload) with
    ts/values concatenated in payload order, or None when the native
    library is unavailable, the input shape is unsupported, or any
    payload is malformed (callers fall back to the Python decoder,
    which raises the precise error)."""
    lib = _load()
    if lib is None:
        return None
    holder, data_ptr, offsets, n = _payload_buffers(payloads)
    if data_ptr is None:
        return None  # unsupported input shape: use the Python decoder
    if n == 0:
        return (np.empty(0, np.int64), np.empty(0, np.float64),
                np.empty(0, np.int64))
    off_ptr = offsets.ctypes.data_as(ctypes.c_void_p)
    cap = lib.chunk_batch_capacity(data_ptr, off_ptr, n)
    if cap < 0:
        return None
    ts = np.empty(int(cap), dtype=np.int64)
    vals = np.empty(int(cap), dtype=np.float64)
    counts = np.empty(n, dtype=np.int64)
    total = lib.chunk_batch_decode(
        data_ptr, off_ptr, n, ts.ctypes.data_as(ctypes.c_void_p),
        vals.ctypes.data_as(ctypes.c_void_p),
        counts.ctypes.data_as(ctypes.c_void_p))
    del holder  # keep the source buffer alive through both FFI calls
    if total < 0:
        return None
    return ts[:int(total)], vals[:int(total)], counts


def _arrow_buffers(payloads):
    """Seam over Array.buffers(): some pyarrow builds hand back a None
    data buffer for all-empty binary arrays (tests patch this to pin
    the fallback behavior — pa.Array.from_buffers validates the shape
    away, so it cannot be constructed directly)."""
    return payloads.buffers()


def _payload_buffers(payloads):
    """(holder, data_ptr, int64 offsets (n+1), n) for the C ABI.
    `holder` keeps the underlying buffer alive; data_ptr is None when
    the input shape can't be used (caller falls back to Python).  The
    pyarrow path is zero-copy: the pointer is the array's own data
    buffer, and slice offsets are honored via the offsets window."""
    try:
        import pyarrow as pa
    except ImportError:  # pragma: no cover
        pa = None
    if pa is not None and isinstance(payloads, pa.ChunkedArray):
        payloads = payloads.combine_chunks()
    if pa is not None and isinstance(payloads, pa.Array) and \
            pa.types.is_binary(payloads.type):
        if payloads.null_count:
            return None, None, None, 0
        _validity, off_buf, data_buf = _arrow_buffers(payloads)
        if data_buf is None:
            # an all-empty binary array carries no data buffer at all;
            # .address would raise — fall back to the Python decoder,
            # which the caller's contract promises on unsupported shapes
            return None, None, None, 0
        offs = np.frombuffer(off_buf, dtype=np.int32)[
            payloads.offset:payloads.offset + len(payloads) + 1]
        return (data_buf, ctypes.c_void_p(data_buf.address),
                np.ascontiguousarray(offs, dtype=np.int64), len(payloads))
    if isinstance(payloads, (list, tuple)):
        lens = np.fromiter((len(p) for p in payloads), dtype=np.int64,
                           count=len(payloads))
        offsets = np.zeros(len(payloads) + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        buf = np.frombuffer(b"".join(payloads) or b"\x00", dtype=np.uint8)
        return (buf, buf.ctypes.data_as(ctypes.c_void_p), offsets,
                len(payloads))
    return None, None, None, 0
