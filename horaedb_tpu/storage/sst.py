"""SST file model (ref: src/storage/src/sst.rs).

`SstFile` couples immutable metadata with a mutable `in_compaction` flag
(the picker's mutual-exclusion mechanism, ref: sst.rs:97-106).  File ids
come from a process-wide monotonic counter seeded with wall-clock
nanoseconds so ids never go backwards across restarts (ref: sst.rs:36-46)
— the id doubles as the write sequence for cross-file dedup.
"""

from __future__ import annotations

from dataclasses import dataclass

from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.id_alloc import MonotonicIdAllocator
from horaedb_tpu.storage.types import Timestamp, TimeRange

DATA_PREFIX = "data"

FileId = int

_SST_IDS = MonotonicIdAllocator()


_U32_MAX = (1 << 32) - 1
_U64_MAX = (1 << 64) - 1


@dataclass(frozen=True)
class FileMeta:
    """Per-SST metadata (ref: sst.rs FileMeta, pb sst.proto SstMeta).

    num_rows and size are u32 on the wire (sst.proto SstMeta, snapshot
    record layout), so the bounds are enforced at construction — a write
    that would overflow must fail at write time, not inside the manifest
    merger.
    """

    max_sequence: int
    num_rows: int
    size: int
    time_range: TimeRange

    def __post_init__(self) -> None:
        ensure(0 <= self.max_sequence <= _U64_MAX,
               f"max_sequence out of u64 range: {self.max_sequence}")
        ensure(0 <= self.num_rows <= _U32_MAX,
               f"num_rows out of u32 range: {self.num_rows}")
        ensure(0 <= self.size <= _U32_MAX,
               f"sst size out of u32 range: {self.size} (split the write)")


class SstFile:
    __slots__ = ("id", "meta", "_in_compaction")

    def __init__(self, file_id: FileId, meta: FileMeta):
        self.id = file_id
        self.meta = meta
        self._in_compaction = False

    @staticmethod
    def allocate_id() -> FileId:
        return _SST_IDS.allocate()

    def mark_compaction(self) -> None:
        self._in_compaction = True

    def unmark_compaction(self) -> None:
        self._in_compaction = False

    @property
    def in_compaction(self) -> bool:
        return self._in_compaction

    def is_expired(self, expire_time: Timestamp | None) -> bool:
        """TTL check: a file is expired when it ends before `expire_time`
        (ref: sst.rs:109-114)."""
        return expire_time is not None and self.meta.time_range.end < expire_time

    @property
    def size(self) -> int:
        return self.meta.size

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, SstFile)
            and other.id == self.id
            and other.meta == self.meta
        )

    def __hash__(self) -> int:
        return hash(self.id)

    def __repr__(self) -> str:
        return (
            f"SstFile(id={self.id}, rows={self.meta.num_rows}, "
            f"size={self.meta.size}, range={self.meta.time_range}, "
            f"in_compaction={self._in_compaction})"
        )


def sst_path(prefix: str, file_id: FileId) -> str:
    """Object-store key for an SST (ref: sst.rs:202-204: `{prefix}/data/{id}.sst`)."""
    return f"{prefix}/{DATA_PREFIX}/{file_id}.sst"


def segment_of(f: "SstFile", segment_duration_ms: int) -> int:
    """The time segment an SST belongs to — THE segment-assignment rule
    (keyed by range START truncation, ref: storage.rs:342-350), shared
    by the scan planner, compaction picker, and race re-resolution so
    they can never disagree."""
    return int(f.meta.time_range.start.truncate_by(segment_duration_ms))
