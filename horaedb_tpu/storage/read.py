"""Merge-scan read path — the north-star pipeline.

The reference builds, per time segment, a DataFusion physical plan
  ParquetExec(+pruning) → FilterExec → SortPreservingMergeExec → MergeExec
and streams batches through it (ref: src/storage/src/read.rs:429-494).

The TPU redesign keeps the same operator boundary but executes each
segment as one compiled device program (see ops/):

  ParquetScan (host, async)      — read + concat all SSTs in the segment
  Encode (host)                  — Arrow → int32/f32 device batch
  Filter (device mask)           — predicate tree → validity mask
  MergeDedup (device)            — sort (pk...,seq) + segmented last-select
  Decode (host)                  — device batch → Arrow, builtin columns
                                   stripped unless keep_builtin

Append mode routes the merge through the host BytesMergeOperator instead
(variable-length values; fixed-width device design).

Plans are described as text via `describe_plan` for golden plan-shape
tests, the analogue of the reference's DisplayableExecutionPlan test
(ref: read.rs:575-617).
"""

from __future__ import annotations

import asyncio
import functools
import logging
import threading
import time
import weakref
from dataclasses import dataclass, replace as dc_replace
from typing import AsyncIterator, Optional

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from horaedb_tpu.common import deviceprof
from horaedb_tpu.common.deadline import checkpoint as deadline_checkpoint
from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.memledger import ledger as memledger
from horaedb_tpu.common.tenant import charge_scan_bytes
from horaedb_tpu.objstore import NotFoundError, ObjectStore
from horaedb_tpu.ops import downsample as downsample_ops
from horaedb_tpu.ops import encode, filter as filter_ops, merge as merge_ops
from horaedb_tpu.storage.config import StorageConfig, UpdateMode
from horaedb_tpu.storage.operator import build_operator
from horaedb_tpu.storage.sst import SstFile, segment_of, sst_path
from horaedb_tpu.storage.types import (
    RESERVED_COLUMN_NAME,
    SEQ_COLUMN_NAME,
    StorageSchema,
    TimeRange,
)
from horaedb_tpu.ops import device_decode
from horaedb_tpu.storage import combine as combine_mod, parquet_io, sidecar
from horaedb_tpu.utils import active_trace, registry, trace_add

logger = logging.getLogger(__name__)

_SCAN_LATENCY = registry.histogram(
    "storage_scan_seconds", "merge-scan latency per segment")
_ROWS_SCANNED = registry.counter(
    "storage_rows_scanned_total", "rows produced by merge-scan")

# Per-plan-stage attribution (the reference wires ExecutionPlanMetricsSet
# through its reader, read.rs:84; ours records real numbers): seconds,
# rows, and bytes per pipeline stage, cumulative in the registry and
# diffable around a query for a per-query profile (bench.py does this).
# One labeled family per unit (stage= label) instead of a metric name
# per stage; per-QUERY attribution additionally lands on the ambient
# trace via tracing.trace_add (docs/observability.md).
_PLAN_STAGES = ("parquet_read", "sidecar_read", "encode_merge",
                "stack_build", "device_decode", "device_aggregate",
                "mesh_aggregate", "combine")
_STAGE_SECONDS = {
    s: registry.histogram("scan_stage_seconds",
                          "wall seconds per merge-scan plan stage"
                          ).labels(stage=s)
    for s in _PLAN_STAGES
}
_STAGE_ROWS = {
    s: registry.counter("scan_stage_rows_total",
                        "rows entering each plan stage").labels(stage=s)
    for s in ("parquet_read", "sidecar_read", "encode_merge",
              "device_decode")
}
_STAGE_BYTES = {
    s: registry.counter("scan_stage_bytes_total",
                        "bytes entering each plan stage").labels(stage=s)
    for s in ("parquet_read", "sidecar_read", "stack_build",
              "device_decode")
}
# cache-effectiveness counters (ops parity with scan_cache_*): the
# replay and stack LRUs are the reason repeat/varied queries are fast —
# a production operator needs their hit rates on /metrics
_REPLAY_HITS = registry.counter(
    "scan_replay_hits_total", "fused-replay plan cache hits")
_REPLAY_ROWS = registry.counter(
    "scan_replay_rows_total",
    "rows served from fused-replay hits without re-scanning")
_REPLAY_MISSES = registry.counter(
    "scan_replay_misses_total", "fused-replay plan cache misses")
_STACK_HITS = registry.counter(
    "scan_stack_cache_hits_total",
    "per-range round-stack LRU hits (small remap/shift/lo entries)")
_STACK_MISSES = registry.counter(
    "scan_stack_cache_misses_total",
    "per-range round-stack LRU misses")
_COLSTACK_HITS = registry.counter(
    "scan_colstack_cache_hits_total",
    "range-independent column-stack LRU hits (the big ts/gid/val "
    "arrays — the expensive reuse)")
_COLSTACK_MISSES = registry.counter(
    "scan_colstack_cache_misses_total",
    "range-independent column-stack LRU misses")
_INCR_REMERGE = registry.counter(
    "scan_incremental_remerge_total",
    "segments re-merged from tier-2-resident parts with only the "
    "missing SSTs fetched (the post-flush path)")

# ---- [scan.mesh] telemetry (docs/parallel.md) ------------------------------
_MESH_ROUNDS = registry.counter(
    "scan_mesh_rounds_total",
    "window rounds dispatched onto the 2-D scan mesh")
_MESH_PARTS = registry.counter(
    "scan_mesh_parts_total",
    "per-segment run parts produced by the on-mesh segmented combine")
_MESH_PART_CELLS = registry.counter(
    "scan_mesh_part_cells_total",
    "aggregate grid cells downloaded from the mesh (run parts + top-k "
    "winner slices) — the per-chip combine egress the top-k pushdown "
    "bounds at O(k x buckets x aggs) per run")
_MESH_SCORE_CELLS = registry.counter(
    "scan_mesh_score_cells_total",
    "per-group score/has cells downloaded by the top-k mesh path "
    "(O(groups), never O(groups x buckets))")
_MESH_TOPK = registry.counter(
    "scan_mesh_topk_total",
    "top-k queries served by the device-scored, winner-sliced mesh "
    "path")
# every way a round/plan declines the mesh, so an operator can tell a
# misconfigured mesh from unsupported data (mirrors
# scan_decode_fallback_total's discipline)
MESH_FALLBACK_REASONS = (
    "merge_impl",    # non-host_perm merge layouts keep the legacy path
    "sum_overlap",   # a run's windows share a (group, bucket) sum cell
    "count_bound",   # time_axis x capacity would overflow f32 counts
    "grid_budget",   # round's transient grid exceeds max_grid_bytes
    "lo_range",      # a window's bucket offset exceeds the query grid
    "run_misaligned",  # a run's windows disagree on their first bucket
    "mesh_error",    # a round dispatch raised (lost shard / XLA error)
    "topk_by",       # ranking agg not selection-exact (count/sum/avg)
    "topk_router",   # near-data agents cover segments: no global score
    "topk_decode",   # device-decode parts can't join device scoring
    "topk_budget",   # two-phase window pinning exceeds the cache budget
    "additive_topk",  # an additive score add was not provably exact
    "mesh_decode_budget",  # a fused-decode round exceeds upload/grid caps
)
_MESH_FALLBACKS = registry.counter(
    "scan_mesh_fallback_total",
    "mesh scans that left their preferred route, by reason: topk_* "
    "reasons downgrade the egress-bounded winner-sliced top-k to "
    "FULL-WIDTH MESH parts (still on the mesh); every other reason "
    "re-runs that round on the single-chip kernel — the declared "
    "failure seams (docs/parallel.md)")
_MESH_FALLBACK_CHILDREN = {r: _MESH_FALLBACKS.labels(reason=r)
                           for r in MESH_FALLBACK_REASONS}
_MESH_AXIS_DEVICES = {
    a: registry.gauge(
        "scan_mesh_axis_devices",
        "devices per scan-mesh axis (0 = mesh off)").labels(axis=a)
    for a in ("time", "series")
}


def note_mesh_fallback(reason: str) -> None:
    child = _MESH_FALLBACK_CHILDREN.get(reason)
    if child is None:  # unknown reasons still count, labeled verbatim
        child = _MESH_FALLBACKS.labels(reason=reason)
        _MESH_FALLBACK_CHILDREN[reason] = child
    child.inc()
    trace_add(f"mesh_fallback_{reason}", 1)


def _stack_counters(key: tuple):
    # the two entry families have different hit economics: conflating
    # them would report ~50% on varied-range workloads even when the
    # expensive column reuse is perfect
    if key and key[0] == "colstack":
        return _COLSTACK_HITS, _COLSTACK_MISSES
    return _STACK_HITS, _STACK_MISSES


def _timed_stage(stage: str):
    """Decorator: attribute a function's wall time to a plan stage —
    both the cumulative registry histogram and (when a request trace is
    ambient; runtimes.run copies the context onto pool threads) the
    per-query trace profile."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                dt = time.perf_counter() - t0
                _STAGE_SECONDS[stage].observe(dt)
                trace_add(f"stage_{stage}_ms", dt * 1e3)
        return wrapper
    return deco


def plan_stage_snapshot() -> dict:
    """Cumulative per-stage numbers; diff two snapshots to attribute a
    query's time (bench.py's cold-path profile)."""
    out = {}
    for s in _PLAN_STAGES:
        h = _STAGE_SECONDS[s]
        out[f"{s}_s"] = round(h.sum, 6)
        out[f"{s}_calls"] = h.count
    for s, c in _STAGE_ROWS.items():
        out[f"{s}_rows"] = int(c.value)
    for s, c in _STAGE_BYTES.items():
        out[f"{s}_bytes"] = int(c.value)
    from horaedb_tpu.storage import pipeline as pipeline_mod

    stalls = pipeline_mod.stall_counts()
    for s in pipeline_mod.PIPELINE_STAGES:
        h = pipeline_mod.STAGE_SECONDS[s]
        out[f"pipeline_{s}_s"] = round(h.sum, 6)
        out[f"pipeline_{s}_calls"] = h.count
        out[f"pipeline_stalls_{s}"] = stalls[s]
        # rows/bytes too: bench A/Bs diff decoded-window bytes against
        # the device path's encoded-bytes-uploaded (config 16)
        out[f"pipeline_{s}_rows"] = int(pipeline_mod.STAGE_ROWS[s].value)
        out[f"pipeline_{s}_bytes"] = int(
            pipeline_mod.STAGE_BYTES[s].value)
    return out
# segment tables held in memory at once by _prefetch_tables (bounds BOTH
# the row-scan and aggregate paths — including compaction's scan);
# fallback when scan.prefetch_segments is 0/unset
_PREFETCH_SEGMENTS = 4
# rows -> bytes conversion for the legacy cache_max_rows knob: a typical
# engine window is ~4 int32/f32 columns (16B) plus the memo allowance
_CACHE_BYTES_PER_ROW = 32
# fused replay plans kept per reader (weakref-only entries; see
# ParquetReader._replay_cache)
_REPLAY_SLOTS = 8

# [scan.decode] modes (validated at reader open; docs/example.toml)
DECODE_MODES = ("auto", "device", "host")


class _MeshFallback(Exception):
    """A mesh round declined dispatch for a counted reason — the
    caller re-runs it on the single-chip kernel (the declared mesh
    failure seam, docs/parallel.md)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


# prep sentinel marking a deferred fused-decode plan in a mesh round's
# item list (host windows carry a real prep tuple, DeviceParts None)
_DECODE_PREP = object()

# guards every window's memo put: memo stores run on worker-pool
# threads, and the byte accounting must not drift (a lost increment
# would let real HBM exceed the scan cache's charged allowance)
_MEMO_LOCK = threading.Lock()


_IOTA_CACHE: dict = {}


def _iota(cap: int) -> np.ndarray:
    """Cached arange per (pow2-bounded, so few distinct) capacity — the
    validity compare runs per window per query and rebuilding the iota
    was measurable on the cold path.  Callers must not mutate.  Benign
    under races: colliding threads store identical arrays."""
    a = _IOTA_CACHE.get(cap)
    if a is None:
        a = np.arange(cap)
        _IOTA_CACHE[cap] = a
    return a


def _memo_store(w, key, value, nbytes: int) -> None:
    """Byte-bounded per-window memo put.  The scan cache charges each
    window MEMO_SLOTS * (capacity*4 + 128) bytes of memo allowance
    (scan_cache.windows_nbytes); this store keeps the REAL bytes held by
    memo values under that allowance — raising one without the other
    would let actual HBM/RAM use exceed the configured cache budget
    (e.g. a dev_cols entry is 12 bytes/row, three "slots" worth).
    A same-key put loses to the entry already stored (identical
    computation by a concurrent query) so bytes are only ever ADDED for
    distinct keys — no overwrite double-count."""
    from horaedb_tpu.storage.scan_cache import MEMO_SLOTS

    budget = MEMO_SLOTS * (w.capacity * 4 + 128)
    if nbytes > budget:
        # an entry larger than the whole allowance (e.g. partial grids
        # for a huge group count) must not bust the accounting — callers
        # just recompute next time
        return
    with _MEMO_LOCK:
        if key in w.memo:
            return
        if len(w.memo) >= MEMO_SLOTS or w.memo_bytes + nbytes > budget:
            w.memo.clear()
            w.memo_bytes = 0
        w.memo[key] = value
        w.memo_bytes += nbytes


@dataclass
class ScanRequest:
    """(ref: storage.rs:65-70)"""

    range: TimeRange
    predicate: Optional[filter_ops.Predicate] = None
    # indexes into the FULL storage schema (user columns + builtins)
    projections: Optional[list[int]] = None


@dataclass
class AggregateSpec:
    """Downsample pushdown: GROUP BY group_col, time(bucket) computed on
    device straight from the merge output — no Arrow materialization and
    no host re-encode on the north-star query path."""

    group_col: str
    ts_col: str
    value_col: str
    range_start: int  # host-time of bucket 0
    bucket_ms: int
    num_buckets: int
    # which aggregates to compute (canonicalized; count always rides
    # along — combining and finalize key on it)
    which: tuple = downsample_ops.ALL_AGGS

    def __post_init__(self):
        self.which = tuple(sorted(set(self.which)))


@dataclass
class SegmentPlan:
    segment_start: int
    ssts: list[SstFile]
    columns: list[str]


@dataclass
class ScanPlan:
    segments: list[SegmentPlan]
    mode: UpdateMode
    predicate: Optional[filter_ops.Predicate]
    keep_builtin: bool
    # pyarrow expression pushed into the Parquet reads (PK-only subtree
    # of `predicate`); the full predicate still applies post-merge
    pushdown: object = None
    # canonical string of the pushed subtree (scan-cache identity)
    pushdown_key: str = ""
    # flattened conjunction of the same pushed subtree for the
    # stats-pruned decode path (None: shape not prunable, use pushdown)
    prune_leaves: Optional[list] = None
    # True when the pushed subtree IS the whole predicate (every leaf a
    # PK leaf in an And shape): the read already filtered exactly these
    # rows, so post-merge re-evaluation is provably a no-op and the
    # window paths skip it (PK leaves cannot interact with last-value
    # dedup; value-column leaves — which can — force this False)
    pushed_complete: bool = False
    # compaction scans set this False: their input SST sets are deleted
    # right after, so caching them only evicts hot query entries
    use_cache: bool = True
    # which worker pool (common.runtimes) carries this plan's CPU work —
    # compaction plans use "compact" so rewrites queue behind each other
    # instead of in front of serving scans (ref: storage.rs:91-104)
    pool: str = "sst"
    # the request's time range (race re-resolution must honor it: a
    # fresh SST in the same segment but outside the requested range
    # must not leak rows into the results)
    range: Optional[TimeRange] = None
    # set by _cached_windows when it routes this plan through the scan
    # pipeline (pipeline_on() AND the has-store-I/O probe passed); the
    # device stage reads it to decide whether aggregation rounds
    # overlap the window feed — one decision per scan, both layers
    # agree (an all-tier-2-resident scan overlapping device rounds
    # with decode measurably LOSES on low-core hosts, same contention
    # as the fetch/decode stages)
    pipeline_active: bool = False
    # set by aggregate_segments when this plan is eligible for the
    # fused device-decode dispatch ([scan.decode]; ops/device_decode.py):
    # the decode stage uploads eligible EncodedSegments' raw encoded
    # buffers and fuses filter + merge-dedup + bucket-aggregate into
    # one jitted program, emitting finished per-segment parts instead
    # of host windows.  None = host decode (row scans, the control)
    decode_spec: Optional["AggregateSpec"] = None
    # set alongside decode_spec on [scan.mesh] plans: the decode stage
    # PLANS the fused dispatch (ops/device_decode.plan_dispatch) but
    # defers the upload — DecodePlans ride the windows lists into the
    # mesh pump, which batches compatible plans into per-round sharded
    # decode programs (_run_mesh_decode_round).  False = each eligible
    # segment uploads and dispatches standalone at decode time
    decode_defer: bool = False
    # set when aggregate_segments routes this plan onto the 2-D scan
    # mesh ([scan.mesh]): window rounds aggregate with the device
    # kernel even where the numpy twin would normally win (CPU
    # backend), so mesh rounds and their per-round fallbacks share one
    # rounding schedule and grids stay byte-identical within a query
    force_xla_agg: bool = False


class ParquetReader:
    """Builds and executes per-segment merge-scan plans
    (ref: ParquetReader, read.rs:407-494)."""

    def __init__(self, store: ObjectStore, root_path: str,
                 schema: StorageSchema, config: StorageConfig,
                 segment_duration_ms: int, runtimes=None):
        self.store = store
        self.root_path = root_path
        self.schema = schema
        self.config = config
        self.segment_duration_ms = segment_duration_ms
        self.runtimes = runtimes
        # optional async callback (segment_start) -> current SstFiles:
        # set by CloudObjectStorage so a STREAMED segment can survive a
        # compaction race mid-segment (see _stream_window_batches) —
        # bulk segments read everything before yielding, so the outer
        # replan covers them
        self.resolve_segment_ssts = None
        from horaedb_tpu.storage.scan_cache import ScanCache

        cache_bytes = (config.scan.cache_max_bytes
                       or config.scan.cache_max_rows * _CACHE_BYTES_PER_ROW)
        # public: consumers that bypass the scan cache (chunked-mode
        # engine LRU) size their own caches off the same budget
        self.cache_budget_bytes = cache_bytes
        self.scan_cache = ScanCache(cache_bytes)
        # flush-stack LRU: stacked (B, cap) aggregation inputs reused by
        # repeat queries over cached windows.  Separately byte-accounted
        # (stacks are far larger than the per-window memo allowance) and
        # LRU-evicted so a changed round composition can't pin dead HBM.
        import threading
        from collections import OrderedDict

        self._stack_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._stack_cache_hits = 0
        self._stack_cache_misses = 0
        # fused replay plans: a completed fused aggregate records its
        # round composition (stack keys + window identities, weakrefs
        # only — no HBM pinned) so an identical repeat query re-runs
        # init -> N accumulates -> finalize in ONE pool dispatch,
        # skipping per-segment prep/memo/np.unique entirely.  Any
        # eviction or SST-set change invalidates by identity check.
        self._replay_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        self._replay_hits = 0
        self._replay_misses = 0
        # tiny device constants (num_buckets, bucket_ms) memoized so a
        # fully-cached query issues literally ZERO host->device
        # transfers — even scalar uploads pay tunnel latency
        self._scalar_cache: dict = {}
        self._stack_cache_bytes = 0
        # live bytes of device-resident mesh top-k score state (the
        # mesh_state ledger account's pull gauge; event-loop owned)
        self._mesh_state_bytes = 0
        # Under the default host_perm merge, windows live in HOST RAM and
        # the stacks ARE the HBM working set — they get the full budget.
        # (In device_sort A/B mode windows also occupy HBM, so worst
        # case there is 2x the configured budget; see ScanConfig.)
        self._stack_cache_max = cache_bytes
        self._stack_cache_lock = threading.Lock()
        # tier 2: host-RAM per-SST encoded parts under the HBM windows
        # cache — an HBM miss rebuilds from host memory, and a changed
        # SST set re-merges incrementally (only missing SSTs fetched).
        # Also owns the per-SST sidecar-missing negative memo.
        from horaedb_tpu.storage.encoded_cache import EncodedSegmentCache

        self.encoded_cache = EncodedSegmentCache(
            config.scan.cache.tier2_max_bytes,
            write_through=config.scan.cache.write_through)
        # combine mode validated at open, not first query (bad TOML
        # must fail the server's boot, not a dashboard's first scan)
        ensure(config.scan.combine.mode in combine_mod.COMBINE_MODES,
               f"unknown [scan.combine] mode "
               f"{config.scan.combine.mode!r}; expected one of "
               f"{combine_mod.COMBINE_MODES}")
        # decode mode validated at open too: bad TOML fails the boot,
        # not a dashboard's first cold scan
        ensure(config.scan.decode.mode in DECODE_MODES,
               f"unknown [scan.decode] mode "
               f"{config.scan.decode.mode!r}; expected one of "
               f"{DECODE_MODES}")
        # conflicting mode COMBINATIONS fail at open too (the PR 9
        # bad-mode precedent): decode.mode="device" under the legacy
        # 1-D segment mesh would decline EVERY query with a counted
        # fallback — a standing misconfiguration, not a data property
        ensure(not (config.scan.decode.mode == "device"
                    and config.scan.mesh_devices > 0),
               '[scan.decode] mode="device" cannot run on the legacy '
               "1-D segment mesh ([scan] mesh_devices > 0): the fused "
               "decode dispatch targets the default device or the 2-D "
               "[scan.mesh] rounds — change the decode mode or the "
               "mesh config")
        # delta-summation tier: per-segment aggregate partials keyed by
        # the segment's exact SST set (event-loop owned, like the scan
        # cache) — narrowed/refined dashboard ranges recompute only
        # delta segments (storage/combine.py PartsMemo)
        self.parts_memo = combine_mod.PartsMemo(
            config.scan.combine.memo_max_bytes)
        # high-water of pipeline in-flight host bytes observed by this
        # reader's scans (pipeline.PipelineBudget; /stats "pipeline")
        self._pipeline_high_water = 0
        # near-data routing ([scanagent]): a ScanRouter attached here
        # sends covered segments' aggregate scans to their store-shard
        # agents and folds the returned partials through the normal
        # combine (scanagent/client.py); None = the direct-scan control
        self.scan_router = None
        self.mesh = None
        self._mesh_agg_fns: dict = {}
        self._mesh_merge_fns: dict = {}
        if config.scan.mesh_devices > 0:
            from horaedb_tpu.parallel import segment_mesh

            self.mesh = segment_mesh(config.scan.mesh_devices)
        # the 2-D (time, series) scan mesh ([scan.mesh]): segments
        # shard along `time` (plan-order slot admission), group blocks
        # along `series`, segmented-reduction combine on the mesh —
        # off reproduces the single-chip path exactly (the chaos
        # suite's bit-identity control)
        self.scan_mesh = None
        self._mesh_run_fns: dict = {}
        if config.scan.mesh.enabled:
            ensure(config.scan.mesh_devices == 0,
                   "[scan] mesh_devices and [scan.mesh] are mutually "
                   "exclusive — the 2-D mesh supersedes the legacy "
                   "1-D segment mesh")
            from horaedb_tpu.parallel import scan_mesh as build_scan_mesh

            self.scan_mesh = build_scan_mesh(config.scan.mesh.time,
                                             config.scan.mesh.series)
            _MESH_AXIS_DEVICES["time"].set(
                int(self.scan_mesh.shape["time"]))
            _MESH_AXIS_DEVICES["series"].set(
                int(self.scan_mesh.shape["series"]))
        # memory plane: every reader-owned byte budget registers a
        # ledger account (common/memledger.py) tagged with its
        # configured budget; close() deregisters so /debug/memory never
        # serves phantom tables.  Anchored weakly on the reader — the
        # ledger keeps nothing alive.  The pipeline module's process
        # account (pipeline_inflight) must exist the moment a reader
        # does, not at the first pipelined scan:
        from horaedb_tpu.storage import pipeline as _pipeline  # noqa: F401
        self._mem_accounts = [
            # RESIDENT bytes, not the LRU's charged bytes: the LRU
            # charges a worst-case per-window memo ALLOWANCE (budget
            # semantics — resident can never exceed the budget), but
            # the ledger must report what is actually allocated or
            # unattributed goes negative by the unmaterialized slack
            memledger.register(
                f"scan_cache:{root_path}",
                lambda r: r._scan_cache_resident_bytes(), anchor=self,
                kind="scan_cache", budget=cache_bytes, owner=root_path),
            # stacks are jnp arrays: host RAM on the CPU backend, HBM
            # on accelerators — there they are NOT host RSS (they show
            # under memory_device_bytes) and must not be subtracted
            # from it, or unattributed goes negative by the stack size
            memledger.register(
                f"stack_cache:{root_path}",
                lambda r: r._stack_cache_bytes, anchor=self,
                kind="stack_cache", budget=self._stack_cache_max,
                owner=root_path,
                host=jax.default_backend() == "cpu"),
            memledger.register(
                f"encoded_cache:{root_path}",
                lambda r: r.encoded_cache.total_bytes, anchor=self,
                kind="encoded_cache",
                budget=config.scan.cache.tier2_max_bytes,
                owner=root_path),
            memledger.register(
                f"parts_memo:{root_path}",
                lambda r: r.parts_memo.lru.total_bytes, anchor=self,
                kind="parts_memo",
                budget=config.scan.combine.memo_max_bytes,
                owner=root_path),
            # device-resident mesh top-k score state (selection or
            # compensated additive planes) held for the two-pass
            # ranking's duration; decode round stacks ride the
            # stack_cache account above
            memledger.register(
                f"mesh_state:{root_path}",
                lambda r: r._mesh_state_bytes, anchor=self,
                kind="mesh_state",
                budget=config.scan.mesh.max_grid_bytes,
                owner=root_path,
                host=jax.default_backend() == "cpu"),
        ]

    def close(self) -> None:
        """Release every reader-owned cache tier and deregister ledger
        accounts: a closed table holds ZERO attributable bytes (the
        clear-on-close gauge discipline — scan_cache_bytes{tier=} and
        the ledger's account gauges must read 0 afterwards)."""
        self.drop_hbm_state()
        self.scan_cache.clear()
        self.encoded_cache.clear()
        self.parts_memo.lru.clear()
        self._scalar_cache.clear()
        # compiled mesh programs (host-window AND fused-decode): their
        # executables pin device constant buffers; a closed table keeps
        # none
        self._mesh_run_fns.clear()
        if self.scan_mesh is not None:
            # clear-on-close gauge discipline: a closed table must not
            # report a phantom mesh (last-writer semantics: the gauges
            # are process-global, like every axis-shaped gauge here)
            _MESH_AXIS_DEVICES["time"].set(0)
            _MESH_AXIS_DEVICES["series"].set(0)
        for acct in self._mem_accounts:
            memledger.deregister(acct)
        self._mem_accounts = []
        # device-plane clear-on-close: compile/dispatch/transfer
        # families and the per-device high-water marks are process
        # -global like the mesh gauges above — a closed table leaves
        # them zeroed/absent (last-writer semantics)
        deviceprof.profiler.clear()
        memledger.reset_device_high_water()

    def _scan_cache_resident_bytes(self) -> int:
        """Actual bytes the tier-1 cache holds: column buffers at
        their allocated (capacity-padded) widths plus MATERIALIZED
        memo bytes — the ledger's pull gauge.  Differs from
        scan_cache.total_bytes, which charges the worst-case memo
        allowance up front (eviction must bound the budget; the
        ledger must report residency).  Event-loop owned, like the
        cache itself."""
        total = 0
        for windows in self.scan_cache.values():
            for w in windows:
                total += sum(int(c.dtype.itemsize) * w.capacity
                             for c in w.columns.values())
                total += int(w.memo_bytes)
        return total

    def _mem_delta_marks(self) -> Optional[list]:
        """Per-trace memory attribution: snapshot this reader's cache
        balances at scan start; _mem_delta_attribute() records the
        deltas as mem_account_delta_<kind> trace counters — a cold
        scan's trace shows WHICH tier its resident bytes landed in.
        Reads the caches' CHARGED totals (integer reads — this runs
        twice per traced query, so the sampler-only resident-bytes
        walk has no place here).  None (no ambient trace / ledger
        disabled) skips the bookwork."""
        if not memledger.enabled or active_trace() is None:
            return None
        return [("scan_cache", self.scan_cache.total_bytes),
                ("stack_cache", self._stack_cache_bytes),
                ("encoded_cache", self.encoded_cache.total_bytes),
                ("parts_memo", self.parts_memo.lru.total_bytes)]

    def _mem_delta_attribute(self, marks: Optional[list]) -> None:
        if not marks:
            return
        now = dict(self._mem_delta_marks() or ())
        for kind, before in marks:
            delta = now.get(kind, before) - before
            if delta:
                trace_add(f"mem_account_delta_{kind}", delta)

    # ---- plan construction -------------------------------------------------

    def build_plan(self, ssts: list[SstFile], request: ScanRequest,
                   keep_builtin: bool = False,
                   use_cache: bool = True, pool: str = "sst") -> ScanPlan:
        columns = plan_columns(self.schema, request.projections)

        by_segment: dict[int, list[SstFile]] = {}
        for f in ssts:
            by_segment.setdefault(
                segment_of(f, self.segment_duration_ms), []).append(f)
        segments = [
            SegmentPlan(segment_start=seg, ssts=sorted(files, key=lambda f: f.id),
                        columns=columns)
            for seg, files in sorted(by_segment.items())
        ]
        pushdown = None
        pushdown_key = ""
        allowed = set(self.schema.primary_key_names)
        if request.predicate is not None:
            pushdown, pushdown_key = filter_ops.to_arrow_expression_with_key(
                request.predicate, allowed)
        prune_leaves, pushed_complete = parquet_io.conjunct_leaves_ex(
            request.predicate, allowed)
        return ScanPlan(segments=segments, mode=self.schema.update_mode,
                        predicate=request.predicate, keep_builtin=keep_builtin,
                        pushdown=pushdown, pushdown_key=pushdown_key,
                        prune_leaves=prune_leaves,
                        pushed_complete=pushed_complete,
                        use_cache=use_cache, pool=pool, range=request.range)

    # ---- execution ---------------------------------------------------------

    async def execute(self, plan: ScanPlan) -> AsyncIterator[pa.RecordBatch]:
        marks = self._mem_delta_marks()
        seg_iter = self.execute_segments(plan)
        try:
            async for _seg_start, batch in seg_iter:
                if batch is not None:
                    yield batch
        finally:
            # an abandoned consumer must drain the pipeline NOW, not
            # at GC-time async-gen finalization
            await seg_iter.aclose()
            self._mem_delta_attribute(marks)

    async def execute_segments(self, plan: ScanPlan):
        """Like execute(), but yields (segment_start, batch_or_None) —
        callers that must retry after a concurrent compaction (see
        CloudObjectStorage.scan) track completed segments by start time.
        A segment may yield SEVERAL batches (one per merge window) so
        large segments never re-materialize whole on the host, and ends
        with an explicit (segment_start, None) completion marker — only
        that marker makes the segment retry-safe to skip."""
        if plan.mode is not UpdateMode.OVERWRITE:
            # host (Append) path: uncached streaming merge.  Segments
            # over the stream threshold merge window-by-window so the
            # host bound holds for Append tables too (chunked-data
            # tables are typically the largest).
            # aclose the feed DETERMINISTICALLY on any consumer
            # exception/abandonment — otherwise its primed prefetch task
            # only dies at GC time, possibly after the caller has
            # already replanned and started a new scan
            feed = self._segment_feed(plan, plan.segments)
            try:
                async for seg, is_streamed, table, read_s in feed:
                    # cooperative deadline checkpoint: an expired query
                    # aborts between segments, not after a full scan
                    deadline_checkpoint()
                    async for out in self._append_segment(
                            seg, is_streamed, table, read_s, plan):
                        yield out
            finally:
                await feed.aclose()
            return

        windows_iter = self._cached_windows(plan)
        try:
            async for seg, windows, read_s in windows_iter:
                elapsed = 0.0  # decode work only — yields suspend into
                for w in windows:  # the consumer, not scan time
                    # per-window deadline checkpoint (the merge loop's
                    # cooperative cancellation point)
                    deadline_checkpoint()
                    t0 = time.perf_counter()
                    part = await self._run_pool(
                        plan.pool, self._window_to_arrow, w,
                        list(seg.columns), plan)
                    if part is not None and part.num_rows:
                        part = self._strip_builtin(part, plan)
                    elapsed += time.perf_counter() - t0
                    if part is not None and part.num_rows:
                        _ROWS_SCANNED.inc(part.num_rows)
                        yield seg.segment_start, part
                _SCAN_LATENCY.observe(read_s + elapsed)
                # completion marker: consumers mark the segment done now
                yield seg.segment_start, None
        finally:
            await windows_iter.aclose()

    async def _append_segment(self, seg, is_streamed: bool, table,
                              read_s: float, plan: ScanPlan):
        """One Append-mode segment's host merge, streamed or bulk.
        Yields (segment_start, batch) parts then the completion marker."""
        if is_streamed:
            spent = 0.0
            async for batch in self._stream_window_batches(
                    seg, plan, strict_no_replay=True):
                deadline_checkpoint()
                t0 = time.perf_counter()
                part = await self._run_pool(
                    plan.pool, self._merge_segment_table,
                    pa.Table.from_batches([batch]), seg, plan)
                spent += time.perf_counter() - t0
                if part is not None and part.num_rows:
                    _ROWS_SCANNED.inc(part.num_rows)
                    yield seg.segment_start, part
            _SCAN_LATENCY.observe(spent)
            yield seg.segment_start, None  # completion marker
            return
        t0 = time.perf_counter()
        batch = await self._run_pool(
            plan.pool, self._merge_segment_table, table, seg, plan)
        _SCAN_LATENCY.observe(read_s + (time.perf_counter() - t0))
        if batch is not None and batch.num_rows:
            _ROWS_SCANNED.inc(batch.num_rows)
            yield seg.segment_start, batch
        yield seg.segment_start, None  # completion marker

    def _cache_key(self, seg: SegmentPlan, plan: ScanPlan):
        from horaedb_tpu.storage.scan_cache import segment_cache_key

        # A pushdown changes WHICH rows were read pre-merge, so the
        # canonical key of the PUSHED subtree (complete, unlike pyarrow
        # expression str() which elides long isin lists) is part of the
        # cached merge output's identity.  Predicates differing only in
        # their value-column parts share one entry; with no pushdown the
        # read is full and one entry serves every predicate shape.
        return segment_cache_key(
            seg.segment_start, (f.id for f in seg.ssts),
            tuple(seg.columns) + (plan.pushdown_key,))

    # segments whose merges are dispatched but not yet synced: overlaps
    # device merge compute with the NEXT segments' host decode/encode
    _MERGE_LOOKAHEAD = 2

    async def _cached_windows(self, plan: ScanPlan):
        """Per segment, yield (seg, post-merge DeviceBatch windows,
        read_seconds) — from the HBM-resident cache when the segment's
        (SST set, columns, pushdown) is unchanged, else by reading +
        merging (and populating the cache unless the plan opted out).

        Merge programs for up to _MERGE_LOOKAHEAD upcoming segments are
        dispatched before the current segment's run counts are synced,
        so the device pipeline never drains while the host prepares the
        next segment."""
        from collections import deque

        cached: dict[int, list] = {}
        to_read: list[SegmentPlan] = []
        for seg in plan.segments:
            windows = (self.scan_cache.get(self._cache_key(seg, plan))
                       if plan.use_cache else None)
            if windows is None:
                to_read.append(seg)
            else:
                cached[id(seg)] = windows
        if self.mesh is not None:
            mesh_iter = self._cached_windows_mesh(plan, cached, to_read)
            try:
                async for out in mesh_iter:
                    yield out
            finally:
                await mesh_iter.aclose()
            return
        if self.pipeline_on() and self._pipeline_has_io(plan, to_read):
            plan.pipeline_active = True
            pipe_iter = self._cached_windows_pipelined(plan, cached,
                                                       to_read)
            try:
                async for out in pipe_iter:
                    yield out
            finally:
                await pipe_iter.aclose()
            return

        # the shared _segment_feed owns the streamed/bulk split and the
        # prefetch priming; pump() adds the merge-dispatch LOOKAHEAD on
        # top (bulk merges dispatch ahead of the yield position so the
        # device pipeline never drains).  Encodes stay SERIAL on the
        # pump: running lookahead encodes as concurrent tasks was
        # measured a net loss on low-core hosts (GIL + memory-bandwidth
        # contention with the prefetch deserializes outweighed the
        # overlap; 2-core A/B showed cold +36%).
        feed = self._segment_feed(plan, to_read).__aiter__()
        pending: "deque[tuple[SegmentPlan, str, list, float]]" = deque()
        exhausted = False

        async def pump() -> None:
            nonlocal exhausted
            try:
                fseg, is_streamed, table, read_s = await feed.__anext__()
            except StopAsyncIteration:
                exhausted = True
                return
            if is_streamed:
                # a marker only: the actual streaming happens when this
                # segment reaches the yield position
                pending.append((fseg, "stream", [], 0.0))
                return
            dispatched: list = []
            if table.num_rows:
                dispatched = await self._run_pool(
                    plan.pool, self._dispatch_segment_table, table, plan)
            pending.append((fseg, "bulk", dispatched, read_s))

        try:
            for seg in plan.segments:
                # cooperative deadline checkpoint between segments: a
                # query that ran out of budget stops reading/merging
                # instead of finishing a doomed scan
                deadline_checkpoint()
                if id(seg) in cached:
                    yield seg, cached[id(seg)], 0.0
                    continue
                while len(pending) <= self._MERGE_LOOKAHEAD and not exhausted:
                    await pump()
                read_seg, kind, dispatched, read_s = pending.popleft()
                assert read_seg is seg
                if kind == "stream":
                    dispatched, read_s = \
                        await self._read_streamed_dispatched(seg, plan)
                windows = await self._run_pool(
                    plan.pool, self._finalize_windows, dispatched)
                if plan.use_cache and self._cacheable_windows(windows):
                    self.scan_cache.put(self._cache_key(seg, plan),
                                        windows)
                yield seg, windows, read_s
        finally:
            await feed.aclose()

    def pipeline_on(self) -> bool:
        """Whether OVERWRITE cold scans run through the bounded
        producer/consumer pipeline (storage/pipeline.py).  Meshed scans
        keep their own round scheduler; [scan.pipeline] enabled = false
        reproduces the pre-pipeline pump exactly."""
        return self.config.scan.pipeline.enabled and self.mesh is None

    def _pipeline_has_io(self, plan: ScanPlan, to_read: list) -> bool:
        """Whether pipelining this scan can pay for itself: the
        pipeline exists to hide object-store latency behind decode and
        device work, so a scan whose every bulk segment is already
        tier-2 resident (zero store I/O — the post-flush / warm-cache
        regime) runs the sequential pump instead.  On low-core hosts
        the stages' concurrency measurably INFLATES the same CPU work
        (GIL + XLA intra-op contention: tier2-cold 56-segment A/B
        showed encode_merge 2.8x and device rounds 2.3x slower wall
        under overlap, 0.7x end to end) — with no latency left to hide
        there is nothing to win it back.  Streamed segments read the
        store incrementally and any non-resident bulk segment fetches
        it, so either makes the pipeline worthwhile.  The probe is the
        cache's stats-free peek — it must not bump LRU recency or
        hit/miss telemetry (the real reads that follow do that)."""
        if not self._sidecar_plan_ok(plan):
            return bool(to_read)  # every read is a store read
        leaf_cols = {lf.column for lf in plan.prune_leaves or []}

        def resident(seg: SegmentPlan) -> bool:
            if self.encoded_cache.is_assembly_failed(
                    frozenset(f.id for f in seg.ssts)):
                return False
            want = set(seg.columns) | leaf_cols
            return all(self.encoded_cache.peek(f.id, want)
                       for f in seg.ssts)

        return any(self._stream_segment(seg) or not resident(seg)
                   for seg in to_read)

    async def _cached_windows_pipelined(self, plan: ScanPlan,
                                        cached: dict, to_read: list):
        """Pipelined twin of the pump below: fetch and decode/merge run
        as background stages (storage/pipeline.py) while this consumer
        — the device stage's doorstep — yields segments in plan order.
        Same outputs, same cache puts, same error positions; only the
        schedule differs (tests/test_pipeline.py asserts
        bit-identically)."""
        from horaedb_tpu.storage.pipeline import ScanPipeline

        pipe = ScanPipeline(self, plan, to_read)
        try:
            for seg in plan.segments:
                # cooperative deadline checkpoint between segments,
                # same position as the pump's
                deadline_checkpoint()
                if id(seg) in cached:
                    yield seg, cached[id(seg)], 0.0
                    continue
                got, windows, read_s = await pipe.next_segment()
                assert got is seg
                if plan.use_cache and self._cacheable_windows(windows):
                    self.scan_cache.put(self._cache_key(seg, plan),
                                        windows)
                yield seg, windows, read_s
        finally:
            # deterministic teardown: cancels the stage tasks and
            # AWAITS them, draining any in-flight pool job before the
            # caller proceeds to table/engine teardown
            await pipe.aclose()

    async def _read_streamed_dispatched(self, seg: SegmentPlan,
                                        plan: ScanPlan):
        """One streamed segment's windows, dispatched (pre-finalize):
        sidecar stream first, whole-segment parquet-stream fallback.
        Returns (dispatched, read_seconds) — shared by the sequential
        pump and the pipeline's decode stage so the two cannot
        drift."""
        t0 = time.perf_counter()
        dispatched: list = []
        es_iter = await self._open_sidecar_stream(seg, plan)
        if es_iter is not None:
            try:
                async for es in es_iter:
                    dispatched.extend(await self._run_pool(
                        plan.pool, self._dispatch_segment_table, es,
                        plan))
            except Exception as exc:  # noqa: BLE001
                # nothing has been yielded for this segment yet
                # (windows buffer here), so a clean whole-segment
                # fallback is safe
                logger.warning(
                    "sidecar stream failed for segment %s (%s); "
                    "falling back to parquet", seg.segment_start, exc)
                dispatched = []
                es_iter = None
        if es_iter is None:
            async for batch in self._stream_window_batches(seg, plan):
                dispatched.extend(await self._run_pool(
                    plan.pool, self._dispatch_merged_windows, batch))
        return dispatched, time.perf_counter() - t0

    def _dispatch_segment_table(self, table, plan: "ScanPlan" = None
                                ) -> list:
        """Pool-side encode+merge dispatch of one bulk segment's read
        result (pa.Table or sidecar.EncodedSegment) — the ONE body
        shared by the sequential pump and the pipeline's decode stage
        so the two cannot drift.

        Device-decode-routed plans (plan.decode_spec set) short-circuit
        here: the segment's ENCODED buffers upload raw and one fused
        program does filter + merge-dedup + bucket-aggregate
        (ops/device_decode.py) — the decode pool dispatch shrinks to a
        memcpy-shaped pad + upload.  Per-segment ineligibility falls
        back to the host path with its reason counted, resolving any
        deferred leaf mask first."""
        if isinstance(table, sidecar.EncodedSegment):
            es = table
            if plan is not None and plan.decode_spec is not None:
                disp = self._dispatch_device_decode(es, plan)
                if disp is not None:
                    return disp
                es = sidecar.apply_leaves_host(es)
            elif es.pending_leaves is not None:
                es = sidecar.apply_leaves_host(es)
            return self._dispatch_encoded_windows(es)
        if plan is not None and plan.decode_spec is not None:
            device_decode.note_fallback("parquet")
        batch = table.combine_chunks().to_batches()[0]
        return self._dispatch_merged_windows(batch)

    def _dispatch_device_decode(self, es: "sidecar.EncodedSegment",
                                plan: "ScanPlan") -> Optional[list]:
        """Dispatch one EncodedSegment through the fused device-decode
        program; None (with the reason counted) when this segment's
        layout can't ride it — the caller falls back to host decode."""
        spec = plan.decode_spec
        leaves = (es.pending_leaves if es.pending_leaves is not None
                  else [])
        got = device_decode.plan_dispatch(
            es, spec, pk_names=self._pk_names_in(list(es.names)),
            seq_name=SEQ_COLUMN_NAME, leaves=leaves,
            max_bytes=self.config.scan.decode.max_upload_bytes,
            width=self._window_grid_width(spec),
            pad_capacity=encode.pad_capacity)
        if isinstance(got, str):
            device_decode.note_fallback(got)
            return None
        if isinstance(got, device_decode.DecodePlan) \
                and not plan.decode_defer:
            got = device_decode.execute_plan(got)
        return [got]

    def _decode_segment_windows(self, table, plan: ScanPlan) -> list:
        """The pipeline's decode stage body, one pool dispatch per
        segment: encode + k-way merge + window planning + finalize
        fused — no intermediate hand-back to the event loop between
        them.  `table` is a pa.Table or sidecar.EncodedSegment."""
        return self._finalize_windows(
            self._dispatch_segment_table(table, plan))

    async def _cached_windows_mesh(self, plan: ScanPlan, cached: dict,
                                   to_read: list):
        """Mesh twin of _cached_windows' read path: merge windows from
        DIFFERENT segments batch into rounds of mesh-size
        sharded_merge_dedup programs (shard-local sort/dedup, no
        collectives), so every query shape drives all chips — the
        reference's UnionExec-parallel merge (storage.rs:342-368) with
        segments as the shard axis.  Segments still yield in plan order,
        each one only after all its windows' rounds have run."""
        from horaedb_tpu.parallel.scan import shard_leading_axis

        n_dev = self.mesh.devices.size
        # pinned for the whole scan: window prep (sort normalization)
        # and the round kernel must use the SAME impl even if
        # set_merge_impl flips mid-scan
        scan_host_perm = merge_ops.merge_impl() == "host_perm"
        feed = self._segment_feed(plan, to_read).__aiter__()
        # buffer entries: [seg, windows(list, filled in round order),
        #                  outstanding window count, read_s]
        buffer: list[list] = []
        pending: list[tuple[list, dict, int, int, dict]] = []

        def run_round(round_items: list) -> None:
            cap = max(it[3] for it in round_items)
            names = list(round_items[0][1].keys())
            stacks = {}
            for name in names:
                rows = np.zeros(
                    (n_dev, cap), dtype=round_items[0][1][name].dtype)
                for d, (_e, cols, n_win, wcap, _enc) in enumerate(round_items):
                    rows[d, :wcap] = cols[name]
                stacks[name] = shard_leading_axis(self.mesh, rows)
            n_valid = np.zeros(n_dev, dtype=np.int32)
            for d, it in enumerate(round_items):
                n_valid[d] = it[2]
            pk_names = self._pk_names_in(names)
            value_names = [nm for nm in names
                           if nm not in pk_names and nm != SEQ_COLUMN_NAME]
            # only the device_sort A/B mode reaches here (host_perm
            # windows arrive pre-merged and skip the rounds entirely)
            fn = self._mesh_merge_fns.get(len(pk_names))
            if fn is None:
                from horaedb_tpu.parallel.scan import sharded_merge_dedup

                fn = sharded_merge_dedup(self.mesh, num_pks=len(pk_names))
                self._mesh_merge_fns[len(pk_names)] = fn
            out_pks, out_seq, out_vals, _valid, num_runs = fn(
                tuple(stacks[nm] for nm in pk_names),
                stacks[SEQ_COLUMN_NAME],
                tuple(stacks[nm] for nm in value_names),
                shard_leading_axis(self.mesh, n_valid))
            runs_host = np.asarray(num_runs)
            for d, (entry, _cols, _n, _wcap, enc) in enumerate(round_items):
                columns = {
                    **{nm: a[d] for nm, a in zip(pk_names, out_pks)},
                    SEQ_COLUMN_NAME: out_seq[d],
                    **{nm: a[d] for nm, a in zip(value_names, out_vals)},
                }
                entry[1].append(encode.DeviceBatch(
                    columns=columns, encodings=enc,
                    n_valid=int(runs_host[d]), capacity=cap))
                entry[2] -= 1

        async def enqueue(entry: list, descs: list) -> None:
            if scan_host_perm:
                # windows arrive merged+deduped on host (_prepare does
                # the k-way merge): no shard merge rounds to run — the
                # mesh engages at the AGGREGATE stage, where stacked
                # windows shard over chips with psum combines
                for cols, n_win, wcap, enc in descs:
                    entry[1].append(encode.DeviceBatch(
                        columns=cols, encodings=enc, n_valid=n_win,
                        capacity=wcap))
                return
            entry[2] += len(descs)
            for cols, n_win, wcap, enc in descs:
                pending.append((entry, cols, n_win, wcap, enc))
            while len(pending) >= n_dev:
                await self._run_pool(plan.pool, run_round, pending[:n_dev])
                del pending[:n_dev]

        try:
            for seg in plan.segments:
                deadline_checkpoint()  # between-segment cancellation point
                if id(seg) in cached:
                    buffer.append([seg, cached[id(seg)], 0, 0.0])
                else:
                    fseg, is_streamed, table, read_s = await feed.__anext__()
                    assert fseg is seg
                    if is_streamed:
                        # feed rounds window-by-window: at most a round's
                        # worth of un-merged host windows is ever resident
                        t0 = time.perf_counter()
                        entry = [seg, [], 0, 0.0]
                        buffer.append(entry)
                        es_iter = await self._open_sidecar_stream(seg,
                                                                  plan)
                        if es_iter is not None:
                            try:
                                async for es in es_iter:
                                    await enqueue(entry, await
                                                  self._run_pool(
                                        plan.pool,
                                        self._prepare_encoded_windows,
                                        es, scan_host_perm))
                            except Exception as exc:  # noqa: BLE001
                                # windows already enqueued into mesh
                                # rounds can't be retracted: fail to the
                                # outer replan (same as a mid-stream
                                # compaction race), not a silent retry
                                raise Error(
                                    "sidecar stream failed mid-mesh-"
                                    f"round: {exc}") from exc
                        else:
                            async for batch in self._stream_window_batches(
                                    seg, plan):
                                await enqueue(entry, await self._run_pool(
                                    plan.pool,
                                    self._prepare_merge_windows, batch,
                                    scan_host_perm))
                        entry[3] = time.perf_counter() - t0
                    else:
                        descs = []
                        if table.num_rows:
                            def encode_windows(tbl=table):
                                if isinstance(tbl, sidecar.EncodedSegment):
                                    return self._prepare_encoded_windows(
                                        tbl, scan_host_perm)
                                batch = tbl.combine_chunks().to_batches()[0]
                                return self._prepare_merge_windows(
                                    batch, scan_host_perm)

                            descs = await self._run_pool(plan.pool,
                                                         encode_windows)
                        entry = [seg, [], 0, read_s]
                        buffer.append(entry)
                        await enqueue(entry, descs)
                while buffer and buffer[0][2] == 0:
                    seg0, windows, _outstanding, read_s0 = buffer.pop(0)
                    if plan.use_cache and id(seg0) not in cached:
                        self.scan_cache.put(self._cache_key(seg0, plan),
                                            windows)
                    yield seg0, windows, read_s0
            if pending:
                # tail round: pad with empty windows bound to a discard
                # entry so real segments' window lists stay exact
                discard = [None, [], len(pending) - n_dev, 0.0]
                _e, cols0, _n, wcap0, enc0 = pending[-1]
                tail = list(pending)
                while len(tail) < n_dev:
                    tail.append((discard, cols0, 0, wcap0, enc0))
                await self._run_pool(plan.pool, run_round, tail)
                pending.clear()
            while buffer:
                seg0, windows, outstanding, read_s0 = buffer.pop(0)
                assert outstanding == 0
                if plan.use_cache and id(seg0) not in cached:
                    self.scan_cache.put(self._cache_key(seg0, plan),
                                        windows)
                yield seg0, windows, read_s0

        finally:
            # deterministic cleanup of the feed's primed prefetch task
            await feed.aclose()

    async def _segment_feed(self, plan: ScanPlan,
                            segments: list[SegmentPlan]):
        """Shared streamed/bulk split: yields (seg, is_streamed,
        table_or_None, read_s) in segment order.  The bulk prefetch
        pipeline is primed immediately so object-store reads overlap any
        streamed segment processed before them."""
        streamed = {id(s) for s in segments if self._stream_segment(s)}
        bulk = [s for s in segments if id(s) not in streamed]
        read_iter = self._prefetch_tables(bulk, plan).__aiter__()
        primed: Optional[asyncio.Task] = (
            asyncio.ensure_future(read_iter.__anext__()) if bulk else None)
        try:
            for seg in segments:
                if id(seg) in streamed:
                    yield seg, True, None, 0.0
                    continue
                if primed is not None:
                    step, primed = primed, None
                    read_seg, table, read_s = await step
                else:
                    read_seg, table, read_s = await read_iter.__anext__()
                assert read_seg is seg
                yield seg, False, table, read_s
        finally:
            if primed is not None:
                primed.cancel()
                try:
                    await primed
                except (asyncio.CancelledError, Exception):
                    pass
            # deterministic teardown of the prefetch generator: its
            # eagerly-created SST read tasks must be cancelled NOW, not
            # at GC-time finalization
            await read_iter.aclose()

    async def _prefetch_tables(self, segments: list[SegmentPlan],
                               plan: ScanPlan):
        """Bounded segment prefetch shared by the row and aggregate paths:
        object-store reads overlap downstream device work while at most
        scan.prefetch_segments tables are in memory (the permit is
        released only after the consumer finishes with a segment).
        Yields (segment, table, read_seconds)."""
        sem = asyncio.Semaphore(
            max(1, self.config.scan.prefetch_segments
                or _PREFETCH_SEGMENTS))

        async def read(seg: SegmentPlan):
            await sem.acquire()
            return await self._read_segment_any(seg, plan)

        tasks = [asyncio.create_task(read(seg)) for seg in segments]
        try:
            for seg, task in zip(segments, tasks):
                table, read_s = await task
                try:
                    yield seg, table, read_s
                finally:
                    sem.release()
        finally:
            for task in tasks:
                task.cancel()
            # drain, don't just cancel: a read whose pool job (sidecar
            # deserialize, parquet decode) is mid-flight only finishes
            # after the job does — awaiting here keeps cancelled-scan
            # teardown from racing in-flight decode work (the PR 3
            # discipline), and retrieves failed reads' exceptions
            await asyncio.gather(*tasks, return_exceptions=True)

    async def _read_segment_any(self, seg: SegmentPlan, plan: ScanPlan,
                                runner=None):
        """One bulk segment's cold read — tier-2/sidecar serve resident
        parts and fetch only missing SSTs; parquet is the fallback —
        with read-stage attribution.  Returns (table, read_seconds);
        `table` is a pa.Table or sidecar.EncodedSegment.  Shared by the
        sequential prefetch and the pipeline's fetch stage (which
        bounds the CPU-side deserialize concurrency via `runner`)."""
        t0 = time.perf_counter()
        table = None
        stage = "sidecar_read"
        if self._sidecar_plan_ok(plan):
            table = await self._read_segment_encoded(seg, plan,
                                                     runner=runner)
        if table is None:
            stage = "parquet_read"
            table = await self._read_segment_table(
                seg, plan.pushdown, pool=plan.pool,
                leaves=plan.prune_leaves)
        read_s = time.perf_counter() - t0
        _STAGE_SECONDS[stage].observe(read_s)
        _STAGE_ROWS[stage].inc(table.num_rows)
        _STAGE_BYTES[stage].inc(table.nbytes)
        trace_add(f"stage_{stage}_ms", read_s * 1e3)
        trace_add(f"stage_{stage}_rows", table.num_rows)
        trace_add(f"stage_{stage}_bytes", table.nbytes)
        # tenant scan-byte budget: charged where the stage bytes are
        # attributed, observed at the deadline checkpoints
        charge_scan_bytes(table.nbytes)
        return table, read_s

    def _sidecar_plan_ok(self, plan: ScanPlan) -> bool:
        """Whether this plan may serve bulk segments from device-layout
        sidecars: OVERWRITE merge only (Append's BytesMerge needs exact
        Arrow bytes), and the pushdown — when present — must have a leaf
        -conjunction form the sidecar path can evaluate host-side."""
        if not self.config.scan.use_sidecar:
            return False
        if plan.mode is not UpdateMode.OVERWRITE:
            return False
        return plan.pushdown is None or plan.prune_leaves is not None

    def _resident_segment_parts(self, seg: SegmentPlan,
                                plan: ScanPlan) -> Optional[list]:
        """Event-loop-side tier-2 residency probe: every SST's encoded
        part for this plan's column set, straight from the cache — or
        None when any part is missing (or a negative memo says the
        sidecar path is doomed), in which case the full fetch path
        decides between store reads and the parquet fallback.

        The pipeline's fetch stage uses this so ALL-RESIDENT segments
        never dispatch a pool job from fetch: on a 2-core host, N
        in-flight fetches each racing an assemble job starved the
        decode/device stages the consumer was actually waiting on
        (priority inversion measured as tier2-cold 0.74x vs the
        sequential pump) — resident segments instead assemble inside
        the decode stage's one serial pool dispatch."""
        if not self._sidecar_plan_ok(plan):
            return None
        if any(self.encoded_cache.is_missing(f.id) for f in seg.ssts):
            return None
        if self.encoded_cache.is_assembly_failed(
                frozenset(f.id for f in seg.ssts)):
            return None
        want = set(seg.columns) | {lf.column
                                   for lf in plan.prune_leaves or []}
        parts = []
        for f in seg.ssts:
            part = self.encoded_cache.get(f.id, want)
            if part is None:
                return None
            parts.append(part)
        return parts

    def _assemble_resident_segment(self, seg: SegmentPlan, parts: list,
                                   plan: ScanPlan
                                   ) -> Optional[sidecar.EncodedSegment]:
        """Pool-side assemble of tier-2-resident parts with the same
        stage attribution the fetch path gives an assembled segment.
        None = assembly failed (the CALLER memoizes the composition on
        the event loop and falls back to parquet — the cache's negative
        memos are loop-owned)."""
        t0 = time.perf_counter()
        defer = plan.decode_spec is not None
        try:
            es = sidecar.assemble_parts(
                parts, list(seg.columns),
                None if defer else plan.prune_leaves)
        except Exception as exc:  # noqa: BLE001 — cache read only
            logger.warning("sidecar assembly raised for segment %s: %s",
                           seg.segment_start, exc)
            es = None
        if es is None:
            return None
        if defer:
            es.pending_leaves = list(plan.prune_leaves or [])
        read_s = time.perf_counter() - t0
        _STAGE_SECONDS["sidecar_read"].observe(read_s)
        _STAGE_ROWS["sidecar_read"].inc(es.n)
        _STAGE_BYTES["sidecar_read"].inc(es.nbytes)
        trace_add("stage_sidecar_read_ms", read_s * 1e3)
        trace_add("stage_sidecar_read_rows", es.n)
        trace_add("stage_sidecar_read_bytes", es.nbytes)
        charge_scan_bytes(es.nbytes)
        return es

    async def _read_segment_encoded(self, seg: SegmentPlan, plan: ScanPlan,
                                    runner=None
                                    ) -> Optional[sidecar.EncodedSegment]:
        """Segment read that never touches parquet: serve each SST's
        encoded part from tier 2 when resident, fetch only the missing
        SSTs' sidecars, and assemble filtered, concatenated encoded
        columns.  This is the incremental re-merge: after a flush (one
        new small SST in an otherwise-unchanged segment) only that SST
        crosses the wire — and with write-through admission not even
        that.  None (→ parquet fallback) when any SST lacks a valid
        sidecar.  `runner` overrides the pool dispatch for the
        CPU-bound deserialize/assemble steps (the pipeline bounds
        fetch-stage CPU concurrency through it)."""
        if any(self.encoded_cache.is_missing(f.id) for f in seg.ssts):
            return None  # known-missing sidecar: skip the GETs entirely
        seg_ids = frozenset(f.id for f in seg.ssts)
        if self.encoded_cache.is_assembly_failed(seg_ids):
            return None  # this exact composition is known unassemblable
        leaves = plan.prune_leaves
        want = set(seg.columns) | {lf.column for lf in leaves or []}

        if runner is None:
            def runner(fn, *args):  # CPU-bound deserialize off the loop
                return self._run_pool(plan.pool, fn, *args)

        parts: list = [None] * len(seg.ssts)
        fetch: list[tuple[int, SstFile]] = []
        for i, f in enumerate(seg.ssts):
            part = self.encoded_cache.get(f.id, want)
            if part is None:
                fetch.append((i, f))
            else:
                parts[i] = part
        if fetch and len(fetch) < len(seg.ssts):
            _INCR_REMERGE.inc()
        # per-SST GETs overlap WITHIN the segment (one gather), and the
        # prefetch pipeline overlaps segments on top
        got = await asyncio.gather(*(
            sidecar.load_sst_encoded(
                self.store, sidecar.sidecar_path(self.root_path, f.id),
                want, leaves, runner=runner)
            for _i, f in fetch), return_exceptions=True)
        for (i, f), res in zip(fetch, got):
            if isinstance(res, NotFoundError):
                # permanent for this id (SSTs/ids are immutable and the
                # sidecar is written before the SST becomes visible):
                # memo the miss so later cold scans of this segment
                # don't re-fetch the siblings' blobs just to fall back
                self.encoded_cache.mark_missing(f.id)
                return None
            if isinstance(res, BaseException):
                # transient store failure: the sidecar is a cache — fall
                # back to the authoritative parquet, never fail the scan
                logger.warning("sidecar fetch failed for sst %s: %s",
                               f.id, res)
                return None
            if res is None:
                self.encoded_cache.mark_missing(f.id)
                logger.warning("invalid sidecar for sst %s; using "
                               "parquet", f.id)
                return None
            parts[i] = res
            # only COMPLETE parts are cacheable: a block-pruned load
            # returned a row subset tied to this plan's leaves
            if res[1] == f.meta.num_rows:
                self.encoded_cache.put(f.id, res[0], res[1])
        # device-decode plans DEFER the exact leaf mask: the fused
        # dispatch evaluates the conjunction in encoded space on
        # device, so the host never pays the mask + per-column
        # compaction (ops/device_decode.py; a per-segment fallback
        # resolves pending leaves host-side)
        defer = plan.decode_spec is not None
        try:
            es = await runner(sidecar.assemble_parts, parts,
                              list(seg.columns),
                              None if defer else leaves)
        except Exception as exc:  # noqa: BLE001 — cache read only
            # a part that parses but is internally inconsistent can blow
            # up deep in eval/concat; the contract is fallback, not
            # failure
            logger.warning("sidecar assembly raised for segment %s: %s",
                           seg.segment_start, exc)
            es = None
        if es is not None and defer:
            es.pending_leaves = list(leaves or [])
        if es is None:
            # cross-SST assembly failed (e.g. an irreconcilable column
            # type across parts).  Do NOT memoize the member SSTs as
            # sidecar-missing — each part deserialized fine on its own,
            # and the same ids may assemble cleanly in other
            # compositions (the old whole-set memo permanently disabled
            # every valid sibling).  Memoize the COMPOSITION instead,
            # so repeat cold scans of this unchanged segment skip the
            # doomed sidecar GETs; any flush/compaction changes the set
            # and retries naturally.
            self.encoded_cache.mark_assembly_failed(seg_ids)
            logger.warning("sidecar assembly failed for segment %s; "
                           "using parquet", seg.segment_start)
        return es

    async def _open_sidecar_stream(self, seg: SegmentPlan, plan: ScanPlan):
        """Streamed-segment windows straight from sidecars: PK-value
        -range windows planned from per-block stats, each window loaded
        via the pruned loader with synthetic range leaves (see
        sidecar.SstStreamSession / plan_stream_windows) — no parquet
        two-pass, no Arrow.  Returns an async generator of
        EncodedSegments, or None when any SST lacks a plannable sidecar
        (the parquet streamer serves the segment instead)."""
        if not self._sidecar_plan_ok(plan):
            return None
        if any(self.encoded_cache.is_missing(f.id) for f in seg.ssts):
            return None
        leaves = plan.prune_leaves or []
        want = set(seg.columns) | {lf.column for lf in leaves}

        def runner(fn, *args):
            return self._run_pool(plan.pool, fn, *args)

        got = await asyncio.gather(*(
            sidecar.SstStreamSession.open(
                self.store, sidecar.sidecar_path(self.root_path, f.id),
                want, runner=runner)
            for f in seg.ssts), return_exceptions=True)
        sessions = []
        for f, res in zip(seg.ssts, got):
            if isinstance(res, NotFoundError) or res is None:
                # permanent per immutable id — same memo as the bulk
                # path, so later streamed scans skip the probes
                self.encoded_cache.mark_missing(f.id)
                return None
            if isinstance(res, BaseException):
                logger.warning("sidecar stream open failed for sst "
                               "%s: %s", f.id, res)
                return None
            sessions.append(res)
        planned = await sidecar.plan_stream_windows(
            sessions, self._pk_names_in(list(seg.columns)),
            self.config.scan.max_window_rows)
        if planned is None:
            return None
        part_col, ranges = planned

        async def gen():
            rows = nbytes = 0
            for lo, hi in ranges:
                wleaves = list(leaves)
                if lo is not None:
                    wleaves.append(filter_ops.Ge(part_col, lo))
                if hi is not None:
                    wleaves.append(filter_ops.Lt(part_col, hi))
                parts = await asyncio.gather(*(
                    s.load_window(wleaves) for s in sessions))
                if any(p is None for p in parts):
                    raise Error("sidecar stream window failed")
                # device-decode plans defer the exact window mask to
                # the fused dispatch — the synthetic range leaves keep
                # windows exactly disjoint there, same as the host mask
                defer = plan.decode_spec is not None
                es = await self._run_pool(
                    plan.pool, sidecar.assemble_parts, list(parts),
                    list(seg.columns), None if defer else wleaves)
                if es is None:
                    raise Error("sidecar stream assembly failed")
                if defer:
                    es.pending_leaves = list(wleaves)
                if es.n:
                    rows += es.n
                    nbytes += es.nbytes
                    yield es
            # counters commit only on a COMPLETE stream: a mid-stream
            # failure re-serves the segment via parquet, which would
            # otherwise double-count the already-yielded windows
            _STAGE_ROWS["sidecar_read"].inc(rows)
            _STAGE_BYTES["sidecar_read"].inc(nbytes)
            trace_add("stage_sidecar_read_rows", rows)
            trace_add("stage_sidecar_read_bytes", nbytes)
            charge_scan_bytes(nbytes)

        return gen()

    def drop_hbm_state(self) -> None:
        """Evict everything HBM-RESIDENT that derives from cached
        windows — round stacks, fused-replay plans, per-window memos
        (device column copies, aggregation grids) — while KEEPING the
        post-merge windows themselves, which live in host RAM under the
        default host_perm merge.  This is the 'HBM evicted' state the
        bench ladder measures: the next query re-stacks/re-uploads from
        host windows instead of re-reading and re-merging.  (Tests and
        benchmarks only; production eviction is the LRUs' own.)"""
        with self._stack_cache_lock:
            # includes the mesh decode round stacks — the fused path's
            # uploaded (time, capacity) column matrices share this LRU
            self._stack_cache.clear()
            self._stack_cache_bytes = 0
        self._replay_cache.clear()
        # tiny device scalars (num_buckets, bucket_ms) are HBM too on
        # accelerators; re-uploading them is part of 'HBM evicted'
        self._scalar_cache.clear()
        with _MEMO_LOCK:
            for windows in self.scan_cache.values():
                for w in windows:
                    w.memo.clear()
                    w.memo_bytes = 0

    def cache_stats(self) -> dict:
        """The /stats cache section: every reader-owned cache tier's
        residency and effectiveness, one dict per tier."""
        return {
            "scan_cache": {
                "entries": len(self.scan_cache),
                "bytes": self.scan_cache.total_bytes,
                "max_bytes": self.scan_cache.max_bytes,
                "hits": self.scan_cache.hits,
                "misses": self.scan_cache.misses,
            },
            "encoded_cache": self.encoded_cache.stats(),
            "parts_memo": self.parts_memo.stats(),
            "pipeline": {
                "enabled": self.pipeline_on(),
                "depth": self.config.scan.pipeline.depth,
                "inflight_bytes": self.config.scan.pipeline.inflight_bytes,
                "high_water_bytes": self._pipeline_high_water,
            },
            "decode": {
                "mode": self.config.scan.decode.mode,
                "resolved": self._decode_mode(),
                "max_upload_bytes":
                    self.config.scan.decode.max_upload_bytes,
            },
            "mesh": self.mesh_stats(),
            "stack_cache": {
                "entries": len(self._stack_cache),
                "bytes": self._stack_cache_bytes,
                "max_bytes": self._stack_cache_max,
                "hits": self._stack_cache_hits,
                "misses": self._stack_cache_misses,
            },
        }

    def mesh_stats(self) -> dict:
        """The /stats mesh section: axis shape, round/part volume, the
        egress counter the top-k bound is asserted against, and every
        counted fallback reason (docs/parallel.md)."""
        from horaedb_tpu.storage import pipeline as pipeline_mod

        shape = None
        if self.scan_mesh is not None:
            shape = {"time": int(self.scan_mesh.shape["time"]),
                     "series": int(self.scan_mesh.shape["series"])}
        return {
            "enabled": self.scan_mesh is not None,
            "shape": shape,
            "rounds": int(_MESH_ROUNDS.value),
            "parts": int(_MESH_PARTS.value),
            "part_cells": int(_MESH_PART_CELLS.value),
            "score_cells": int(_MESH_SCORE_CELLS.value),
            "topk_served": int(_MESH_TOPK.value),
            "fallbacks": {r: int(c.value)
                          for r, c in _MESH_FALLBACK_CHILDREN.items()
                          if c.value},
            "stalls": pipeline_mod.mesh_stall_counts(),
        }

    async def _read_segment_table(self, seg: SegmentPlan,
                                  pushdown=None,
                                  pool: str = "sst",
                                  leaves: Optional[list] = None) -> pa.Table:
        tables = await asyncio.gather(*(
            parquet_io.read_sst(self.store, sst_path(self.root_path, f.id),
                                columns=seg.columns, filters=pushdown,
                                runtimes=self.runtimes, pool=pool,
                                leaves=leaves,
                                # manifest size: big SSTs stream into a
                                # file-backed mmap instead of buffering
                                # whole in RSS (get_stream)
                                size_hint=f.meta.size)
            for f in seg.ssts
        ))
        return pa.concat_tables(tables)

    async def _run_pool(self, pool: str, fn, *args, **kwargs):
        """CPU work (parquet codec, host merge, numpy prep, device
        dispatch/sync) runs on a named worker pool, never on the event
        loop (ref: dedicated runtimes, storage.rs:91-104)."""
        return await parquet_io._run(self.runtimes, pool, fn, *args,
                                     **kwargs)

    def _strip_builtin(self, batch: Optional[pa.RecordBatch],
                       plan: ScanPlan) -> Optional[pa.RecordBatch]:
        """Drop builtin columns unless the plan keeps them — the single
        home for this rule across every row path."""
        if batch is None or plan.keep_builtin:
            return batch
        keep = [c for c in batch.schema.names
                if not self.schema.is_builtin_name(c)]
        return batch.select(keep)

    def _combine_and_strip(self, parts: list[pa.RecordBatch],
                           plan: ScanPlan) -> Optional[pa.RecordBatch]:
        """Concatenate per-window outputs and drop builtin columns unless
        the plan keeps them."""
        if not parts:
            return None
        batch = (parts[0] if len(parts) == 1 else
                 pa.Table.from_batches(parts).combine_chunks().to_batches()[0])
        return self._strip_builtin(batch, plan)

    def _merge_segment_table(self, table: pa.Table, seg: SegmentPlan,
                             plan: ScanPlan) -> Optional[pa.RecordBatch]:
        """Host (Append/BytesMerge) merge of one segment's table, with
        the same PK-range windowing as the device path when the segment
        exceeds the window budget (sort/merge work stays bounded)."""
        if table.num_rows == 0:
            return None
        batch = table.combine_chunks().to_batches()[0]
        window = self.config.scan.max_window_rows
        if batch.num_rows <= window:
            return self._strip_builtin(self._merge_on_host(batch, plan),
                                       plan)
        pk1 = batch.column(batch.schema.names.index(
            self._pk_names_in(batch.schema.names)[0]))
        # dense value-order ranks straight from Arrow (same comparator the
        # merge sort uses); cross-window order then follows value order
        ranks = np.asarray(pa.compute.rank(pk1, sort_keys="ascending",
                                           tiebreaker="dense"))
        parts = []
        for sel in _plan_pk_windows(ranks, window):
            part = self._merge_on_host(batch.take(pa.array(sel)), plan)
            if part is not None and part.num_rows:
                parts.append(part)
        return self._combine_and_strip(parts, plan)

    def _pk_names_in(self, columns: list[str]) -> list[str]:
        """PK names present, in SCHEMA order — the merge must sort by the
        declared key order even when a projection reordered columns."""
        present = set(columns)
        return [n for n in self.schema.primary_key_names if n in present]

    def _stream_segment(self, seg: SegmentPlan) -> bool:
        """True when this segment should be read window-by-window instead
        of fully materialized: manifest row count over the row threshold,
        OR stored byte size over the byte threshold — a wide-schema
        segment can be host-RAM-huge long before it hits the row knob."""
        row_thresh = self.config.scan.stream_read_min_rows
        if row_thresh <= 0:
            return False  # 0 disables streaming entirely (stable contract)
        rows = sum(f.meta.num_rows for f in seg.ssts)
        if rows <= self.config.scan.max_window_rows:
            # everything fits one window: streaming would pay the pass-1
            # scan and still materialize the same single window
            return False
        if rows > row_thresh:
            return True
        byte_thresh = self.config.scan.stream_read_min_bytes
        return byte_thresh > 0 and sum(
            f.meta.size for f in seg.ssts) > byte_thresh

    async def _stream_window_batches(self, seg: SegmentPlan, plan: ScanPlan,
                                     strict_no_replay: bool = False):
        """Streamed segment read (the reference's pull-based batch
        streaming, read.rs:346-385, re-shaped for device windows): pass 1
        streams ONE PK column's row groups to plan value-range windows of
        <= max_window_rows; pass 2 reads each window's rows via parquet
        predicate pushdown.  Host materialization is bounded by the
        window budget (plus file buffers on non-filesystem stores), not
        the segment size.  Yields one Arrow batch per window, PK-range
        ascending, each encoded WINDOW-LOCALLY downstream."""
        import pyarrow.compute as pc

        # one source per SST: local stores mmap, remote stores download
        # the object ONCE and serve both passes and every window from it
        sources = await asyncio.gather(*(
            parquet_io.open_sst_source(self.store,
                                       sst_path(self.root_path, f.id))
            for f in seg.ssts))

        pk_names = self._pk_names_in(seg.columns)
        values = counts = None
        part_col = pk_names[-1]
        for nm in pk_names:
            per_sst = await asyncio.gather(*(
                self._run_pool(plan.pool, src.value_counts, nm)
                for src in sources))
            values, counts = parquet_io.merge_value_counts(per_sst)
            if len(values) == 0:
                return  # segment is empty
            if len(values) > 1:
                part_col = nm
                break
            # constant column: windowing on it cannot bound anything
        window = self.config.scan.max_window_rows
        ranges: list[tuple] = []
        start = acc = 0
        for i, c in enumerate(counts):
            if acc and acc + int(c) > window:
                ranges.append((values[start], values[i - 1]))
                start, acc = i, 0
            acc += int(c)
        if acc:
            ranges.append((values[start], values[-1]))
        pyval = lambda x: x.item() if hasattr(x, "item") else x
        yielded_any = False
        for lo, hi in ranges:
            # streamed segments can span many windows: check the
            # deadline before paying for each window's pushdown read
            deadline_checkpoint()
            expr = (pc.field(part_col) >= pyval(lo)) \
                & (pc.field(part_col) <= pyval(hi))
            if plan.pushdown is not None:
                expr = expr & plan.pushdown
            refresh = False
            for attempt in range(3):
                try:
                    if refresh:
                        # re-resolution/re-open can themselves race a
                        # second deletion — they live INSIDE the try so
                        # that also consumes an attempt, never escapes
                        fresh = await self.resolve_segment_ssts(
                            seg.segment_start, plan.range)
                        sources = await asyncio.gather(*(
                            parquet_io.open_sst_source(
                                self.store, sst_path(self.root_path, f.id))
                            for f in fresh))
                        refresh = False
                    if not sources:
                        # the whole segment vanished (TTL GC): nothing
                        # left to stream
                        return
                    tables = await asyncio.gather(*(
                        self._run_pool(plan.pool, src.read,
                                       columns=seg.columns, filters=expr)
                        for src in sources))
                    break
                except NotFoundError:
                    # a compaction deleted an input SST mid-segment.
                    # Windows already yielded can't be retracted, so the
                    # OUTER replan would duplicate them — instead
                    # re-resolve this segment's CURRENT SSTs (the
                    # compacted output holds the same rows) and continue
                    # with the remaining value ranges, which partition
                    # rows independently of file boundaries.
                    if self.resolve_segment_ssts is None or attempt == 2:
                        if strict_no_replay and yielded_any:
                            # the CONSUMER already emitted these batches
                            # downstream (Append path): an outer replan
                            # would DUPLICATE them — fail loudly as a
                            # non-retryable error instead.  Buffering
                            # consumers (OVERWRITE/aggregate) pass
                            # strict_no_replay=False and let the replan
                            # recover duplicate-free.
                            raise Error(
                                f"streamed segment {seg.segment_start} "
                                "lost its SSTs mid-stream after retries; "
                                "failing rather than duplicating "
                                "already-emitted rows")
                        raise
                    refresh = True
            tbl = pa.concat_tables(tables)
            if tbl.num_rows:
                yielded_any = True
                yield tbl.combine_chunks().to_batches()[0]

    @_timed_stage("encode_merge")
    def _prepare_merge_windows(self, batch: pa.RecordBatch,
                               host_perm: Optional[bool] = None) -> list:
        """Host half of the merge: encode + PK-window planning + padding,
        WITHOUT dispatching any device program.  Returns
        [(padded host cols, n_win, capacity, encodings)] — the mesh
        round scheduler stacks these onto the shard axis.

        `host_perm` pins the merge-impl decision for a whole scan (the
        caller captures merge_impl() once): window prep and the round
        kernel must agree, or an impl flip mid-scan would hand unsorted
        windows to the sort-free kernel."""
        _STAGE_ROWS["encode_merge"].inc(batch.num_rows)
        dev = encode.encode_batch(batch)
        return self._prepare_windows_dev(dev, list(batch.schema.names),
                                         host_perm)

    @_timed_stage("encode_merge")
    def _prepare_encoded_windows(self, es: "sidecar.EncodedSegment",
                                 host_perm: Optional[bool] = None) -> list:
        """Sidecar twin of _prepare_merge_windows (mesh window prep)."""
        _STAGE_ROWS["encode_merge"].inc(es.n)
        return self._prepare_windows_dev(self._encoded_to_device_batch(es),
                                         list(es.names), host_perm)

    def _prepare_windows_dev(self, dev: encode.DeviceBatch, names: list,
                             host_perm: Optional[bool] = None) -> list:
        pk_names = self._pk_names_in(names)
        ensure(len(pk_names) == self.schema.num_primary_keys,
               "projection lost primary key columns")
        n = dev.n_valid
        window = self.config.scan.max_window_rows
        if n == 0:
            return []
        if host_perm is None:
            host_perm = merge_ops.merge_impl() == "host_perm"
        if host_perm:
            seq_h = np.asarray(dev.columns[SEQ_COLUMN_NAME])[:n]
            seq_ordered = bool(np.all(seq_h[1:] >= seq_h[:-1]))
        host_cols = {name: np.asarray(c)[:n]
                     for name, c in dev.columns.items()}
        if n <= window:
            selections: list[Optional[np.ndarray]] = [None]
        else:
            # partition on the first NON-constant pk (same as the
            # non-mesh path): windowing on a constant column would
            # produce one unbounded window and defeat the HBM budget
            part_name = next(
                (nm for nm in pk_names
                 if host_cols[nm][0] != host_cols[nm][-1]
                 or not bool((host_cols[nm] == host_cols[nm][0]).all())),
                pk_names[0])
            selections = _plan_pk_windows(host_cols[part_name], window)
        if host_perm:
            # same host merge+dedup as _dispatch_merged_windows: the
            # shard round then needs NO merge kernel at all
            return _host_merge_window_descs(dev, host_cols, pk_names,
                                            seq_h, seq_ordered, selections,
                                            n)
        descs = []
        for sel in selections:
            if sel is not None and not len(sel):
                continue
            if sel is None:
                descs.append(({kk: np.asarray(v) for kk, v
                               in dev.columns.items()},
                              n, dev.capacity, dev.encodings))
                continue
            n_win = len(sel)
            cap = encode.pad_capacity(n_win)
            padded = {kk: np.pad(v[sel], (0, cap - n_win))
                      for kk, v in host_cols.items()}
            descs.append((padded, n_win, cap, dev.encodings))
        return descs

    @_timed_stage("encode_merge")
    def _dispatch_merged_windows(self, batch: pa.RecordBatch) -> list:
        """Merge one segment with bounded memory: segments above
        scan.max_window_rows are split into PK-code-range windows, each a
        complete set of PK groups, merged independently in key order
        (windows are PK-ascending, so global order is preserved).  The
        streaming analogue of the reference's pull-based MergeStream
        (SURVEY.md hard part #5).

        Under the default host_perm impl the merge is a host
        permutation-plan + run-keep over the pre-sorted SST runs and the
        windows stay HOST-resident (rows cross to the device only as
        batched stacks in the aggregate path).  Under device_sort the
        original per-window lax.sort programs dispatch WITHOUT syncing;
        _finalize_windows syncs the run counts either way.
        """
        _STAGE_ROWS["encode_merge"].inc(batch.num_rows)
        dev = encode.encode_batch(batch)  # host-resident numpy columns
        return self._dispatch_windows_dev(dev, list(batch.schema.names))

    @staticmethod
    def _encoded_to_device_batch(es: "sidecar.EncodedSegment"
                                 ) -> encode.DeviceBatch:
        """Pad sidecar columns (read-only views) to a static-shape
        capacity — the only prep the already-device-layout data needs."""
        cap = encode.pad_capacity(es.n)
        columns = {}
        for name, arr in es.columns.items():
            padded = np.zeros(cap, dtype=arr.dtype)  # calloc: tail free
            padded[:es.n] = arr
            columns[name] = padded
        return encode.DeviceBatch(columns=columns, encodings=es.encodings,
                                  n_valid=es.n, capacity=cap)

    @_timed_stage("encode_merge")
    def _dispatch_encoded_windows(self, es: "sidecar.EncodedSegment"
                                  ) -> list:
        """Sidecar twin of _dispatch_merged_windows."""
        _STAGE_ROWS["encode_merge"].inc(es.n)
        return self._dispatch_windows_dev(self._encoded_to_device_batch(es),
                                          list(es.names))

    def _dispatch_windows_dev(self, dev: encode.DeviceBatch,
                              names: list) -> list:
        """Post-encode half of the segment merge, shared by the Arrow
        and sidecar reads (see _dispatch_merged_windows for the plan)."""
        pk_names = self._pk_names_in(names)
        ensure(len(pk_names) == self.schema.num_primary_keys,
               "projection lost primary key columns")
        value_names = [n for n in names
                       if n not in pk_names and n != SEQ_COLUMN_NAME]
        n = dev.n_valid
        host_cols = {name: np.asarray(c)[:n] for name, c in dev.columns.items()}

        # sort-operand elision (the variadic sort is the scan's hottest
        # kernel; comparator cost and data movement scale with operands):
        # - PK columns constant across the segment (e.g. a single-metric
        #   table's metric/field ids) can't affect the order — carry them
        #   as values instead of sorting by them;
        # - seq non-decreasing with row index (SSTs are concatenated in
        #   file-id order and seq IS the file id) means the stable PK
        #   sort already leaves the highest-seq row last per run.
        def is_const(a: np.ndarray) -> bool:
            # first!=last shortcuts the full scan for sorted columns
            return len(a) == 0 or (a[0] == a[-1] and bool((a == a[0]).all()))

        sort_pk_names = [nm for nm in pk_names
                         if not is_const(host_cols[nm])]
        if not sort_pk_names:
            sort_pk_names = pk_names[:1]
        carry_names = [nm for nm in pk_names
                       if nm not in sort_pk_names] + value_names
        seq_h = host_cols[SEQ_COLUMN_NAME]
        seq_ordered = bool(n == 0 or np.all(seq_h[1:] >= seq_h[:-1]))

        window = self.config.scan.max_window_rows
        if n <= window:
            selections: list[Optional[np.ndarray]] = [None]
        else:
            # partition on the first NON-constant pk so windows stay
            # meaningfully bounded even when pk 0 is constant
            selections = _plan_pk_windows(host_cols[sort_pk_names[0]], window)

        if merge_ops.merge_impl() == "host_perm":
            # The merge runs ENTIRELY on host: plan the k-way-merge
            # permutation over the pre-sorted SST runs, keep the last
            # row per PK run, and hand out HOST-resident windows.  No
            # per-window device round trips — the device sees rows only
            # as large stacked uploads in the aggregate path, and row
            # scans decode without a device->host fetch (the tunnel's
            # scarce direction).
            return [
                (cols, enc, k, cap)
                for cols, k, cap, enc in _host_merge_window_descs(
                    dev, host_cols, sort_pk_names, seq_h, seq_ordered,
                    selections, n)
            ]

        dispatched = []
        for sel in selections:
            if sel is None:
                # single-window fast path: encode_batch already padded
                padded, n_win, cap = dev.columns, n, dev.capacity
            else:
                sub = {k: v[sel] for k, v in host_cols.items()}
                n_win = len(sel)
                cap = encode.pad_capacity(n_win)
                padded = {k: np.pad(v, (0, cap - n_win))
                          for k, v in sub.items()}
            if n_win == 0:
                continue
            dev_cols = {name: deviceprof.device_put(c)
                        for name, c in padded.items()}
            pks = tuple(dev_cols[name] for name in sort_pk_names)
            seq = dev_cols[SEQ_COLUMN_NAME]
            values = tuple(dev_cols[name] for name in carry_names)
            out_pks, out_seq, out_values, _out_valid, num_runs = \
                merge_ops.merge_dedup_last(pks, seq, values, n_win,
                                           seq_in_row_order=seq_ordered)
            columns = {**{name: a for name, a in zip(sort_pk_names, out_pks)},
                       SEQ_COLUMN_NAME: out_seq,
                       **{name: a for name, a in zip(carry_names, out_values)}}
            dispatched.append((columns, dev.encodings, num_runs, cap))
        return dispatched

    @staticmethod
    def _finalize_windows(dispatched: list) -> list:
        """Sync the dispatched merges' run counts (int() blocks until the
        device finishes) and wrap them as DeviceBatches.  Split from
        dispatch so callers can overlap merge compute across segments.
        Device-decode entries (in-flight fused dispatches) finalize
        into DeviceParts — finished per-segment aggregate partials that
        ride the same windows list."""
        out = []
        for entry in dispatched:
            if isinstance(entry, device_decode.DevicePart):
                out.append(entry)
            elif isinstance(entry, device_decode.DecodePlan):
                # deferred fused decode: the plan rides the windows
                # list into the mesh pump, which batches compatible
                # plans into one sharded per-round program
                out.append(entry)
            elif isinstance(entry, device_decode.DecodeDispatch):
                out.append(entry.finalize())
            else:
                columns, encodings, num_runs, cap = entry
                out.append(encode.DeviceBatch(
                    columns=columns, encodings=encodings,
                    n_valid=int(num_runs), capacity=cap))
        return out

    @staticmethod
    def _cacheable_windows(windows: list) -> bool:
        """Only host-decoded window lists may enter the scan cache:
        DeviceParts are aggregate partials keyed to one spec — serving
        them to a row scan or a different aggregate would be wrong, and
        repeat aggregates are already served structurally by the parts
        memo (storage/combine.py)."""
        return all(isinstance(w, encode.DeviceBatch) for w in windows)

    def _window_to_arrow(self, out_batch: encode.DeviceBatch,
                         out_names: list[str],
                         plan: ScanPlan) -> Optional[pa.RecordBatch]:
        # Predicates apply AFTER dedup: filtering before would break
        # last-value semantics when the predicate touches value columns
        # (a filtered-out newer row must still shadow an older row) —
        # PK-only predicates can't, so a fully-pushed plan skips the
        # re-evaluation (the read already filtered exactly these rows).
        k = out_batch.n_valid
        if plan.predicate is not None and not plan.pushed_complete:
            mask = filter_ops.eval_predicate(plan.predicate, out_batch)
            sel = np.flatnonzero(np.asarray(mask)[:k])
            arrow = encode.decode_to_arrow(out_batch, names=out_names)
            return arrow.take(pa.array(sel))
        return encode.decode_to_arrow(out_batch, names=out_names)

    # ---- aggregate pushdown ------------------------------------------------

    async def execute_aggregate(self, plan: ScanPlan, spec: AggregateSpec
                                ) -> tuple[np.ndarray, dict]:
        """Run the merge + downsample entirely on device, returning
        (group_values, finalized grids) combined across all segments and
        windows.  group_values are decoded host values (e.g. tsids) in
        sorted order; each grid is (len(group_values), num_buckets)."""
        marks = self._mem_delta_marks()
        try:
            if self.fused_aggregate_ok(plan):
                return await self.execute_aggregate_fused(plan, spec)
            # collected per segment and folded in segment order:
            # memo-served segments may yield out of plan order, and the
            # combine fold order is part of the bit-identity contract
            done: dict[int, list] = {}
            async for seg_start, seg_parts in self.aggregate_segments(
                    plan, spec):
                done[seg_start] = seg_parts
            parts = [p for s in sorted(done) for p in done[s]]
            return self.finalize_aggregate(parts, spec)
        finally:
            # cold scans move megabytes into the cache tiers; the trace
            # shows which account they landed in
            self._mem_delta_attribute(marks)

    def router_covers(self, plan: ScanPlan) -> bool:
        """Whether the attached near-data router would serve any of
        this plan's segments.  scan_aggregate consults it ahead of the
        fused gate: the fused accumulator needs every segment's windows
        HOST-resident — exactly the shipped-segment cost the agents
        exist to avoid — so covered plans take the parts path."""
        return (self.scan_router is not None
                and plan.range is not None
                and self.scan_router.covers_any(plan.segments))

    def fused_aggregate_ok(self, plan: Optional[ScanPlan] = None) -> bool:
        """Whether the fused device-accumulated aggregate serves this
        scan (see _fused_agg_ok_base for the structural gates).  An
        explicit `[scan.decode] mode = "device"` outranks it for
        decode-eligible plans: the fused accumulator still pays host
        decode for every window, which is the wall the device-decode
        dispatch removes — forcing fused (HORAEDB_FUSED_AGG=1) still
        wins, so existing coverage keeps its path."""
        if not self._fused_agg_ok_base(plan):
            return False
        import os

        if os.environ.get("HORAEDB_FUSED_AGG", "") == "1":
            return True
        if (plan is not None and self._decode_mode() == "device"
                and self._device_decode_plan_ok(plan, count=False)):
            return False
        return True

    def _fused_agg_ok_base(self, plan: Optional[ScanPlan] = None) -> bool:
        """The fused aggregate's own gates: single-device host_perm
        mode, and by default ACCELERATOR backends only — there,
        device->host is the scarce resource (the per-flush partial
        downloads dominate) and scatters are fast; on XLA-CPU the trade
        inverts — downloads are free and scatter is the slow op, so the
        per-flush host f64 fold wins.  HORAEDB_FUSED_AGG=1/0 forces it
        on/off (tests force it on to cover the fused path on the CPU
        backend).  The mesh path keeps per-round psum combines either
        way.

        When `plan` is given, queries whose estimated row volume exceeds
        the scan-cache budget fall back to the parts path: fused is
        two-phase (all windows collected before the union group space is
        known), so unlike the parts pipeline it pins every window in
        host RAM for the query's duration — the budget is the bound."""
        if self.mesh is not None or merge_ops.merge_impl() != "host_perm":
            return False
        if self.scan_mesh is not None:
            # [scan.mesh] supersedes the fused single-chip accumulator:
            # the mesh's parts path is the one that scales across chips
            return False
        import os

        forced = os.environ.get("HORAEDB_FUSED_AGG", "")
        if forced == "1":  # force wins over the budget gate too
            return True
        if forced == "0":
            return False
        if plan is not None:
            est_rows = sum(f.meta.num_rows
                           for seg in plan.segments for f in seg.ssts)
            if est_rows * _CACHE_BYTES_PER_ROW > self.cache_budget_bytes:
                return False
        import jax

        return jax.default_backend() != "cpu"

    def _decode_mode(self) -> str:
        """Resolved [scan.decode] mode: HORAEDB_DEVICE_DECODE=1/0
        forces device/host over the config (the bench/chaos override
        convention of HORAEDB_FUSED_AGG and friends)."""
        import os

        forced = os.environ.get("HORAEDB_DEVICE_DECODE", "")
        if forced == "1":
            return "device"
        if forced == "0":
            return "host"
        return self.config.scan.decode.mode

    def _device_decode_plan_ok(self, plan: ScanPlan,
                               count: bool = True) -> bool:
        """Plan-level gate for the fused device-decode dispatch
        (ops/device_decode.py) — the decode twin of fused_aggregate_ok.
        Per-reason fallbacks are counted (scan_decode_fallback_total)
        unless `count` is False (the fused gate probes without
        recording, or structural misses would double-count).

        "auto" engages on accelerator backends for plans the fused
        aggregate declines on its own terms (the oversized-cold shape
        whose windows can't pin in RAM anyway); "device" forces the
        dispatch wherever structurally possible; "host" is the
        bit-identity control.  Per-SEGMENT gates (encodings, dtype,
        upload budget) live in _dispatch_device_decode."""
        mode = self._decode_mode()
        note = device_decode.note_fallback if count else (lambda _r: None)
        if mode == "host":
            return False
        if mode == "auto":
            import jax

            if jax.default_backend() == "cpu":
                # host numpy decode measured faster than XLA-CPU device
                # programs on this backend (the host_agg trade)
                return False
            if self._fused_agg_ok_base(plan):
                return False  # fused keeps the warm/replay path
            # auto + the 2-D scan mesh rides the mesh-placed fused
            # decode rounds (plan.decode_defer; _run_mesh_decode_round)
            # — decode shards along the time axis with the aggregation
            # instead of declining here
        if self.mesh is not None:
            note("mesh")
            return False
        if plan.mode is not UpdateMode.OVERWRITE:
            note("append_mode")
            return False
        if plan.predicate is not None and not plan.pushed_complete:
            # value-column leaves interact with last-value dedup and
            # Or/Not shapes have no pushed conjunction — host decode
            # evaluates those post-merge.  Checked BEFORE the sidecar
            # gate: an unpushable predicate also fails that one, and
            # "predicate" is the reason an operator can act on
            note("predicate")
            return False
        if not device_decode.leaf_shape_supported(plan.prune_leaves):
            note("predicate")
            return False
        if not self._sidecar_plan_ok(plan):
            note("no_sidecar")
            return False
        return True

    async def execute_aggregate_fused(self, plan: ScanPlan,
                                      spec: AggregateSpec,
                                      counted: Optional[set] = None):
        """Merge + downsample with a QUERY-GLOBAL device accumulator:
        rounds of stacked windows aggregate and scatter into one
        (groups, buckets) grid set on device; nothing is downloaded
        until the final grids.

        Two-phase by design: all windows are collected first so the
        union group space is known before any round runs (remap targets
        global rows directly).  Host RAM for the collected windows is
        the same rows the parts path would hold across its pipeline;
        the streamed-segment path still bounds per-segment
        materialization.

        Returns (group_values, grids) where grids hold DEVICE float32
        arrays (downloaded lazily by the caller — np.asarray works; the
        device work itself is complete, block_until_ready'd).  `last`
        queries additionally materialize count/last_ts on host for the
        int64 absolute-time conversion."""
        if counted is None:
            counted = set()
        replay_key = None
        if plan.use_cache and self.mesh is None:
            replay_key = self._replay_key(plan, spec)
            entry = self._replay_cache.get(replay_key)
            if entry is not None:
                # segment validation touches the (lock-free, event-loop-
                # owned) scan cache HERE; only the device rounds go to
                # the pool
                grids = None
                if self._replay_segments_valid(entry):
                    grids = await self._run_pool(
                        plan.pool, self._fused_replay, entry, spec)
                if grids is not None:
                    self._replay_cache.move_to_end(replay_key)
                    self._replay_hits += 1
                    _REPLAY_HITS.inc()
                    # `counted` gates ops metrics across race restarts,
                    # exactly like the full path's per-segment gate
                    # replay rows go to their OWN counter — nothing was
                    # read, so feeding rows_scanned/scan_seconds would
                    # skew operator rows/s and latency percentiles
                    fresh = [(s, r) for s, r in entry["seg_rows"]
                             if s not in counted]
                    if fresh:
                        _REPLAY_ROWS.inc(sum(r for _, r in fresh))
                        counted.update(s for s, _ in fresh)
                    values, grids = self._drop_empty_groups_dev(
                        entry["values"], grids)
                    return values, self._fused_last_ts_to_abs(grids, spec)
                self._replay_cache.pop(replay_key, None)
            self._replay_misses += 1
            _REPLAY_MISSES.inc()
        items: list[tuple[int, encode.DeviceBatch, tuple]] = []
        seg_records: list[tuple] = []
        seg_rows: list[tuple] = []
        windows_iter = self._cached_windows(plan)
        try:
            async for seg, windows, read_s in windows_iter:
                s = seg.segment_start
                # `counted` survives compaction-race restarts so a
                # re-scanned segment doesn't double-count ops metrics
                count_metrics = s not in counted

                def prep(ws=windows, s=s, cm=count_metrics):
                    out = []
                    for w in ws:
                        if cm:
                            _ROWS_SCANNED.inc(w.n_valid)
                        pr = self._window_groups(w, spec, plan)
                        if pr is not None:
                            out.append((s, w, pr))
                    return out

                items.extend(await self._run_pool(plan.pool, prep))
                if replay_key is not None:
                    seg_records.append((self._cache_key(seg, plan), tuple(
                        weakref.ref(w) for w in windows)))
                    seg_rows.append((s, sum(w.n_valid for w in windows)))
                if count_metrics:
                    _SCAN_LATENCY.observe(read_s)
                    counted.add(s)
        finally:
            await windows_iter.aclose()
        if not items:
            values, grids = combine_aggregate_parts([], spec.num_buckets,
                                                    which=spec.which)
            return values, grids
        all_values = np.unique(np.concatenate([it[2][0] for it in items]))
        g = len(all_values)
        g_pad = max(8, 1 << (g - 1).bit_length())
        local_ok = all(
            it[1].encodings[spec.ts_col].kind == "offset" for it in items)
        width = self._window_grid_width(spec) if local_ok \
            else spec.num_buckets
        max_w = max(1, self.config.scan.agg_batch_windows)
        space_fp = (g, hash(all_values.tobytes()))
        recorded_rounds: list[tuple] = []

        def build_rounds():
            # lazy: round i+1's stacks build on host while round i's
            # accumulate runs on device (dispatches are async)
            i = 0
            while i < len(items):
                chunk = items[i:i + max_w]
                batch_w = min(max_w, 1 << (len(chunk) - 1).bit_length())
                cap = max(it[1].capacity for it in chunk)
                # the chunk offset `i` disambiguates consecutive rounds
                # of one big segment that share (seg0, batch_w, cap) —
                # without it the stack LRU would overwrite round 1's
                # entry with round 2's and every replay would miss
                stack_key = self._round_stack_key(
                    chunk[0][0], spec, plan, batch_w, cap, g_pad, width,
                    space_fp) + (i,)
                arrays = self._build_round_stacks(
                    chunk, spec, plan, batch_w, cap, g_pad, width,
                    all_values, local_ok, stack_key=stack_key)
                if replay_key is not None:
                    windows = tuple(it[1] for it in chunk)
                    recorded_rounds.append((
                        stack_key,
                        self._col_stack_key(windows, spec, plan, batch_w,
                                            cap),
                        tuple(weakref.ref(w) for w in windows)))
                i += len(chunk)
                yield arrays

        def run_rounds():
            out, t_dev = self._fused_run_device_rounds(
                build_rounds(), spec, g, g_pad, width)
            _STAGE_SECONDS["device_aggregate"].observe(t_dev)
            return out

        grids = await self._run_pool(plan.pool, run_rounds)
        if replay_key is not None:
            self._replay_cache[replay_key] = {
                "segments": seg_records,
                "rounds": recorded_rounds,
                "values": all_values,
                "g": g, "g_pad": g_pad, "width": width,
                "seg_rows": seg_rows,
            }
            self._replay_cache.move_to_end(replay_key)
            while len(self._replay_cache) > _REPLAY_SLOTS:
                self._replay_cache.popitem(last=False)
        all_values, grids = self._drop_empty_groups_dev(all_values, grids)
        return all_values, self._fused_last_ts_to_abs(grids, spec)

    def _replay_key(self, plan: ScanPlan, spec: AggregateSpec) -> tuple:
        """Identity of a fused aggregate over a specific plan: the
        per-segment scan-cache keys (SST ids + columns + pushdown) plus
        the full aggregate spec and predicate.  Any write or compaction
        changes a segment's SST set and therefore the key."""
        seg_keys = tuple(self._cache_key(seg, plan) for seg in plan.segments)
        return (seg_keys, spec.group_col, spec.ts_col, spec.value_col,
                spec.range_start, spec.bucket_ms, spec.num_buckets,
                spec.which,
                filter_ops.canonical_predicate_key(plan.predicate))

    def _replay_segments_valid(self, entry: dict) -> bool:
        """Every segment's scan-cache entry must still hold the exact
        window objects recorded (object identity — a re-read, eviction,
        or compaction breaks it).  Runs on the EVENT LOOP: the scan
        cache is lock-free and event-loop-owned."""
        for key, refs in entry["segments"]:
            ws = self.scan_cache.get(key)
            if (ws is None or len(ws) != len(refs)
                    or any(r() is not w for r, w in zip(refs, ws))):
                return False
        return True

    def _fused_replay(self, entry: dict, spec: AggregateSpec):
        """Re-run a recorded fused aggregate in ONE worker-pool
        dispatch: check every round's stacks are still in the
        (thread-safe) stack LRU — BEFORE any device work — then run the
        accumulate rounds straight from the cached device arrays.
        Returns device grids, or None to fall back to the full path."""
        rounds = []
        for stack_key, col_key, refs in entry["rounds"]:
            ws = tuple(r() for r in refs)
            if any(w is None for w in ws):
                return None
            cols = self._stack_cache_get(col_key, ws)
            small = self._stack_cache_get(stack_key, ws)
            if cols is None or small is None:
                return None
            rounds.append(cols + small)
        out, t_dev = self._fused_run_device_rounds(
            rounds, spec, entry["g"], entry["g_pad"], entry["width"])
        _STAGE_SECONDS["device_aggregate"].observe(t_dev)
        return out

    def _fused_run_device_rounds(self, rounds, spec: AggregateSpec,
                                 g: int, g_pad: int, width: int):
        """The fused aggregate's device sequence, shared by the full
        path and the replay: acc init -> one accumulate per round ->
        finalize -> slice to g -> sync.  `rounds` is any iterable of
        stack tuples (a lazy generator on the full path, so stack
        building overlaps device execution).  Returns (grids, device
        seconds) — device time excludes the caller's stack building,
        which self-reports under stack_build."""
        total = self._dev_scalar(spec.num_buckets)
        bucket_ms = self._dev_scalar(spec.bucket_ms)
        t_dev = 0.0
        t0 = time.perf_counter()
        acc = _fused_acc_init_jit(num_groups=g_pad,
                                  num_buckets=spec.num_buckets,
                                  which=spec.which)
        t_dev += time.perf_counter() - t0
        for ts_s, gid_s, val_s, remap_d, shift_d, lo_dev, _lo in rounds:
            t0 = time.perf_counter()
            acc = _fused_round_accumulate_jit(
                acc, ts_s, gid_s, val_s, remap_d, shift_d, lo_dev,
                total, bucket_ms, num_groups=g_pad, width=width,
                which=spec.which)
            t_dev += time.perf_counter() - t0
        t0 = time.perf_counter()
        final = _fused_finalize_jit(acc, spec.which)
        out = {k: v[:g] for k, v in final.items()}
        deviceprof.block_until_ready(out, fn="fused_rounds")
        t_dev += time.perf_counter() - t0
        return out, t_dev

    @staticmethod
    def _drop_empty_groups_dev(values: np.ndarray, grids: dict):
        """Fused-path twin of finalize_aggregate's empty-group drop (the
        aligned fast path can register groups whose rows all fall outside
        the range — see that docstring).  Device-friendly: only a G-byte
        any-mask crosses to host; the grids move only in the rare case a
        leak actually exists, so cached/replay queries stay at zero grid
        downloads."""
        if not len(values):
            return values, grids
        has = np.asarray(_group_has_data_jit(grids["count"]))
        if has.all():
            return values, grids
        idx = np.flatnonzero(has)
        return values[idx], {k: jnp.take(v, idx, axis=0)
                             for k, v in grids.items()}

    @staticmethod
    def _fused_last_ts_to_abs(grids: dict, spec: AggregateSpec) -> dict:
        if "last_ts" in grids:
            # absolute float ms needs int64 range: host conversion
            count_h = np.asarray(grids["count"])
            lt = np.asarray(grids["last_ts"]).astype(np.float64)
            grids["last_ts"] = np.where(count_h > 0,
                                        lt + spec.range_start, np.nan)
        return grids

    async def aggregate_segments(self, plan: ScanPlan, spec: AggregateSpec,
                                 top_k=None):
        """Per segment, yield (segment_start, partial parts) — the
        retryable unit for scan_aggregate (segments already yielded are
        skipped on a replan; a segment is yielded only once ALL its
        windows are aggregated).

        Routing order: memo-served segments first (free), then — with a
        ScanRouter attached ([scanagent]) — covered segments' partials
        are fetched from their near-data agents CONCURRENTLY with the
        local pipeline scanning the uncovered rest; agent failures fall
        back per segment through the local pump (the declared fallback
        seam).  Callers fold parts in sorted segment order, so yield
        order is free whichever route served a segment.

        [scan.mesh] plans route their local scans through the 2-D mesh
        pump instead of the single-chip pump (same yield contract; per
        -round fallback through the single-chip kernel is the mesh's
        declared failure seam).  `top_k` additionally enables the
        device-scored winner-sliced mesh path, which bypasses the memo
        (its parts are winner slices — memoizing them would poison
        full-grid queries) and yields only after all compute, so a
        compaction race replans from zero, never double-counts."""
        ensure(plan.mode is UpdateMode.OVERWRITE,
               "aggregate pushdown requires Overwrite mode")
        # device-native decode ([scan.decode]): eligible plans thread
        # the aggregate spec to the decode stage, which uploads each
        # EncodedSegment's raw encoded buffers and fuses filter +
        # merge-dedup + bucket-aggregate into ONE device dispatch —
        # finished per-segment parts come back instead of host windows
        # (ops/device_decode.py; host decode is the bit-identity
        # control).  The copy keeps the caller's plan reusable.
        if self._device_decode_plan_ok(plan):
            plan = dc_replace(plan, decode_spec=spec)

        use_mesh = self._mesh_plan_ok(plan)
        if use_mesh:
            # mesh rounds and their single-chip fallbacks must share
            # one rounding schedule (see ScanPlan.force_xla_agg).
            # Decode-eligible plans additionally DEFER the fused
            # dispatch: DecodePlans ride the windows lists and batch
            # into per-round sharded decode programs on the mesh
            plan = dc_replace(plan, force_xla_agg=True,
                              decode_defer=plan.decode_spec is not None)
            if top_k is not None and self._mesh_topk_ok(plan, spec,
                                                        top_k):
                pump = self._aggregate_topk_mesh(plan, spec, top_k)
                try:
                    async for out in pump:
                        yield out
                finally:
                    await pump.aclose()
                return

        # delta summation: segments whose partials are memoized (same
        # SST set + compatible bucket grid) are served up front and
        # dropped from the scan plan entirely — a narrowed/refined
        # dashboard range re-scans only the delta segments.  Runs on
        # the event loop (the memo is event-loop owned, like the scan
        # cache).  Served segments may yield out of plan order; callers
        # fold parts in sorted segment order (the bit-identity fold
        # order), so order here is free.
        memo = self.parts_memo
        use_memo = memo.enabled and plan.use_cache
        seg_keys: dict[int, tuple] = {}
        memo_pred_key = ""
        if use_memo:
            memo_pred_key = filter_ops.canonical_predicate_key(
                plan.predicate)
            remaining = []
            for seg in plan.segments:
                key = self._cache_key(seg, plan)
                seg_keys[seg.segment_start] = key
                got = memo.probe(key, seg.segment_start,
                                 self.segment_duration_ms, spec,
                                 memo_pred_key)
                if got is None:
                    remaining.append(seg)
                else:
                    yield seg.segment_start, got
            if len(remaining) < len(plan.segments):
                plan = dc_replace(plan, segments=remaining)
            if not remaining:
                return

        def memo_store(seg_start: int, parts: list) -> None:
            if use_memo:
                memo.store(seg_keys[seg_start], spec, memo_pred_key,
                           parts)

        router = self.scan_router
        covered: list = []
        uncovered = plan.segments
        if (router is not None and router.active
                and plan.range is not None):
            covered, uncovered = router.split(plan.segments)
        # local scans route through the mesh pump when [scan.mesh] is
        # on (same yield contract, per-round single-chip fallback)
        pump_fn = (self._aggregate_segments_mesh if use_mesh
                   else self._aggregate_segments_pump)
        # every pump iteration below carries an explicit aclose on
        # abandonment: delegation must not let the pump's in-flight
        # fetch/decode/device tasks outlive a closed consumer into
        # table teardown (PR 3/8 discipline — `async for` does NOT
        # close its source, and a nested drain-generator would just
        # move the leak one level up)
        if not covered:
            pump = pump_fn(plan, spec, memo_store)
            try:
                async for out in pump:
                    yield out
            finally:
                await pump.aclose()
            return
        # near-data routing: agent RPCs run as one background gather
        # while the local pump scans the uncovered segments — the
        # coordinator's store reads and the agents' shard scans
        # overlap, and a slow agent costs its own segments only
        agent_task = asyncio.create_task(
            router.gather(plan, spec, covered))
        try:
            if uncovered:
                pump = pump_fn(
                    dc_replace(plan, segments=list(uncovered)), spec,
                    memo_store)
                try:
                    async for out in pump:
                        yield out
                finally:
                    await pump.aclose()
            served, failed = await agent_task
            agent_task = None
        finally:
            if agent_task is not None:
                # local-pump failure/cancellation: the gather must not
                # outlive the scan into table teardown (PR 3/8
                # discipline)
                agent_task.cancel()
                await asyncio.gather(agent_task, return_exceptions=True)
        for seg_start, parts in served:
            memo_store(seg_start, parts)
            yield seg_start, parts
        if failed:
            # THE declared fallback seam: failed covered segments go
            # through the exact local pump the unrouted scan uses —
            # direct store reads happen here and nowhere else on the
            # routed path (tools/lint.py enforces the nowhere-else)
            pump = pump_fn(
                dc_replace(plan, segments=list(failed)), spec,
                memo_store)
            try:
                async for out in pump:
                    yield out
            finally:
                await pump.aclose()

    async def _aggregate_segments_pump(self, plan: ScanPlan,
                                       spec: AggregateSpec, memo_store):
        """The local aggregate pipeline (store fetch -> decode ->
        device rounds) over `plan.segments`.

        Windows from different segments batch into rounds of
        `scan.agg_batch_windows` (mesh size when meshed) and run as ONE
        compiled program per round — the reference parallelizes segments
        under UnionExec (storage.rs:342-368); here segments share the
        batch/mesh leading axis.  Cross-segment batching is safe because
        segments partition time and windows partition PKs: no two
        windows share a (group, bucket, timestamp) cell, so the host
        combine has no tie-break subtleties."""
        from collections import deque

        batch_w = (self.mesh.devices.size if self.mesh is not None
                   else max(1, self.config.scan.agg_batch_windows))
        queue: list[tuple[int, encode.DeviceBatch, tuple]] = []
        parts: dict[int, list] = {}
        pending: dict[int, int] = {}
        arrived: "deque[int]" = deque()
        # pipelined device stage: ONE aggregation round runs as a
        # background task while this loop keeps pulling/prepping the
        # next windows from the (also pipelined) fetch/decode stages —
        # rounds still apply strictly in dispatch order, so parts per
        # segment are identical to the sequential path's.  The decision
        # is plan.pipeline_active — set by _cached_windows once it has
        # probed whether the scan has store I/O to hide — so it must be
        # read AFTER the windows iterator starts (flush can only run
        # then; asserted by the first-flush-after-first-window order)
        def pipelined() -> bool:
            return plan.pipeline_active
        flush_task: Optional[asyncio.Task] = None

        def _apply(flushed) -> None:
            for seg_start, part in flushed:
                parts[seg_start].append(part)
                pending[seg_start] -= 1

        async def settle_flush() -> None:
            nonlocal flush_task
            if flush_task is None:
                return
            t, flush_task = flush_task, None
            _apply(await t)

        async def flush_round(chunk: list) -> list:
            # stage seconds observed HERE, around the round itself
            # (pool-queue wait included): settling happens at the NEXT
            # flush, so measuring dispatch-to-settle would absorb the
            # consumer's decode/fetch waits into stage="device" and
            # contradict the stall counters the docs say to read
            # alongside it
            from horaedb_tpu.storage import pipeline as pipeline_mod

            t0 = time.perf_counter()
            out = await self._run_pool(
                plan.pool, self._flush_window_batch, chunk, spec, plan)
            pipeline_mod.observe_stage(
                "device", time.perf_counter() - t0,
                rows=sum(w.n_valid for _s, w, _p in chunk))
            return out

        async def flush(k: int) -> None:
            nonlocal flush_task
            chunk = queue[:k]
            del queue[:k]
            if not pipelined():
                _apply(await self._run_pool(
                    plan.pool, self._flush_window_batch, chunk, spec,
                    plan))
                return
            # stage-boundary checkpoint: no new device round for an
            # expired query (the in-flight one drains via settle)
            deadline_checkpoint()
            await settle_flush()
            flush_task = asyncio.create_task(flush_round(chunk))

        windows_iter = self._cached_windows(plan)
        try:
            try:
                async for seg, windows, read_s in windows_iter:
                    t0 = time.perf_counter()
                    s = seg.segment_start
                    arrived.append(s)
                    parts[s] = []
                    pending[s] = 0

                    def prep_windows(ws=windows):
                        out = []
                        for w in ws:
                            # same semantics as the row path: post-dedup
                            # rows
                            _ROWS_SCANNED.inc(w.n_valid)
                            if isinstance(w, device_decode.DevicePart):
                                # already a finished aggregate partial;
                                # rides the queue (prep=None) so a
                                # segment's parts keep window order.
                                # Provably-empty parts never enqueue —
                                # a pending[] count that no flush entry
                                # repays would park the segment (and
                                # every later one) at the stream
                                # head-of-line until end-of-scan
                                if w.part is not None:
                                    out.append((w, None))
                                continue
                            prep = self._window_groups(w, spec, plan)
                            if prep is not None:
                                out.append((w, prep))
                        return out

                    for w, prep in await self._run_pool(plan.pool,
                                                        prep_windows):
                        queue.append((s, w, prep))
                        pending[s] += 1
                    while len(queue) >= batch_w:
                        await flush(batch_w)
                    _SCAN_LATENCY.observe(read_s
                                          + (time.perf_counter() - t0))
                    while arrived and pending[arrived[0]] == 0:
                        s0 = arrived.popleft()
                        seg_parts = parts.pop(s0)
                        memo_store(s0, seg_parts)
                        yield s0, seg_parts
            finally:
                await windows_iter.aclose()
            if queue:
                await flush(len(queue))
            await settle_flush()
            while arrived:
                s0 = arrived.popleft()
                seg_parts = parts.pop(s0)
                memo_store(s0, seg_parts)
                yield s0, seg_parts
        finally:
            if flush_task is not None:
                # cancelled/failed scan: drain the in-flight device
                # round (the pool job runs to completion regardless) so
                # it never races table teardown
                flush_task.cancel()
                await asyncio.gather(flush_task, return_exceptions=True)

    # ---- the 2-D scan mesh ([scan.mesh]; docs/parallel.md) -----------------

    def _mesh_plan_ok(self, plan: ScanPlan) -> bool:
        """Plan-level [scan.mesh] routing gate; per-round gates (sum
        overlap, count bound, grid budget) live in _run_mesh_round and
        fall back per round.  Counted reasons mirror the device-decode
        discipline (scan_mesh_fallback_total{reason=})."""
        if self.scan_mesh is None:
            return False
        if plan.mode is not UpdateMode.OVERWRITE:
            return False
        if merge_ops.merge_impl() != "host_perm":
            # device_sort windows live sharded on the legacy segment
            # mesh; the 2-D scan consumes host-merged windows
            note_mesh_fallback("merge_impl")
            return False
        return True

    def _mesh_topk_ok(self, plan: ScanPlan, spec: AggregateSpec,
                      tk) -> bool:
        """Whether a top-k query can take the device-scored, winner
        -sliced mesh path (egress bounded at O(k x buckets x aggs) per
        run).  Selection rankings (min/max/last) score exactly on
        device; additive rankings (count/sum/avg) score through the
        compensated (hi, lo) plane — exact when every add provably is,
        with a counted `additive_topk` downgrade otherwise.  Mixed
        -provenance scans (near-data partials, device-decode parts)
        keep the full-parts path, which is still mesh-combined — just
        not egress-bounded."""
        if tk.by not in ("min", "max", "last", "count", "sum", "avg") \
                or not (tk.by == "count" or tk.by in set(spec.which)):
            # same requested-agg rule combine_top_k enforces (count is
            # always folded, so ranking by it needs no spec entry)
            note_mesh_fallback("topk_by")
            return False
        if plan.decode_spec is not None:
            note_mesh_fallback("topk_decode")
            return False
        router = self.scan_router
        if (router is not None and router.active
                and plan.range is not None
                and router.split(plan.segments)[0]):
            # agent-served segments never reach the device score state,
            # so a global ranking over it would miss their groups
            note_mesh_fallback("topk_router")
            return False
        est_rows = sum(f.meta.num_rows
                       for seg in plan.segments for f in seg.ssts)
        if est_rows * _CACHE_BYTES_PER_ROW > self.cache_budget_bytes:
            # two-phase: every window pins in host RAM until winners
            # are known (the fused path's budget discipline)
            note_mesh_fallback("topk_budget")
            return False
        return True

    def _mesh_runs(self, items: list) -> list[list]:
        """Consecutive same-segment slot runs of one round, as
        [seg_start, first_slot, last_slot] triples — the segmented
        reduction's run layout (plan-order slot admission keeps a
        segment's windows adjacent)."""
        runs: list[list] = []
        for i, (s, _w, _prep) in enumerate(items):
            if runs and runs[-1][0] == s:
                runs[-1][2] = i
            else:
                runs.append([s, i, i])
        return runs

    def _mesh_round_gates(self, items: list, runs: list,
                          spec: AggregateSpec, g_pad: int,
                          width: int, cap: int,
                          local_ok: bool) -> None:
        """Per-round exactness/budget gates; raises _MeshFallback with
        the counted reason.  Only multi-slot runs combine on the mesh,
        so the exactness gates apply to those alone."""
        T = int(self.scan_mesh.shape["time"])
        want = combine_mod.expand_which(spec.which)
        multi = any(b > a for _s, a, b in runs)
        if multi and local_ok:
            # the cell-wise run combine is only bucket-aligned when
            # every slot of a run shares the same first bucket `lo`.
            # Bulk/sidecar-streamed windows share their segment's
            # epoch, but the parquet-streamed fallback encodes each
            # chunk with its OWN epoch — those runs combine per window
            # on the single-chip kernel instead (a silent mesh combine
            # would shift rows by whole buckets AND clip rows past the
            # common window span; caught by the streamed chaos
            # schedules, regression-tested in test_mesh_scan)
            for _s, a, b in runs:
                lo0 = max(0, items[a][2][2] // spec.bucket_ms)
                for i in range(a + 1, b + 1):
                    if max(0, items[i][2][2] // spec.bucket_ms) != lo0:
                        raise _MeshFallback("run_misaligned")
        if multi and T * cap >= (1 << 24):
            # f32 integer adds stay exact below 2^24; a run's combined
            # per-cell count is bounded by slots x capacity
            raise _MeshFallback("count_bound")
        if multi and "sum" in want:
            # any shared group between two windows of one run would
            # f32-add sum cells the host folds in f64.  When the group
            # column is the LEADING primary key, window group ranges
            # are ordered, so only adjacent boundary values can repeat
            # (transitively: a group shared by non-adjacent windows
            # pinches every window between to that one group, which
            # the adjacent checks catch).  Any other group column can
            # recur in non-adjacent windows — check EVERY pair (runs
            # are at most time-axis slots wide, so this stays tiny).
            lead_pk = (self.schema.primary_key_names[0] == spec.group_col
                       if self.schema.primary_key_names else False)
            for _s, a, b in runs:
                if lead_pk:
                    for i in range(a, b):
                        va, vb = items[i][2][0], items[i + 1][2][0]
                        if len(va) > 0 and len(vb) > 0 and va[-1] == vb[0]:
                            raise _MeshFallback("sum_overlap")
                else:
                    for i in range(a, b):
                        for j in range(i + 1, b + 1):
                            if np.intersect1d(items[i][2][0],
                                              items[j][2][0]).size:
                                raise _MeshFallback("sum_overlap")
        naggs = len(want) + (1 if "last" in want else 0)
        if g_pad * width * 4 * naggs > self.config.scan.mesh.max_grid_bytes:
            raise _MeshFallback("grid_budget")

    def _run_mesh_round(self, items: list, spec: AggregateSpec,
                        plan: ScanPlan, group_space=None,
                        download: bool = True, round_salt: int = 0):
        """Dispatch one round of host windows onto the 2-D scan mesh:
        per-slot window partials (series-sharded group blocks) plus the
        on-mesh segmented time-axis combine, one compiled program
        (parallel.scan.mesh_run_partials).

        download=True (the streaming pump): downloads each run TAIL's
        combined grids and returns [(seg_start, part, repay)] entries
        shaped exactly like _flush_host_round's emission — parts enter
        the same combine/memo machinery.  download=False (the top-k
        score/winner passes): returns the device outputs + run layout,
        nothing leaves the mesh here."""
        from horaedb_tpu.parallel.scan import (
            mesh_run_partials,
            shard_time_axis,
        )

        mesh = self.scan_mesh
        T = int(mesh.shape["time"])
        series = int(mesh.shape["series"])
        ensure(len(items) <= T, "mesh round exceeds the time axis")
        runs = self._mesh_runs(items)
        cap = max(it[1].capacity for it in items)
        if group_space is None:
            group_space = np.unique(
                np.concatenate([it[2][0] for it in items]))
        g = len(group_space)
        g_pad = max(8, series, 1 << (g - 1).bit_length())
        local_ok = all(
            it[1].encodings[spec.ts_col].kind == "offset" for it in items)
        width = self._window_grid_width(spec) if local_ok \
            else spec.num_buckets
        self._mesh_round_gates(items, runs, spec, g_pad, width, cap,
                               local_ok)
        space_fp = (g, hash(group_space.tobytes()))
        # round_salt disambiguates consecutive rounds of one segment
        # that share (seg0, T, cap, ...) — without it round 2's small
        # stacks overwrite round 1's and every replay/warm repeat
        # misses (the fused path's chunk-offset lesson, read above)
        stack_key = self._round_stack_key(items[0][0], spec, plan, T,
                                          cap, g_pad, width, space_fp
                                          ) + (round_salt,)
        put = functools.partial(shard_time_axis, mesh)
        ts_s, gid_s, val_s, remap_d, shift_d, lo_dev, lo = \
            self._build_round_stacks(items, spec, plan, T, cap, g_pad,
                                     width, group_space, local_ok,
                                     stack_key=stack_key, put=put,
                                     key_salt=("mesh2",))
        if any(int(lo[b]) >= spec.num_buckets for _s, _a, b in runs):
            raise _MeshFallback("lo_range")
        fn_key = (g_pad, width, spec.which)
        fn = self._mesh_run_fns.get(fn_key)
        if fn is None:
            fn = mesh_run_partials(mesh, num_groups=g_pad,
                                   num_buckets=width, which=spec.which)
            self._mesh_run_fns[fn_key] = fn
        # plan-order slot admission per mesh column: slot i is item i;
        # padding slots get unique negative ids so they never combine
        seg_ids = -(np.arange(T, dtype=np.int32) + 1)
        for ridx, (_s, a, b) in enumerate(runs):
            seg_ids[a:b + 1] = ridx
        t0 = time.perf_counter()
        out = fn(ts_s, gid_s, val_s, remap_d, shift_d, lo_dev,
                 shard_time_axis(mesh, seg_ids),
                 self._dev_scalar(spec.num_buckets),
                 self._dev_scalar(spec.bucket_ms, "arr1"))
        _MESH_ROUNDS.inc()
        if len(items) < T:
            from horaedb_tpu.storage import pipeline as pipeline_mod

            pipeline_mod.note_mesh_stall("time")
        if g <= (series - 1) * (g_pad // series):
            from horaedb_tpu.storage import pipeline as pipeline_mod

            pipeline_mod.note_mesh_stall("series")
        rows_per_shard = [int(it[1].n_valid) for it in items]
        pad_rows = (T - len(items)) * cap \
            + sum(cap - r for r in rows_per_shard)
        if not download:
            _STAGE_SECONDS["mesh_aggregate"].observe(
                time.perf_counter() - t0)
            deviceprof.record_round(
                "mesh_run", slots=len(items), capacity=T,
                rows_per_shard=rows_per_shard, padding_rows=pad_rows,
                seconds=time.perf_counter() - t0)
            return {"out": out, "runs": runs, "lo": lo,
                    "lo_dev": lo_dev, "g": g, "width": width}
        entries: list = []
        cells = 0
        dl_bytes = 0
        t_dl = time.perf_counter()
        for s, a, b in runs:
            lo_run, grids = self._slice_mesh_part(out, b, g, int(lo[b]),
                                                  width, spec)
            cells += sum(int(v.shape[0] * v.shape[1])
                         for v in grids.values())
            dl_bytes += sum(int(v.nbytes) for v in grids.values())
            entries.append((s, (group_space, lo_run, grids), b - a + 1))
        # the tail-grid downloads above synced the dispatch — exec and
        # d2h attribution for the round lands here
        deviceprof.observe_exec("mesh_run_partials",
                                time.perf_counter() - t_dl)
        deviceprof.charge_transfer("d2h", dl_bytes)
        _STAGE_SECONDS["mesh_aggregate"].observe(time.perf_counter() - t0)
        _MESH_PARTS.inc(len(entries))
        _MESH_PART_CELLS.inc(cells)
        deviceprof.record_round(
            "mesh_run", slots=len(items), capacity=T,
            rows_per_shard=rows_per_shard, padding_rows=pad_rows,
            seconds=time.perf_counter() - t0)
        return entries

    @staticmethod
    def _slice_mesh_part(out: dict, tail_slot: int, g: int, lo_run: int,
                         width: int, spec: AggregateSpec):
        """THE mesh part emission, shared by the streaming download and
        the top-k winner pass so the two cannot drift: slice tail slot
        `tail_slot`'s combined grids to the real group count (g < 0 =
        keep all rows, the winner-sliced shape) and the query-clipped
        width, then rebase window-local last_ts to range_start-relative
        int64 — byte-for-byte the emission _flush_host_round's per
        -window parts use.  The slices COPY so the (T, g_pad, width)
        download is not pinned by the part (the PartsMemo views
        discipline)."""
        w_eff = min(width, spec.num_buckets - lo_run)
        rows = slice(None) if g < 0 else slice(0, g)
        grids = {k: np.ascontiguousarray(
            np.asarray(v[tail_slot])[rows, :w_eff])
            for k, v in out.items()}
        if "last_ts" in grids:
            lt = grids["last_ts"].astype(np.int64)
            grids["last_ts"] = np.where(
                grids["count"] > 0, lt + lo_run * spec.bucket_ms, lt)
        return lo_run, grids

    def _flush_mesh_round(self, items: list, spec: AggregateSpec,
                          plan: ScanPlan, round_salt: int = 0) -> list:
        """Pool-side mesh round flush: DevicePart entries (finished
        fused-decode partials) pass through in position; host windows
        dispatch onto the mesh, falling back PER ROUND to the single
        -chip kernel (_flush_host_round — the declared failure seam)
        on ineligibility or a failed dispatch (lost shard, XLA error).
        Returns [(seg_start, part_or_None, repaid_windows)]."""
        out: list = []
        host_items: list = []
        deco_items: list = []
        for s, w, prep in items:
            if prep is None:
                out.append((s, w.part, 1))
            elif prep is _DECODE_PREP:
                deco_items.append((s, w))
            else:
                host_items.append((s, w, prep))
        if deco_items:
            out.extend(self._run_mesh_decode_rounds(deco_items, spec,
                                                    plan))
        if not host_items:
            return out
        try:
            out.extend(self._run_mesh_round(host_items, spec, plan,
                                            round_salt=round_salt))
            return out
        except _MeshFallback as f:
            note_mesh_fallback(f.reason)
        except Exception as exc:  # noqa: BLE001 — counted, single-chip
            # fallback below reproduces the result (chaos-asserted)
            note_mesh_fallback("mesh_error")
            logger.warning(
                "mesh round failed (%s); re-running the round on the "
                "single-chip kernel", exc)
        # single-chip rounds are capped at agg_batch_windows; a mesh
        # chunk can be wider (time axis > agg_batch_windows), so split
        # it — per-window grids are round-composition-independent, so
        # the parts are identical either way
        hb = max(1, self.config.scan.agg_batch_windows)
        flushed = []
        for i in range(0, len(host_items), hb):
            flushed.extend(self._flush_host_round(
                host_items[i:i + hb], spec, plan))
        out.extend(
            (host_items[i][0], p[1] if p is not None else None, 1)
            for i, p in enumerate(flushed))
        return out

    def _run_mesh_decode_rounds(self, deco: list, spec: AggregateSpec,
                                plan: ScanPlan) -> list:
        """Batch one flush's deferred DecodePlans into sharded fused
        -decode rounds: plans group by static_key (one compiled program
        per group) in arrival order, time-axis-wide chunks each run as
        ONE mesh dispatch.  A round that declines (budget) or fails
        (lost shard, XLA error) falls back PER ITEM to the standalone
        fused dispatch (execute_plan) — still device decode, just not
        mesh-placed; reasons counted in scan_mesh_fallback_total."""
        T = int(self.scan_mesh.shape["time"])
        groups: dict = {}
        order: list = []
        for s, dp in deco:
            k = dp.static_key()
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append((s, dp))
        entries: list = []
        for k in order:
            grp = groups[k]
            for i in range(0, len(grp), T):
                chunk = grp[i:i + T]
                try:
                    entries.extend(self._run_mesh_decode_round(
                        chunk, spec))
                    continue
                except _MeshFallback as f:
                    note_mesh_fallback(f.reason)
                except Exception as exc:  # noqa: BLE001 — counted,
                    # per-item fused dispatch reproduces the parts
                    note_mesh_fallback("mesh_error")
                    logger.warning(
                        "mesh decode round failed (%s); running the "
                        "per-segment fused dispatch", exc)
                for s, dp in chunk:
                    part = device_decode.execute_plan(dp).finalize()
                    entries.append((s, part.part, 1))
        return entries

    def _run_mesh_decode_round(self, chunk: list,
                               spec: AggregateSpec) -> list:
        """ONE device program from stored bytes to combined run grids:
        stack the chunk's raw encoded buffers one segment per time
        slot, run leaf-filter + (k-way merge | sort | presorted) +
        keep-last dedup + bucket aggregate + segmented ppermute combine
        in a single shard_map dispatch (parallel.scan
        .mesh_decode_partials), then download run-TAIL grids only.

        Slot-local group code spaces ARE the round rows (identity
        remap): same-segment consecutive slots share a seg id — and
        therefore combine on the mesh — only when their dictionaries,
        first bucket, group count and clipped width all match AND the
        combine is exact for the requested aggs (no additive sum
        cells, f32-count bound); everything else gets a unique id and
        comes back as its own part, exactly what the standalone fused
        dispatches would emit."""
        from horaedb_tpu.parallel.scan import (
            mesh_decode_partials,
            shard_time_axis,
        )

        mesh = self.scan_mesh
        T = int(mesh.shape["time"])
        series = int(mesh.shape["series"])
        dps = [dp for _s, dp in chunk]
        dp0 = dps[0]
        cap = max(dp.cap for dp in dps)
        g_pad = max(8, series, max(dp.g_pad for dp in dps))
        width = max(dp.use_width for dp in dps)
        want = combine_mod.expand_which(spec.which)
        naggs = len(want) + (1 if "last" in want else 0)
        ncol = len(dp0.upload_names)
        if (T * cap * 4 * ncol
                > self.config.scan.decode.max_upload_bytes
                or T * g_pad * width * 4 * naggs
                > self.config.scan.mesh.max_grid_bytes):
            raise _MeshFallback("mesh_decode_budget")
        # seg-id sharing gates — see docstring; unique negative ids on
        # padding slots so they never combine (mesh_run_partials'
        # convention)
        sharable = "sum" not in want and T * cap < (1 << 24)
        seg_ids = -(np.arange(T, dtype=np.int32) + 1)
        rid = -1
        for i, (s, dp) in enumerate(chunk):
            joined = False
            if i and sharable:
                ps, pdp = chunk[i - 1]
                joined = (ps == s and pdp.lo == dp.lo
                          and pdp.shift == dp.shift
                          and pdp.g == dp.g and pdp.w_eff == dp.w_eff
                          and np.array_equal(pdp.values, dp.values))
            if not joined:
                rid += 1
            seg_ids[i] = rid
        t0 = time.perf_counter()
        put = functools.partial(shard_time_axis, mesh)
        # decode round stacks are HBM-resident and ride the SAME LRU +
        # weakref discipline as the host-window round stacks (anchored
        # on the cached EncodedSegments instead of merged windows), so
        # warm repeats skip the re-upload and drop_hbm_state evicts
        # them with everything else stack_cache-accounted
        ncst = len(dp0.consts)
        stack_key = ("meshdecode", dp0.static_key(), cap, T,
                     tuple((s, dp.shift, dp.lo, dp.es.n,
                            tuple(c.tobytes() for c in dp.consts))
                           for s, dp in chunk))
        es_list = tuple(dp.es for dp in dps)
        cached = self._stack_cache_get(stack_key, es_list)
        if cached is not None:
            cols_dev = cached[:ncol]
            consts_dev = cached[ncol:ncol + ncst]
            nv_dev, offs_dev, shift_dev, lo_dev = cached[ncol + ncst:]
            upload_bytes = 0
        else:
            # host stacks: one (T, cap) matrix per upload column,
            # padding slots all-zero with n_valid 0 (every row invalid
            # on device)
            cols_np = [np.zeros((T, cap),
                                dtype=dp0.es.columns[nm].dtype)
                       for nm in dp0.upload_names]
            nv = np.zeros(T, dtype=np.int32)
            shift_np = np.zeros(T, dtype=np.int32)
            lo_np = np.zeros(T, dtype=np.int32)
            consts_np = [np.tile(c, (T, 1)).astype(np.int32)
                         for c in dp0.consts]
            if dp0.route == "kway":
                offs_np = np.full((T, dp0.num_runs + 1), cap,
                                  dtype=np.int32)
                offs_np[:, 0] = 0
            else:
                offs_np = np.zeros((T, 1), dtype=np.int32)
            upload_bytes = sum(c.nbytes for c in cols_np)
            for t, (s, dp) in enumerate(chunk):
                n = dp.es.n
                for j, nm in enumerate(dp0.upload_names):
                    cols_np[j][t, :n] = dp.es.columns[nm]
                nv[t] = n
                shift_np[t] = dp.shift
                lo_np[t] = dp.lo
                for ci, c in enumerate(dp.consts):
                    consts_np[ci][t] = c
                if dp0.route == "kway":
                    # rebuild against the ROUND capacity: real run
                    # bounds, then the pad zone [n, cap) as its own
                    # run, trailing runs empty at cap (the
                    # ops/merge.kway_merge_perm contract)
                    rl = dp.es.run_lengths
                    real = np.cumsum((0,) + tuple(rl))
                    offs_np[t, :len(real)] = real
                    offs_np[t, len(rl):] = cap
                    offs_np[t, len(rl)] = n
            cols_dev = tuple(put(c) for c in cols_np)
            consts_dev = tuple(put(c) for c in consts_np)
            nv_dev, offs_dev = put(nv), put(offs_np)
            shift_dev, lo_dev = put(shift_np), put(lo_np)
            self._stack_cache_put(
                stack_key, es_list,
                cols_dev + consts_dev
                + (nv_dev, offs_dev, shift_dev, lo_dev))
        fn_key = ("decode", dp0.static_key(), g_pad, width)
        fn = self._mesh_run_fns.get(fn_key)
        if fn is None:
            fn = mesh_decode_partials(
                mesh, num_groups=g_pad, num_buckets=width,
                which=spec.which, key_slots=dp0.key_slots,
                num_pks=dp0.num_pks, group_pos=dp0.group_pos,
                ts_pos=dp0.ts_pos, val_slot=dp0.val_slot,
                leaf_prog=dp0.leaf_prog, route=dp0.route,
                num_runs=dp0.num_runs)
            self._mesh_run_fns[fn_key] = fn
        out, _kept = fn(cols_dev, nv_dev, consts_dev, offs_dev,
                        shift_dev, lo_dev, put(seg_ids),
                        self._dev_scalar(spec.num_buckets),
                        self._dev_scalar(spec.bucket_ms, "arr1"))
        _MESH_ROUNDS.inc()
        if len(chunk) < T:
            from horaedb_tpu.storage import pipeline as pipeline_mod

            pipeline_mod.note_mesh_stall("time")
        # run-tail emission, byte-for-byte DecodeDispatch.finalize's
        # shape: slice to the tail plan's real group count and clipped
        # width (copies — the (T, g_pad, width) download must not stay
        # pinned), rebase window-local last_ts to range-relative int64
        entries: list = []
        cells = 0
        src_rows = 0
        dl_bytes = 0
        a = 0
        t_dl = time.perf_counter()
        for i in range(len(chunk)):
            if i + 1 < len(chunk) and seg_ids[i + 1] == seg_ids[i]:
                continue
            s, dp = chunk[i]
            grids = {k: np.ascontiguousarray(
                np.asarray(v[i])[:dp.g, :dp.w_eff])
                for k, v in out.items()}
            if "last_ts" in grids:
                lt = grids["last_ts"].astype(np.int64)
                grids["last_ts"] = np.where(
                    grids["count"] > 0,
                    lt + dp.lo * spec.bucket_ms, lt)
            cells += sum(int(v.shape[0] * v.shape[1])
                         for v in grids.values())
            dl_bytes += sum(int(v.nbytes) for v in grids.values())
            src_rows += sum(dp2.es.n for _s2, dp2 in chunk[a:i + 1])
            entries.append(
                (s, (dp.values, dp.lo, grids), i - a + 1))
            a = i + 1
        deviceprof.observe_exec("mesh_decode_partials",
                                time.perf_counter() - t_dl)
        deviceprof.charge_transfer("d2h", dl_bytes)
        _MESH_PARTS.inc(len(entries))
        _MESH_PART_CELLS.inc(cells)
        deviceprof.record_round(
            "mesh_decode", slots=len(chunk), capacity=T,
            rows_per_shard=[int(dp.es.n) for _s, dp in chunk],
            padding_rows=(T - len(chunk)) * cap
            + sum(cap - int(dp.es.n) for _s, dp in chunk),
            upload_bytes=upload_bytes, stack_hit=cached is not None,
            seconds=time.perf_counter() - t0)
        device_decode.observe_decode_stage(
            time.perf_counter() - t0, rows=src_rows,
            nbytes=upload_bytes)
        return entries

    async def _aggregate_segments_mesh(self, plan: ScanPlan,
                                       spec: AggregateSpec, memo_store):
        """The mesh twin of _aggregate_segments_pump: the pipeline's
        fetch/decode stages feed this device stage, which admits
        windows to mesh time slots strictly in plan order and flushes
        rounds of time-axis width.  Per-segment run parts come back
        through the same yield/memo contract, so replans, the
        PartsMemo, and the sorted-segment fold are untouched."""
        from collections import deque

        from horaedb_tpu.storage import pipeline as pipeline_mod

        batch_w = int(self.scan_mesh.shape["time"])
        queue: list[tuple[int, encode.DeviceBatch, tuple]] = []
        parts: dict[int, list] = {}
        pending: dict[int, int] = {}
        arrived: "deque[int]" = deque()

        def pipelined() -> bool:
            return plan.pipeline_active
        flush_task: Optional[asyncio.Task] = None
        flush_ordinal = 0

        def _apply(flushed) -> None:
            for seg_start, part, repay in flushed:
                if part is not None:
                    parts[seg_start].append(part)
                pending[seg_start] -= repay

        async def settle_flush() -> None:
            nonlocal flush_task
            if flush_task is None:
                return
            t, flush_task = flush_task, None
            _apply(await t)

        async def flush_round(chunk: list, salt: int) -> list:
            t0 = time.perf_counter()
            out = await self._run_pool(
                plan.pool, self._flush_mesh_round, chunk, spec, plan,
                salt)
            pipeline_mod.observe_stage(
                "device", time.perf_counter() - t0,
                rows=sum(w.n_valid for _s, w, _p in chunk))
            return out

        async def flush(k: int) -> None:
            nonlocal flush_task, flush_ordinal
            chunk = queue[:k]
            del queue[:k]
            salt = flush_ordinal
            flush_ordinal += 1
            if not pipelined():
                _apply(await self._run_pool(
                    plan.pool, self._flush_mesh_round, chunk, spec,
                    plan, salt))
                return
            # stage-boundary checkpoint: no new mesh round for an
            # expired query (the in-flight one drains via settle)
            deadline_checkpoint()
            await settle_flush()
            flush_task = asyncio.create_task(flush_round(chunk, salt))

        windows_iter = self._cached_windows(plan)
        try:
            try:
                async for seg, windows, read_s in windows_iter:
                    t0 = time.perf_counter()
                    s = seg.segment_start
                    arrived.append(s)
                    parts[s] = []
                    pending[s] = 0

                    def prep_windows(ws=windows):
                        out = []
                        for w in ws:
                            _ROWS_SCANNED.inc(w.n_valid)
                            if isinstance(w, device_decode.DecodePlan):
                                # deferred fused decode: batched into
                                # sharded rounds at flush time
                                out.append((w, _DECODE_PREP))
                                continue
                            if isinstance(w, device_decode.DevicePart):
                                if w.part is not None:
                                    out.append((w, None))
                                continue
                            prep = self._window_groups(w, spec, plan)
                            if prep is not None:
                                out.append((w, prep))
                        return out

                    for w, prep in await self._run_pool(plan.pool,
                                                        prep_windows):
                        queue.append((s, w, prep))
                        pending[s] += 1
                    while len(queue) >= batch_w:
                        await flush(batch_w)
                    _SCAN_LATENCY.observe(read_s
                                          + (time.perf_counter() - t0))
                    while arrived and pending[arrived[0]] == 0:
                        s0 = arrived.popleft()
                        seg_parts = parts.pop(s0)
                        memo_store(s0, seg_parts)
                        yield s0, seg_parts
            finally:
                await windows_iter.aclose()
            if queue:
                await flush(len(queue))
            await settle_flush()
            while arrived:
                s0 = arrived.popleft()
                seg_parts = parts.pop(s0)
                memo_store(s0, seg_parts)
                yield s0, seg_parts
        finally:
            if flush_task is not None:
                # cancelled/failed scan: drain the in-flight mesh
                # round so it never races table teardown (zero leaked
                # tasks — the deadline-mid-mesh chaos schedule asserts
                # it)
                flush_task.cancel()
                await asyncio.gather(flush_task, return_exceptions=True)

    async def _aggregate_topk_mesh(self, plan: ScanPlan,
                                   spec: AggregateSpec, tk):
        """Egress-bounded top-k on the scan mesh, two passes over the
        collected windows (two-phase like the fused path — the budget
        gate in _mesh_topk_ok bounds the pinned rows):

          score   every round's segmented-combined grids fold into a
                  device-resident (groups, buckets) score state —
                  selection ops, exact — and only a per-group
                  (score, has) vector downloads: O(groups) bytes;
          winners rank on host with combine.rank_top_k (the same
                  stable tie-break combine_top_k uses), then re-run
                  the rounds (stacks are LRU-cached) and download ONLY
                  the k winners' grid rows per run: O(k x buckets x
                  aggs) per part, independent of cardinality
                  (scan_mesh_part_cells_total asserts it).

        Yields (seg_start, winner-sliced parts); finalize_aggregate's
        combine_top_k then reproduces the full ranking byte-for-byte
        restricted to the winner set.  Any round-level ineligibility
        (sum overlap, budget, mesh error) downgrades the WHOLE query
        to full-width mesh parts — correct, just not egress-bounded."""
        from horaedb_tpu.parallel import scan as pscan

        T = int(self.scan_mesh.shape["time"])
        items: list = []
        windows_iter = self._cached_windows(plan)
        try:
            async for seg, windows, read_s in windows_iter:
                s = seg.segment_start

                def prep_windows(ws=windows, s=s):
                    out = []
                    for w in ws:
                        _ROWS_SCANNED.inc(w.n_valid)
                        prep = self._window_groups(w, spec, plan)
                        if prep is not None:
                            out.append((s, w, prep))
                    return out

                items.extend(await self._run_pool(plan.pool,
                                                  prep_windows))
                _SCAN_LATENCY.observe(read_s)
        finally:
            await windows_iter.aclose()
        if not items:
            return
        # canonical fold order: sorted segment, window order within —
        # the order finalize folds parts in, so pass-2 part emission
        # matches the control's arithmetic order exactly
        items.sort(key=lambda it: it[0])
        all_values = np.unique(np.concatenate([it[2][0]
                                               for it in items]))
        g = len(all_values)
        series = int(self.scan_mesh.shape["series"])
        g_pad = max(8, series, 1 << (g - 1).bit_length())
        local_ok = all(it[1].encodings[spec.ts_col].kind == "offset"
                       for it in items)
        width = self._window_grid_width(spec) if local_ok \
            else spec.num_buckets
        chunks = [items[i:i + T] for i in range(0, len(items), T)]
        bucket_dev = self._dev_scalar(spec.bucket_ms)
        additive = tk.by in ("count", "sum", "avg")
        if additive:
            state = pscan.mesh_additive_init(
                g_pad, spec.num_buckets + width, tk.by)
        else:
            state = pscan.mesh_score_init(
                g_pad, spec.num_buckets + width, tk.by)
        # the score state is device-resident for the whole two-pass
        # ranking: account it (mesh_state ledger kind) and free it on
        # EVERY exit path before any parts yield
        state_bytes = sum(int(v.nbytes) for v in state.values())
        self._mesh_state_bytes += state_bytes
        downgrade = None
        finished = None
        try:
            try:
                for ci, chunk in enumerate(chunks):
                    deadline_checkpoint()

                    def score_round(chunk=chunk, state=state, ci=ci):
                        got = self._run_mesh_round(
                            chunk, spec, plan, group_space=all_values,
                            download=False, round_salt=ci)
                        if additive:
                            # TAIL slots only: a tail's segmented
                            # combine already holds its whole run,
                            # prefixes would double-count
                            tails = np.zeros(T, dtype=bool)
                            for _s, _a, b in got["runs"]:
                                tails[b] = True
                            return pscan.mesh_additive_update(
                                state, got["out"]["count"],
                                got["out"].get("sum",
                                               got["out"]["count"]),
                                jnp.asarray(tails), got["lo_dev"],
                                by=tk.by)
                        last_ts = (got["out"].get("last_ts")
                                   if tk.by == "last" else None)
                        return pscan.mesh_score_update(
                            state, got["out"][tk.by],
                            got["out"]["count"], last_ts,
                            got["lo_dev"], bucket_dev, by=tk.by)

                    state = await self._run_pool(plan.pool,
                                                 score_round)
            except _MeshFallback as f:
                downgrade = f.reason
            except NotFoundError:
                raise  # compaction race: the caller replans
            except Exception as exc:  # noqa: BLE001 — counted
                # downgrade
                downgrade = "mesh_error"
                logger.warning("mesh top-k scoring failed (%s); "
                               "serving full-width parts", exc)
            if downgrade is None:
                def finish_scores():
                    if not additive:
                        scores_d, has_d = pscan.mesh_score_finalize(
                            state, largest=tk.largest,
                            num_buckets=spec.num_buckets)
                        _MESH_SCORE_CELLS.inc(2 * g)
                        return (np.asarray(scores_d)[:g]
                                .astype(np.float64),
                                np.asarray(has_d)[:g])
                    fin = pscan.mesh_additive_finalize(
                        state, by=tk.by, largest=tk.largest,
                        num_buckets=spec.num_buckets)
                    if bool(fin["lossy"]):
                        # an add was not provably exact: the
                        # compensated pair may not match the host's
                        # f64 fold — counted downgrade to full parts,
                        # never a silently drifted winner set
                        return None
                    if tk.by == "avg":
                        # the device cannot divide bit-identically to
                        # the host, so avg downloads the full (groups,
                        # buckets) cnt/sum pairs and the host runs
                        # combine_top_k's exact score formula — the
                        # one honestly O(g x buckets) score egress
                        # (counted as such)
                        cnt = (np.asarray(fin["cnt_hi"], np.float64)
                               + np.asarray(fin["cnt_lo"],
                                            np.float64))[:g]
                        sm = (np.asarray(fin["sum_hi"], np.float64)
                              + np.asarray(fin["sum_lo"],
                                           np.float64))[:g]
                        hs = np.asarray(fin["has"])[:g]
                        _MESH_SCORE_CELLS.inc(5 * cnt.size + g)
                        with np.errstate(invalid="ignore",
                                         divide="ignore"):
                            cell = sm / np.maximum(cnt, 1)
                        fill = -np.inf if tk.largest else np.inf
                        cell = np.where(hs, cell, fill)
                        sc = (cell.max(axis=1) if tk.largest
                              else cell.min(axis=1))
                        return sc, hs.any(axis=1)
                    sc = (np.asarray(fin["score_hi"], np.float64)
                          + np.asarray(fin["score_lo"],
                                       np.float64))[:g]
                    _MESH_SCORE_CELLS.inc(3 * g)
                    return sc, np.asarray(fin["has_any"])[:g]

                finished = await self._run_pool(plan.pool,
                                                finish_scores)
        finally:
            state = None
            self._mesh_state_bytes -= state_bytes
        if downgrade is not None:
            note_mesh_fallback(downgrade)
            # full-width mesh parts through the normal chunk flush —
            # still byte-identical, just not egress-bounded (finalize's
            # host combine_top_k ranks them)
            async for out in self._yield_chunks_as_parts(chunks, spec,
                                                         plan):
                yield out
            return
        if finished is None:
            note_mesh_fallback("additive_topk")
            async for out in self._yield_chunks_as_parts(chunks, spec,
                                                         plan):
                yield out
            return
        scores, has_any = finished
        kept = np.flatnonzero(has_any)
        winners = combine_mod.rank_top_k(
            [int(r) for r in kept], scores[kept], tk)
        if not winners:
            return
        w_rows = np.asarray(sorted(winners), dtype=np.int32)
        winner_values = all_values[w_rows]
        seg_parts: dict[int, list] = {}
        cells = 0
        try:
            for ci, chunk in enumerate(chunks):
                deadline_checkpoint()

                def winner_round(chunk=chunk, ci=ci):
                    got = self._run_mesh_round(chunk, spec, plan,
                                               group_space=all_values,
                                               download=False,
                                               round_salt=ci)
                    sliced = pscan.mesh_take_rows(got["out"],
                                                  jnp.asarray(w_rows))
                    out = []
                    for s, _a, b in got["runs"]:
                        # the round's OWN grid width: a chunk whose ts
                        # encodings forced full-range grids is wider
                        # than the offset-encoded default
                        lo_run, grids = self._slice_mesh_part(
                            sliced, b, -1, int(got["lo"][b]),
                            got["width"], spec)
                        out.append((s, (winner_values, lo_run, grids)))
                    return out

                for s, part in await self._run_pool(plan.pool,
                                                    winner_round):
                    seg_parts.setdefault(s, []).append(part)
                    cells += sum(int(v.shape[0] * v.shape[1])
                                 for v in part[2].values())
        except NotFoundError:
            raise  # compaction race: the caller replans
        except Exception as exc:  # noqa: BLE001 — counted downgrade;
            # nothing has been yielded (all-or-nothing), so the full
            # -width path below replaces the winner slices wholesale
            note_mesh_fallback("mesh_error"
                               if not isinstance(exc, _MeshFallback)
                               else exc.reason)
            logger.warning("mesh top-k winner pass failed (%s); "
                           "serving full-width parts", exc)
            async for out in self._yield_chunks_as_parts(chunks, spec,
                                                         plan):
                yield out
            return
        _MESH_PART_CELLS.inc(cells)
        _MESH_TOPK.inc()
        for s in sorted(seg_parts):
            yield s, seg_parts[s]

    async def _yield_chunks_as_parts(self, chunks: list,
                                     spec: AggregateSpec,
                                     plan: ScanPlan):
        """Downgrade path for the top-k mesh route: flush the already
        -collected window chunks through the normal mesh round (its
        own per-round fallback included) and yield per-segment full
        parts — finalize's host combine_top_k ranks them instead."""
        seg_parts: dict[int, list] = {}
        for ci, chunk in enumerate(chunks):
            deadline_checkpoint()
            flushed = await self._run_pool(
                plan.pool, self._flush_mesh_round, chunk, spec, plan,
                ci)
            for s, part, _repay in flushed:
                if part is not None:
                    seg_parts.setdefault(s, []).append(part)
        for s in sorted(seg_parts):
            yield s, seg_parts[s]

    def finalize_aggregate(self, parts: list, spec: AggregateSpec,
                           top_k=None):
        """Combine per-window parts into the user-facing grids.

        Mode-dispatched through storage/combine.py ([scan.combine]):
        the sparse fold pastes parts straight into the output buffers;
        `dense` keeps the pre-sparse accumulator fold as the
        bit-identity control.  A `top_k` spec pushes the ranking down
        into combine (combine_top_k) so only the k winners' rows are
        ever materialized — the full groups x buckets grid is never
        built (the north-star 1B top-k's bound).  In `dense` mode the
        pushdown is OFF too: the control materializes the full grid and
        ranks host-side (apply_top_k), so the mode flag A/Bs the whole
        pre-change path, not just the fold."""
        mode = self.config.scan.combine.mode
        t0 = time.perf_counter()
        try:
            if top_k is not None and mode != "dense":
                # empty-group drop is built into the pushdown (groups
                # are dropped before ranking, same cells as the dense
                # drop below)
                group_values, grids = combine_mod.combine_top_k(
                    parts, spec.num_buckets, spec.which, top_k)
            else:
                group_values, grids = combine_mod.combine_parts(
                    parts, spec.num_buckets, which=spec.which, mode=mode)
                # drop groups with no row in ANY bucket: the aligned
                # fast path omits the ts leaf (query_downsample), so
                # boundary-segment rows outside [start, end) can
                # register a group whose every cell is empty — without
                # this the aligned and ts-leaf paths return different
                # tsid sets for the same data
                if len(group_values):
                    nonzero = grids["count"].sum(axis=1) > 0
                    if not nonzero.all():
                        group_values = group_values[nonzero]
                        grids = {k: v[nonzero] for k, v in grids.items()}
                if top_k is not None:
                    from horaedb_tpu.storage.plan import apply_top_k

                    group_values, grids = apply_top_k(group_values,
                                                      grids, top_k)
        finally:
            dt = time.perf_counter() - t0
            _STAGE_SECONDS["combine"].observe(dt)
            trace_add("stage_combine_ms", dt * 1e3)
        # last_ts is computed relative to range_start on device; expose it
        # as ABSOLUTE time so all downsample paths share one unit
        if len(group_values) and "last_ts" in grids:
            grids["last_ts"] = grids["last_ts"] + spec.range_start
        return group_values, grids

    def _window_groups(self, out_batch: encode.DeviceBatch,
                       spec: AggregateSpec, plan: ScanPlan):
        """Shared per-window prep: (group_values, gid_full, ts_shift) or
        None when the window contributes nothing.  Memoized on the batch
        (keyed by group column + full predicate) so repeat queries over
        scan-cached windows skip the dense-ification.  The memo value is
        RANGE-INDEPENDENT (values + gid); only the two-int shift depends
        on range_start and is derived per call — so varied-range queries
        over the same windows still hit the memo."""
        memo_key = ("window_groups", spec.group_col, spec.ts_col,
                    filter_ops.canonical_predicate_key(plan.predicate))
        # single atomic .get(): this now runs on worker-pool threads, so
        # a check-then-read against a concurrent clear() could KeyError;
        # duplicate computation on a lost race is benign (same result)
        miss = object()
        cached_val = out_batch.memo.get(memo_key, miss)
        if cached_val is miss:
            cached_val = self._window_groups_uncached(out_batch, spec, plan)
            # charge the capacity-sized gid only: group_values is a tiny
            # host array, and the allowance must fit this entry (4B/row)
            # PLUS a dev_cols entry (12B/row) for the same spec
            nbytes = 0 if cached_val is None else int(cached_val[1].nbytes)
            _memo_store(out_batch, memo_key, cached_val, nbytes)
        if cached_val is None:
            return None
        group_values, gid_full, epoch = cached_val
        shift = epoch - spec.range_start  # host_ts = dev_ts + epoch
        ensure(abs(shift) < 2**31, "query range too far from segment epoch")
        return group_values, gid_full, shift

    def _window_groups_uncached(self, out_batch: encode.DeviceBatch,
                                spec: AggregateSpec, plan: ScanPlan):
        k = out_batch.n_valid
        cap = out_batch.capacity
        if k == 0:
            return None
        keep = _iota(cap) < k
        mask_all = True
        if plan.predicate is not None and not plan.pushed_complete:
            mask = np.asarray(
                filter_ops.eval_predicate(plan.predicate, out_batch))
            mask_all = bool(mask[:k].all())
            keep = keep & mask
            # fully-filtered window: empty result, NOT an encoding error
            # (the ensure below must only fire for windows with rows)
            if not mask_all and not keep.any():
                return None

        ts_enc = out_batch.encodings[spec.ts_col]
        ensure(ts_enc.kind in ("offset", "numeric"),
               f"aggregate needs arithmetic timestamps, got "
               f"{ts_enc.kind!r} encoding for {spec.ts_col!r}")
        # dense group ids: one int32 column roundtrips to host (cheap),
        # values/timestamps stay on device; the dense-id array itself is
        # memoized DEVICE-resident so repeat queries over cached windows
        # upload nothing
        codes = np.asarray(out_batch.columns[spec.group_col])
        enc_g = out_batch.encodings[spec.group_col]
        if (mask_all and enc_g.kind == "dict" and len(enc_g.dictionary)
                and int(codes[:k].min()) == 0
                and int(codes[:k].max()) == len(enc_g.dictionary) - 1):
            # dict-encoded group column whose window uses the WHOLE
            # dictionary (single-window segments — sidecar loads and
            # encode_batch both produce dense sorted-rank codes): the
            # codes already ARE the dense ids and the dictionary the
            # sorted group values — skip the per-window np.unique, the
            # cold scan's hottest host op.  Windows spanning a code
            # subrange (pk-windowed big segments) fail the min/max
            # check and take the exact path below.
            gid_full = np.where(keep, codes, -1).astype(np.int32)
            group_values = enc_g.dictionary
            if isinstance(out_batch.columns[spec.group_col], np.ndarray):
                return group_values, gid_full, ts_enc.epoch
            return group_values, jnp.asarray(gid_full), ts_enc.epoch
        sel_codes = codes[keep]
        if len(sel_codes) == 0:
            return None
        uniq, dense = np.unique(sel_codes, return_inverse=True)
        gid_full = np.full(cap, -1, dtype=np.int32)
        gid_full[keep] = dense.astype(np.int32)

        group_values = _decode_group_values(
            uniq, out_batch.encodings[spec.group_col])
        # the memo stores the window's ts EPOCH, not a shift: the caller
        # derives shift = epoch - range_start so the memo entry serves
        # every query range.  Host windows keep a host gid (stacked +
        # uploaded per round); device windows memoize it device-resident
        if isinstance(out_batch.columns[spec.group_col], np.ndarray):
            return group_values, gid_full, ts_enc.epoch
        return group_values, jnp.asarray(gid_full), ts_enc.epoch

    def _dev_scalar(self, val: int, kind: str = "i32"):
        """Memoized tiny device constants: 'i32' scalar or 'arr1'
        one-element int32 array."""
        key = (kind, int(val))
        a = self._scalar_cache.get(key)
        if a is None:
            a = (jnp.asarray([int(val)], dtype=jnp.int32) if kind == "arr1"
                 else jnp.int32(val))
            self._scalar_cache[key] = a
        return a

    def _stack_cache_get(self, key: tuple, windows_now: tuple):
        with self._stack_cache_lock:
            hits, misses = _stack_counters(key)
            entry = self._stack_cache.get(key)
            if entry is None:
                self._stack_cache_misses += 1
                misses.inc()
                return None
            stored_refs, arrays, nbytes = entry
            # WEAK references: the entry must not pin evicted windows'
            # column buffers in HBM; a dead ref or changed composition
            # means the round was re-read — drop the stale stack
            if len(stored_refs) != len(windows_now) or not all(
                    ref() is w for ref, w in zip(stored_refs, windows_now)):
                del self._stack_cache[key]
                self._stack_cache_bytes -= nbytes
                self._stack_cache_misses += 1
                misses.inc()
                return None
            self._stack_cache.move_to_end(key)
            self._stack_cache_hits += 1
            hits.inc()
            return arrays

    def _stack_cache_put(self, key: tuple, windows_now: tuple,
                         arrays: tuple) -> None:
        nbytes = sum(int(a.nbytes) for a in arrays)
        refs = tuple(weakref.ref(w) for w in windows_now)
        with self._stack_cache_lock:
            if nbytes > self._stack_cache_max:
                return
            old = self._stack_cache.pop(key, None)
            if old is not None:
                self._stack_cache_bytes -= old[2]
            self._stack_cache[key] = (refs, arrays, nbytes)
            self._stack_cache_bytes += nbytes
            while (self._stack_cache_bytes > self._stack_cache_max
                   and self._stack_cache):
                _, (_, _, evicted) = self._stack_cache.popitem(last=False)
                self._stack_cache_bytes -= evicted

    def _window_grid_width(self, spec: AggregateSpec) -> int:
        """Static per-window grid width: a window's rows span at most one
        segment, so its buckets span at most segment_ms/bucket_ms (+2
        for epoch/range misalignment).  Per-window grids cover only that
        local range and carry a bucket offset into the host combine —
        a full-query-width grid per window would move groups x
        total_buckets cells to host PER WINDOW (10s of MB each on long
        ranges) instead of groups x window_span."""
        need = self.segment_duration_ms // max(1, spec.bucket_ms) + 2
        return int(min(spec.num_buckets,
                       max(8, 1 << (need - 1).bit_length())))

    def _devcol_stack_ok(self) -> bool:
        """Whether host windows should stack from per-window memoized
        DEVICE columns instead of a fresh numpy stack + bulk upload.
        On accelerators the device copies make varied-range queries
        (distinct specs -> full-stack misses) re-stack cached HBM arrays
        with only KB-sized remap/shift uploads; on XLA-CPU the numpy
        stack is a memcpy and the extra dispatches would only slow it.
        Meshed scans keep the sharded bulk upload (device copies would
        live on one device).  HORAEDB_DEVCOL_STACK=1/0 forces (tests
        cover the device-col path on the CPU backend)."""
        if self.mesh is not None:
            return False
        import os

        forced = os.environ.get("HORAEDB_DEVCOL_STACK", "")
        if forced in ("0", "1"):
            return forced == "1"
        import jax

        return jax.default_backend() != "cpu"

    def _host_agg_ok(self) -> bool:
        """Whether window rounds aggregate with the numpy twin instead of
        the vmap device kernel (_batched_window_partials_jit).  Default:
        host on the CPU backend (numpy bincount beats XLA-CPU's
        segmented scatters ~20x), device elsewhere.  HORAEDB_HOST_AGG=1/0
        forces, mirroring HORAEDB_DEVCOL_STACK, so CPU CI keeps coverage
        of the device parts kernel."""
        if self.mesh is not None:
            return False
        return host_agg_default()

    def _window_device_cols(self, w: encode.DeviceBatch,
                            spec: AggregateSpec, plan: ScanPlan,
                            gid: np.ndarray):
        """(ts, gid, value) device copies of one host window at its own
        capacity — all range-independent, memoized on the window (same
        MEMO_SLOTS bound the scan cache charges for)."""
        memo_key = ("dev_cols", spec.group_col, spec.ts_col,
                    spec.value_col,
                    filter_ops.canonical_predicate_key(plan.predicate))
        miss = object()
        got = w.memo.get(memo_key, miss)
        if got is not miss:
            return got
        out = (jnp.asarray(np.asarray(w.columns[spec.ts_col],
                                      dtype=np.int32)),
               jnp.asarray(np.asarray(gid, dtype=np.int32)),
               jnp.asarray(np.asarray(w.columns[spec.value_col],
                                      dtype=np.float32)))
        _memo_store(w, memo_key, out, sum(int(a.nbytes) for a in out))
        return out

    @staticmethod
    def _round_stack_key(seg0: int, spec: AggregateSpec, plan: ScanPlan,
                         batch_w: int, cap: int, g_pad: int, width: int,
                         space_fp: tuple) -> tuple:
        """Stack-LRU identity of one round's RANGE-DEPENDENT small
        arrays (remap/shift/lo — KBs; shared with the fused replay
        recording, so the key must be computed ONE way)."""
        return (seg0, spec.group_col, spec.ts_col,
                spec.value_col, spec.bucket_ms, spec.range_start,
                batch_w, cap, g_pad, width, space_fp,
                filter_ops.canonical_predicate_key(plan.predicate))

    @staticmethod
    def _col_stack_key(windows_now: tuple, spec: AggregateSpec,
                       plan: ScanPlan, batch_w: int, cap: int) -> tuple:
        """Stack-LRU identity of one round's RANGE-INDEPENDENT stacked
        columns (ts/gid/val — the big HBM arrays).  Keyed by the window
        object ids (validated by identity refs on get, so id reuse after
        eviction can't alias), NOT by range/bucket/group-space: every
        query whose round has the same composition reuses the big
        stacks and only rebuilds the small remap/shift/lo arrays."""
        return ("colstack", tuple(id(w) for w in windows_now),
                spec.group_col, spec.ts_col, spec.value_col, batch_w, cap,
                filter_ops.canonical_predicate_key(plan.predicate))

    def _build_round_stacks(self, items: list, spec: AggregateSpec,
                            plan: ScanPlan, batch_w: int, cap: int,
                            g_pad: int, width: int,
                            group_space: np.ndarray, local_ok: bool,
                            stack_key: Optional[tuple] = None,
                            put=None, key_salt: tuple = ()):
        """Stack one round of windows for the aggregation program,
        tunnel-aware:

        - HOST windows (the default merge layout) stack in numpy and
          cross to the device as ONE transfer per array — not one per
          window per column — or, on accelerators, re-stack per-window
          memoized device columns (_window_device_cols) so only the
          FIRST query over a window pays the upload;
        - remap/shift/lo are placed on device HERE and cached, so a
          full cache hit issues ZERO transfers;
        - under a mesh, placement uses the segment-axis sharding
          directly (cached rounds live sharded — re-placing per query
          would re-pay the transfer).

        Stacked inputs live in a reader-level LRU split in TWO entries:
        the big ts/gid/val stacks under a range-independent key
        (_col_stack_key — shared by every query range over the same
        round composition) and the small remap/shift/lo arrays under
        the full range-dependent key.  Each entry carries the round's
        window OBJECTS: a hit requires the exact same DeviceBatches
        (object identity — stable while scan-cached), which both
        prevents id-reuse collisions and makes entries
        self-invalidating; byte accounting and eviction live in
        _stack_cache_put.

        Returns (ts_s, gid_s, val_s, remap_d, shift_d, lo_d, lo_host).
        """
        # an explicit `put` (the 2-D mesh rounds pass shard_time_axis)
        # keys its entries with `key_salt` so sharded and single-device
        # stacks of one composition never alias in the LRU
        sharded = put is not None
        if put is None:
            if self.mesh is not None:
                from horaedb_tpu.parallel.scan import shard_leading_axis

                put = functools.partial(shard_leading_axis, self.mesh)
                sharded = True
            else:
                put = deviceprof.device_put
        if stack_key is None:
            space_fp = (len(group_space), hash(group_space.tobytes()))
            stack_key = self._round_stack_key(items[0][0], spec, plan,
                                              batch_w, cap, g_pad, width,
                                              space_fp)
        stack_key = stack_key + key_salt
        windows_now = tuple(it[1] for it in items)
        col_key = self._col_stack_key(windows_now, spec, plan, batch_w,
                                      cap) + key_salt
        cols = self._stack_cache_get(col_key, windows_now)
        small = self._stack_cache_get(stack_key, windows_now)
        if cols is not None and small is not None:
            return cols + small
        t_build = time.perf_counter()
        built_bytes = 0
        host_rows = all(
            isinstance(it[1].columns[spec.ts_col], np.ndarray)
            and isinstance(it[2][1], np.ndarray) for it in items)
        if cols is None:
            if host_rows and (sharded or not self._devcol_stack_ok()):
                ts_m = np.zeros((batch_w, cap), dtype=np.int32)
                gid_m = np.full((batch_w, cap), -1, dtype=np.int32)
                val_m = np.zeros((batch_w, cap), dtype=np.float32)
                for d, (_seg_start, w, (_values, gid, _sh)) in \
                        enumerate(items):
                    ts_m[d, : w.capacity] = w.columns[spec.ts_col]
                    gid_m[d, : w.capacity] = gid
                    val_m[d, : w.capacity] = w.columns[spec.value_col]
                ts_s, gid_s, val_s = put(ts_m), put(gid_m), put(val_m)
            else:
                ts_rows, gid_rows, val_rows = [], [], []
                for d, (_seg_start, w, (_values, gid_dev, _sh)) in \
                        enumerate(items):
                    if host_rows:
                        # range-independent device copies, memoized per
                        # window: a varied-range query re-stacks cached
                        # device arrays instead of re-uploading the rows
                        ts_d, gid_dev, val_d = self._window_device_cols(
                            w, spec, plan, gid_dev)
                    else:
                        ts_d = w.columns[spec.ts_col]
                        val_d = w.columns[spec.value_col]
                    if w.capacity < cap:
                        pad_n = cap - w.capacity
                        ts_d = jnp.pad(ts_d, (0, pad_n))
                        gid_dev = jnp.pad(gid_dev, (0, pad_n),
                                          constant_values=-1)
                        val_d = jnp.pad(val_d, (0, pad_n))
                    ts_rows.append(jnp.asarray(ts_d))
                    gid_rows.append(jnp.asarray(gid_dev))
                    val_rows.append(jnp.asarray(val_d))
                if len(items) < batch_w:  # pad round with no-op windows
                    empty_gid = jnp.full(cap, -1, dtype=jnp.int32)
                    zeros_i = jnp.zeros(cap, dtype=jnp.int32)
                    zeros_f = jnp.zeros(cap, dtype=jnp.float32)
                    for _ in range(batch_w - len(items)):
                        ts_rows.append(zeros_i)
                        gid_rows.append(empty_gid)
                        val_rows.append(zeros_f)
                ts_s = jnp.stack(ts_rows)
                gid_s = jnp.stack(gid_rows)
                val_s = jnp.stack(val_rows)
                if sharded:
                    ts_s, gid_s, val_s = put(ts_s), put(gid_s), put(val_s)
            cols = (ts_s, gid_s, val_s)
            built_bytes += sum(int(a.nbytes) for a in cols)
            self._stack_cache_put(col_key, windows_now, cols)
        if small is None:
            remap = np.zeros((batch_w, g_pad), dtype=np.int32)
            shift = np.zeros(batch_w, dtype=np.int32)
            lo = np.zeros(batch_w, dtype=np.int32)
            for d, (_seg_start, _w, (values, _gid, sh)) in enumerate(items):
                remap[d, : len(values)] = np.searchsorted(group_space,
                                                          values)
                shift[d] = sh
                if local_ok:
                    lo[d] = max(0, sh // spec.bucket_ms)
            small = (put(remap), put(shift), put(lo), lo)
            built_bytes += sum(int(a.nbytes) for a in small[:3])
            self._stack_cache_put(stack_key, windows_now, small)
        _STAGE_SECONDS["stack_build"].observe(time.perf_counter() - t_build)
        _STAGE_BYTES["stack_build"].inc(built_bytes)
        return cols + small

    def _flush_window_batch(self, items: list, spec: AggregateSpec,
                            plan: ScanPlan) -> list:
        """Aggregate one round of windows (possibly from several
        segments) as a single compiled program, staying device-resident
        between merge and aggregate.

        items: [(seg_start, window, (group_values, gid_dev, shift))].
        Returns [(seg_start, (round_values, bucket_lo, partial grids))]
        in item order; every part shares the round's union group values
        (rows a window didn't touch have count 0 and fold away in the
        combiner).  Rounds are padded to the full batch width with empty
        windows so one program shape serves every flush.

        Device-decode entries (prep None, window a DevicePart) pass
        through in position — their grids were computed by the fused
        dispatch — so a segment's parts fold in window order whichever
        route each window took."""
        has_device = any(prep is None for _s, _w, prep in items)
        if has_device:
            out: list = [None] * len(items)
            host_pos: list[int] = []
            host_items: list = []
            for i, (s, w, prep) in enumerate(items):
                if prep is None:
                    if w.part is not None:
                        out[i] = (s, w.part)
                else:
                    host_pos.append(i)
                    host_items.append((s, w, prep))
            if host_items:
                for i, p in zip(host_pos, self._flush_host_round(
                        host_items, spec, plan)):
                    out[i] = p
            return [p for p in out if p is not None]
        return [p for p in self._flush_host_round(items, spec, plan)
                if p is not None]

    def _flush_host_round(self, items: list, spec: AggregateSpec,
                          plan: ScanPlan) -> list:
        """One round of HOST-decoded windows aggregated by the batched
        kernel (or its numpy twin) — returns one entry per item, None
        for windows that contribute nothing."""
        if (not plan.force_xla_agg) and self._host_agg_ok() and all(
                isinstance(it[1].columns[spec.ts_col], np.ndarray)
                for it in items):
            # XLA-CPU's segmented scatters run ~20x slower than numpy's
            # bincount and there is no transfer to amortize — aggregate
            # where the rows already live (the accelerator trade-off is
            # the opposite; see _build_round_stacks).  Per-window partial
            # grids are memoized range-independently, so repeat/varied
            # queries slice cached grids instead of re-scanning rows.
            return _host_window_partials(items, spec, plan)

        if self.mesh is not None:
            batch_w = self.mesh.devices.size
        else:
            # pow2 width >= len(items): full rounds share one program,
            # tail/small queries use narrower ones (bounded variants)
            batch_w = min(max(1, self.config.scan.agg_batch_windows),
                          1 << (len(items) - 1).bit_length())
        round_values = np.unique(np.concatenate([it[2][0] for it in items]))
        g = len(round_values)
        g_pad = max(8, 1 << (g - 1).bit_length())
        cap = max(it[1].capacity for it in items)
        # offset-encoded ts columns bound each window's bucket range (the
        # epoch is the segment table's min ts); anything else falls back
        # to full-range grids with lo=0
        local_ok = all(
            it[1].encodings[spec.ts_col].kind == "offset" for it in items)
        width = self._window_grid_width(spec) if local_ok \
            else spec.num_buckets

        ts_s, gid_s, val_s, remap_d, shift_d, lo_dev, lo = \
            self._build_round_stacks(items, spec, plan, batch_w, cap,
                                     g_pad, width, round_values, local_ok)
        total = self._dev_scalar(spec.num_buckets)
        t_dev = time.perf_counter()

        if self.mesh is not None:
            from horaedb_tpu.parallel.scan import sharded_remap_partials

            # memoize the compiled program per grid shape — rebuilding
            # the shard_map closure would recompile every round
            fn_key = (g_pad, width, spec.which)
            fn = self._mesh_agg_fns.get(fn_key)
            if fn is None:
                fn = sharded_remap_partials(self.mesh, num_groups=g_pad,
                                            num_buckets=width,
                                            which=spec.which)
                self._mesh_agg_fns[fn_key] = fn
            stacked = fn(ts_s, gid_s, val_s, remap_d, shift_d, lo_dev, total,
                         self._dev_scalar(spec.bucket_ms, "arr1"))
        else:
            stacked = _batched_window_partials_jit(
                ts_s, gid_s, val_s, remap_d, shift_d,
                lo_dev, total, self._dev_scalar(spec.bucket_ms),
                num_groups=g_pad, num_buckets=width, which=spec.which)
        # per-window partials fold on host in f64 (bit-equal to the
        # single-window path); padding windows are sliced away
        host = {k: np.asarray(v) for k, v in stacked.items()}
        _STAGE_SECONDS["device_aggregate"].observe(
            time.perf_counter() - t_dev)
        parts = []
        for d in range(len(items)):
            lo_d = int(lo[d])
            w_eff = min(width, spec.num_buckets - lo_d)
            grids = {k: v[d, :g, :w_eff] for k, v in host.items()}
            if "last_ts" in grids:
                # re-base window-local last_ts to range_start-relative so
                # parts with different offsets compare correctly
                lt = grids["last_ts"].astype(np.int64)
                grids["last_ts"] = np.where(
                    grids["count"] > 0, lt + lo_d * spec.bucket_ms, lt)
            parts.append((items[d][0], (round_values, lo_d, grids)))
        return parts

    def _merge_on_host(self, batch: pa.RecordBatch,
                       plan: ScanPlan) -> pa.RecordBatch:
        pk_names = self._pk_names_in(batch.schema.names)
        sort_keys = [(n, "ascending") for n in pk_names + [SEQ_COLUMN_NAME]]
        idx = pa.compute.sort_indices(batch, sort_keys=sort_keys)
        batch = batch.take(idx)
        names = batch.schema.names
        value_idxes = [names.index(n) for n in names
                       if n not in pk_names and n != SEQ_COLUMN_NAME]
        op = build_operator(plan.mode, value_idxes)
        # explicit indices: a projection may have reordered columns
        merged = op.merge_sorted_batch(
            batch, pk_indices=[names.index(n) for n in pk_names])
        # fully-pushed PK-only predicates were applied at read time and
        # cannot interact with the merge — same skip as the window paths
        if plan.predicate is not None and not plan.pushed_complete:
            mask = _eval_predicate_host(plan.predicate, merged)
            merged = merged.filter(pa.array(mask))
        return merged


_ACC_TS_MIN = jnp.int32(-(2**31))


# cells ceiling for a memoized full-span window grid (~256 MB of f32
# per aggregate); beyond it the window recomputes range-clipped,
# unmemoized grids instead of allocating the full span
_HOST_GRID_MAX_CELLS = 64 << 20


def host_agg_default() -> bool:
    """THE host-vs-device aggregation default, shared by every numpy
    -twin gate (reader windows, engine chunked downsample): host on the
    CPU backend, device elsewhere; HORAEDB_HOST_AGG=1/0 forces."""
    import os

    forced = os.environ.get("HORAEDB_HOST_AGG", "")
    if forced in ("0", "1"):
        return forced == "1"
    return jax.default_backend() == "cpu"


def host_cell_grids(cell: np.ndarray, vv: np.ndarray, tsv, ncells: int,
                    want) -> dict:
    """Shared host accumulation cores over flat grid cells, used by the
    window partials below and the engine's chunked downsample twin:
    {"count" int64, "sum"? f64, "min"? (+inf fill), "max"? (-inf fill),
    "last"? (lt int64 ts-per-cell with _ACC_TS_MIN fill, li int64
    position-in-vv per cell with -1 fill)} — callers apply their own
    empty-cell conventions.  `tsv` is only read for "last"."""
    out = {"count": np.bincount(cell, minlength=ncells)}
    if "sum" in want:
        out["sum"] = np.bincount(cell, weights=vv, minlength=ncells)
    if "min" in want:
        mn = np.full(ncells, np.inf)
        np.minimum.at(mn, cell, vv)
        out["min"] = mn
    if "max" in want:
        mx = np.full(ncells, -np.inf)
        np.maximum.at(mx, cell, vv)
        out["max"] = mx
    if "last" in want:
        lt = np.full(ncells, int(_ACC_TS_MIN), dtype=np.int64)
        np.maximum.at(lt, cell, tsv)
        at_max = tsv == lt[cell]
        pos = np.flatnonzero(at_max)  # later position wins cell ties
        li = np.full(ncells, -1, dtype=np.int64)
        np.maximum.at(li, cell[at_max], pos)
        out["last"] = (lt, li)
    return out


def _host_window_full_grids(w: encode.DeviceBatch, values: np.ndarray,
                            gid: np.ndarray, epoch: int, phase: int,
                            bucket_ms: int, want: frozenset,
                            ts_col: str, value_col: str,
                            clip: Optional[tuple] = None):
    """One window's partial grids over its FULL ts span, in absolute
    phase-shifted buckets A = (host_ts - phase) // bucket_ms — no query
    range anywhere, so the result is reusable by every query sharing
    (bucket_ms, phase).  Returns (A0, grids): grids cover absolute
    buckets [A0, A0 + W); last_ts is ABSOLUTE host ms (int64, I32_MIN
    sentinel in empty cells).

    `clip=(lo_ms, hi_ms)` bounds the rows to a host-ts range first —
    the fallback shape when the unclipped span would exceed
    _HOST_GRID_MAX_CELLS (returns the string "toobig" in that case so
    the caller can re-invoke clipped and skip the memo)."""
    g = len(values)
    ts_abs = np.asarray(w.columns[ts_col]).astype(np.int64) + epoch
    vals = np.asarray(w.columns[value_col], dtype=np.float64)
    valid = gid >= 0
    if clip is not None:
        valid = valid & (ts_abs >= clip[0]) & (ts_abs < clip[1])
    if not valid.any():
        return None
    A = (ts_abs - phase) // bucket_ms
    A0 = int(A[valid].min())
    W = int(A[valid].max()) - A0 + 1
    ncells = g * W
    if clip is None and ncells > _HOST_GRID_MAX_CELLS:
        return "toobig"
    cell = (gid.astype(np.int64) * W + (A - A0))[valid]
    vv = vals[valid]
    # +/-inf identities for untouched min/max cells — masked rows land
    # in the device kernel's overflow segment, so empty cells read the
    # segmented op's identity, not the F32_MAX row filler
    cores = host_cell_grids(cell, vv, ts_abs[valid], ncells, want)
    grids = {"count": cores["count"].astype(np.float32).reshape(g, W)}
    for k in ("sum", "min", "max"):
        if k in cores:
            grids[k] = cores[k].astype(np.float32).reshape(g, W)
    if "last" in cores:
        lt, li = cores["last"]
        last = np.zeros(ncells)
        has = li >= 0
        last[has] = vv[li[has]]
        grids["last"] = last.astype(np.float32).reshape(g, W)
        grids["last_ts"] = lt.reshape(g, W)
    return A0, grids


def _host_window_partials(items: list, spec: AggregateSpec,
                          plan: ScanPlan) -> list:
    """numpy twin of _batched_window_partials_jit for the CPU backend.

    Each window's full-span grids are memoized RANGE-INDEPENDENTLY on
    the window (keyed by bucket width + range phase + predicate +
    aggregates); a query only slices the cached grids to its bucket
    range and rebases last_ts — repeat AND varied-range queries over
    scan-cached windows skip row aggregation entirely.  Grid
    conventions (combine identities, f32 cells, later-row last
    tie-break) match the device kernel, so combine_aggregate_parts
    cannot tell the paths apart.  Returns one entry per item —
    (seg_start, (values, lo, grids)) or None for a window that
    contributes nothing — aligned so _flush_window_batch can merge
    routes by position."""
    t_dev = time.perf_counter()
    want = frozenset(spec.which) | (
        {"sum"} if "avg" in spec.which else set())
    phase = spec.range_start % spec.bucket_ms
    q0 = (spec.range_start - phase) // spec.bucket_ms
    parts = []
    for seg_start, w, (values, gid_full, sh) in items:
        epoch = sh + spec.range_start
        key = ("host_partials", spec.ts_col, spec.value_col,
               spec.group_col, filter_ops.canonical_predicate_key(
                   plan.predicate), spec.bucket_ms, phase, want)
        miss = object()
        full = w.memo.get(key, miss)
        if full is miss:
            full = _host_window_full_grids(
                w, values, np.asarray(gid_full), epoch, phase,
                spec.bucket_ms, want, spec.ts_col, spec.value_col)
            if full == "toobig":
                # full-span grid too large to hold: compute clipped to
                # the query's grid bounds, and don't memoize (the clip
                # makes it range-dependent)
                full = _host_window_full_grids(
                    w, values, np.asarray(gid_full), epoch, phase,
                    spec.bucket_ms, want, spec.ts_col, spec.value_col,
                    clip=(spec.range_start, spec.range_start
                          + spec.num_buckets * spec.bucket_ms))
            else:
                nbytes = 0 if full is None else sum(
                    int(a.nbytes) for a in full[1].values())
                _memo_store(w, key, full, nbytes)
        if full is None:
            parts.append(None)
            continue
        A0, grids_full = full
        W = grids_full["count"].shape[1]
        # trim the absolute-bucket grid to the query's range
        lo_q = A0 - q0
        cut = max(0, -lo_q)
        lo = max(0, lo_q)
        w_eff = min(W - cut, spec.num_buckets - lo)
        if w_eff <= 0:
            parts.append(None)
            continue
        sl = slice(cut, cut + w_eff)
        grids = {k: v[:, sl] for k, v in grids_full.items()
                 if k != "last_ts"}
        if "last_ts" in grids_full:
            lt = grids_full["last_ts"][:, sl]
            # memo holds ABSOLUTE host ms; parts carry range-relative
            grids["last_ts"] = np.where(grids["count"] > 0,
                                        lt - spec.range_start,
                                        int(_ACC_TS_MIN))
        parts.append((seg_start, (values, lo, grids)))
    _STAGE_SECONDS["device_aggregate"].observe(time.perf_counter() - t_dev)
    return parts


@deviceprof.jit(static_argnames=("num_groups", "num_buckets", "which"))
def _fused_acc_init_jit(*, num_groups: int, num_buckets: int, which: tuple):
    """Query-global device accumulator grids with combine-identity
    inits (matching ops.downsample partial conventions)."""
    shape = (num_groups, num_buckets)
    want = set(which)
    if "avg" in want:
        want.add("sum")
    acc = {"count": jnp.zeros(shape, jnp.float32)}
    if "sum" in want:
        acc["sum"] = jnp.zeros(shape, jnp.float32)
    if "min" in want:
        acc["min"] = jnp.full(shape, jnp.finfo(jnp.float32).max, jnp.float32)
    if "max" in want:
        acc["max"] = jnp.full(shape, -jnp.finfo(jnp.float32).max,
                              jnp.float32)
    if "last" in want:
        acc["last"] = jnp.zeros(shape, jnp.float32)
        acc["last_ts"] = jnp.full(shape, _ACC_TS_MIN, jnp.int32)
    return acc


@deviceprof.jit(static_argnames=("num_groups", "width", "which"),
                donate_argnums=(0,))
def _fused_round_accumulate_jit(acc, ts, gid, vals, remap, shift, lo, total,
                                bucket_ms, *, num_groups: int, width: int,
                                which: tuple):
    """One round of windows aggregated AND scattered into the
    query-global accumulator, entirely on device.

    This is the tunnel-aware replacement for the per-flush host fold:
    instead of downloading (B, G, width) partial grids every round
    (device->host is the scarce direction), each round's window-local
    grids land in `acc` via bucket-offset scatters and only the final
    grids ever leave the device.  `acc` is donated — the accumulator
    updates in place round over round.

    Correctness of the scatter combine: count/sum add their identity
    (0) for cells a window didn't touch; min/max scatter through
    .at[].min/.max with +/-F32_MAX identities; `last` does a sequential
    gather-compare-scatter per window (window order = segment order, so
    `>=` keeps later-window ties, matching the host combiner)."""
    from horaedb_tpu.ops import downsample

    def one(ts_b, gid_b, vals_b, remap_b, shift_b, lo_b):
        return downsample.window_local_partials(
            ts_b, gid_b, vals_b, remap_b, shift_b, lo_b, total, bucket_ms,
            num_groups=num_groups, num_buckets=width, which=which)

    p = jax.vmap(one)(ts, gid, vals, remap, shift, lo)
    w_iota = jnp.arange(width, dtype=jnp.int32)

    def body(d, acc):
        cols = lo[d] + w_iota
        out = dict(acc)
        out["count"] = acc["count"].at[:, cols].add(p["count"][d],
                                                    mode="drop")
        if "sum" in acc:
            out["sum"] = acc["sum"].at[:, cols].add(p["sum"][d], mode="drop")
        if "min" in acc:
            out["min"] = acc["min"].at[:, cols].min(p["min"][d], mode="drop")
        if "max" in acc:
            out["max"] = acc["max"].at[:, cols].max(p["max"][d], mode="drop")
        if "last" in acc:
            # fill_value must be a hashable Python scalar (jaxpr param)
            cur_ts = acc["last_ts"].at[:, cols].get(mode="fill",
                                                    fill_value=-(2**31))
            cur_last = acc["last"].at[:, cols].get(mode="fill",
                                                   fill_value=0.0)
            win_has = p["count"][d] > 0
            win_ts = jnp.where(win_has,
                               p["last_ts"][d] + lo[d] * bucket_ms,
                               _ACC_TS_MIN)
            take = win_has & (win_ts >= cur_ts)
            out["last"] = acc["last"].at[:, cols].set(
                jnp.where(take, p["last"][d], cur_last), mode="drop")
            out["last_ts"] = acc["last_ts"].at[:, cols].set(
                jnp.where(take, win_ts, cur_ts), mode="drop")
        return out

    return jax.lax.fori_loop(0, ts.shape[0], body, acc)


@deviceprof.jit(static_argnames=("which",))
def _fused_finalize_jit(acc: dict, which: tuple) -> dict:
    """Device finalize of the fused accumulator.  Conventions match
    combine_aggregate_parts: min/max empty cells read +/-inf, avg/last
    NaN.  last_ts stays int32 (range-relative) — the absolute float
    conversion needs int64 range and happens on host."""
    count = acc["count"]
    empty = count == 0
    nan = jnp.float32(jnp.nan)
    requested = set(which) | {"count"}
    out = {"count": count}
    if "sum" in acc and "sum" in requested:
        out["sum"] = acc["sum"]
    if "sum" in acc and "avg" in requested:
        out["avg"] = jnp.where(empty, nan,
                               acc["sum"] / jnp.maximum(count, 1.0))
    if "min" in acc and "min" in requested:
        out["min"] = jnp.where(empty, jnp.float32(jnp.inf), acc["min"])
    if "max" in acc and "max" in requested:
        out["max"] = jnp.where(empty, -jnp.float32(jnp.inf), acc["max"])
    if "last" in acc and "last" in requested:
        out["last"] = jnp.where(empty, nan, acc["last"])
        out["last_ts"] = acc["last_ts"]
    return out


@deviceprof.jit
def _group_has_data_jit(count):
    """Per-group any-data mask — G bools, the only bytes the aligned
    fast path's empty-group check ever downloads."""
    return (count > 0).any(axis=1)


@deviceprof.jit(static_argnames=("num_groups", "num_buckets", "which"))
def _batched_window_partials_jit(ts, gid, vals, remap, shift, lo, total,
                                 bucket_ms, num_groups: int,
                                 num_buckets: int, which: tuple):
    """Single-device twin of parallel.scan.sharded_remap_partials: vmap
    over the window axis instead of shard_map over the mesh — one device
    dispatch aggregates a whole round of windows into window-LOCAL grids
    of `num_buckets` buckets starting at each window's `lo` bucket."""
    from horaedb_tpu.ops import downsample

    def one(ts_b, gid_b, vals_b, remap_b, shift_b, lo_b):
        return downsample.window_local_partials(
            ts_b, gid_b, vals_b, remap_b, shift_b, lo_b, total, bucket_ms,
            num_groups=num_groups, num_buckets=num_buckets, which=which)

    return jax.vmap(one)(ts, gid, vals, remap, shift, lo)


def _decode_group_values(codes: np.ndarray, enc) -> np.ndarray:
    """Device group codes -> host values (dictionary entries / epoch
    shift), in the same (sorted) order as the codes."""
    if enc.kind == "dict":
        return enc.dictionary[codes]
    if enc.kind == "offset":
        return codes.astype(np.int64) + enc.epoch
    return codes


@_timed_stage("combine")
def combine_aggregate_parts(parts: list[tuple[np.ndarray, int, dict]],
                            num_buckets: int,
                            which: tuple = downsample_ops.ALL_AGGS
                            ) -> tuple[np.ndarray, dict]:
    """Compatibility shim over storage/combine.py's DENSE fold (the
    bit-identity control).  The reader's own finalize path dispatches
    by [scan.combine] mode instead; standalone callers (cluster-tier
    helpers, old tests) keep this name."""
    return combine_mod.combine_aggregate_parts(parts, num_buckets,
                                               which=which)


def _is_lex_sorted(keys: list[np.ndarray]) -> bool:
    """True iff rows are non-decreasing under lexicographic key order."""
    n = len(keys[0])
    if n <= 1:
        return True
    still_equal = np.ones(n - 1, dtype=bool)
    for c in keys:
        if bool(np.any(still_equal & (c[:-1] > c[1:]))):
            return False
        still_equal &= c[:-1] == c[1:]
        if not still_equal.any():
            return True
    return True


def _plan_merge_perm(sort_cols: list[np.ndarray],
                     seq: Optional[np.ndarray]) -> Optional[np.ndarray]:
    """Host half of the k-way merge of pre-sorted SST runs.

    The reference never re-sorts SST data: its per-file streams are
    already PK-ordered and SortPreservingMergeExec merges them
    (ref: src/storage/src/read.rs:455-480).  Our SSTs are written
    PK-sorted too (storage.py), so the scan's device program does not
    need an O(n log n) `lax.sort` — it needs, at most, a permutation
    that interleaves the pre-sorted runs.  That permutation is planned
    here, on the host, where the decoded parquet columns already live:

    - verify sortedness first (O(n) compares): single-SST segments and
      non-overlapping time-partitioned writes need NO work at all;
    - otherwise pack the lexicographic key into one int64 and use
      numpy's stable (radix, O(n)) argsort — effectively a k-way merge
      whose cost is independent of comparator depth;
    - keys whose combined range exceeds int64 fall back to np.lexsort.

    `seq` must be passed ONLY when rows are not already in ascending
    sequence order (stability preserves row order within equal keys,
    which is what last-wins dedup needs).  Returns None when rows are
    already sorted, else an int32 permutation over the input rows.
    """
    keys = list(sort_cols) + ([] if seq is None else [seq])
    n = len(keys[0])
    if n <= 1:
        return None
    # sortedness first: single-SST segments and non-overlapping writes
    # (the common cold case) exit here after ~one compare pass, before
    # paying any key-packing arithmetic
    if _is_lex_sorted(keys):
        return None
    packed = None
    span_prod = 1
    for c in keys:  # most-significant first
        c64 = c.astype(np.int64, copy=False)
        lo = int(c64.min())
        span = int(c64.max()) - lo + 1
        if span_prod * span >= 2**63:
            packed = None
            break
        span_prod *= span
        part = c64 - lo
        packed = part if packed is None else packed * span + part
    if packed is not None:
        return np.argsort(packed, kind="stable").astype(np.int32)
    return np.lexsort(tuple(reversed(keys))).astype(np.int32)


def _window_merge_sel(sort_cols: list[np.ndarray], seq_h: np.ndarray,
                      seq_ordered: bool, sel: np.ndarray) -> np.ndarray:
    """Compose a window selection with its planned merge permutation —
    the ONE place the (sort cols, seq-ordering) contract is applied to a
    window, so every path orders rows identically."""
    perm = _plan_merge_perm([c[sel] for c in sort_cols],
                            None if seq_ordered else seq_h[sel])
    return sel if perm is None else sel[perm]


def _batch_merge_perm(sort_cols: list[np.ndarray], seq_h: np.ndarray,
                      seq_ordered: bool, n: int) -> Optional[np.ndarray]:
    """Whole-batch twin of _window_merge_sel: perm over rows [0, n) or
    None when already sorted."""
    return _plan_merge_perm([c[:n] for c in sort_cols],
                            None if seq_ordered else seq_h[:n])


def _host_merge_window_descs(dev: encode.DeviceBatch, host_cols: dict,
                             sort_pk_names: list[str], seq_h: np.ndarray,
                             seq_ordered: bool, selections: list,
                             n: int) -> list:
    """THE host merge under the default host_perm impl, shared by the
    single-device and mesh window preps so the two paths cannot drift:
    per window, plan the k-way-merge permutation over pre-sorted SST
    runs (_plan_merge_perm contract), keep the last row of each PK run,
    and emit padded HOST-resident column dicts.

    Returns [(cols, n_valid, capacity, encodings)] — deduped, PK-sorted
    windows ready to wrap as DeviceBatches."""
    descs = []
    sort_cols = [host_cols[nm] for nm in sort_pk_names]
    for sel in selections:
        if sel is not None and not len(sel):
            continue
        if sel is None:
            base = _batch_merge_perm(sort_cols, seq_h, seq_ordered, n)
        else:
            base = _window_merge_sel(sort_cols, seq_h, seq_ordered, sel)
        keys = (sort_cols if base is None
                else [c[base] for c in sort_cols])
        keep = _host_dedup_keep(keys)
        k = int(keep.sum())
        if k == 0:
            continue
        if base is None:
            if k == n and sel is None:
                # no duplicates, already padded by encode_batch
                descs.append(({kk: np.asarray(v) for kk, v
                               in dev.columns.items()},
                              n, dev.capacity, dev.encodings))
                continue
            idx = np.flatnonzero(keep)
        else:
            idx = base if k == len(base) else base[keep]
        cap = encode.pad_capacity(k)
        cols = {kk: np.pad(v[idx], (0, cap - k))
                for kk, v in host_cols.items()}
        descs.append((cols, k, cap, dev.encodings))
    return descs


def _host_dedup_keep(sort_cols: list[np.ndarray]) -> np.ndarray:
    """Boolean keep-mask over PK-SORTED rows: the LAST row of each
    equal-PK run survives (rows arrive with the preferred — highest
    sequence — row last; see _plan_merge_perm's ordering contract).

    This is the host half of last-value dedup under the default
    host_perm merge: with the permutation already planned on host, the
    run-boundary compare is a single vectorized pass over columns the
    host just decoded — shipping rows to the device only to compare
    neighbours and ship survivors back would pay the tunnel twice for
    an O(n) bandwidth-bound op.  The devices' FLOPs are saved for the
    aggregation grids."""
    n = len(sort_cols[0])
    if n == 0:
        return np.zeros(0, dtype=bool)
    keep = np.empty(n, dtype=bool)
    keep[-1] = True
    diff = np.zeros(n - 1, dtype=bool)
    for c in sort_cols:
        diff |= c[:-1] != c[1:]
    keep[:-1] = diff
    return keep


def _plan_pk_windows(pk1_codes: np.ndarray, window: int) -> list[np.ndarray]:
    """Partition rows into PK-range windows of <= `window` rows.

    Rows sharing a first-PK code always land in one window (dedup only
    needs equal-PK rows co-located; later PK columns refine within a
    code).  Greedy packing over the contiguous code histogram; a single
    code with more rows than `window` gets a window of its own (which may
    exceed the budget — correctness over the soft limit).  Windows are
    code-ascending, so concatenated outputs stay globally PK-sorted.
    """
    # factorize to dense ranks: cost scales with DISTINCT keys, not the
    # code value span (offset-encoded int PKs can span ~2^31 sparsely)
    _, inv, counts = np.unique(pk1_codes, return_inverse=True,
                               return_counts=True)
    order = np.argsort(inv, kind="stable")
    boundaries = np.cumsum(np.concatenate([[0], counts]))
    # greedy packing by searchsorted over the cumulative histogram:
    # O(windows x log keys) instead of a Python iteration per DISTINCT
    # key (high-cardinality segments made this loop the window-prep
    # hot spot on low-core hosts — ROADMAP item 1 residual)
    nkeys = len(counts)
    windows: list[np.ndarray] = []
    s = 0
    while s < nkeys:
        e = int(np.searchsorted(boundaries, boundaries[s] + window,
                                side="right")) - 1
        if e <= s:
            e = s + 1  # single code over budget: a window of its own
        windows.append(order[boundaries[s]:boundaries[e]])
        s = e
    return windows


def _eval_predicate_host(pred, batch: pa.RecordBatch) -> np.ndarray:
    """Host twin of ops.filter.eval_predicate over an Arrow batch."""
    F = filter_ops
    if isinstance(pred, F.And):
        out = np.ones(batch.num_rows, dtype=bool)
        for c in pred.children:
            out &= _eval_predicate_host(c, batch)
        return out
    if isinstance(pred, F.Or):
        out = np.zeros(batch.num_rows, dtype=bool)
        for c in pred.children:
            out |= _eval_predicate_host(c, batch)
        return out
    if isinstance(pred, F.Not):
        return ~_eval_predicate_host(pred.child, batch)
    col = batch.column(batch.schema.names.index(pred.column))
    return F.leaf_mask_host(pred, col.to_numpy(zero_copy_only=False))


def plan_columns(schema: StorageSchema,
                 projections: Optional[list[int]]) -> list[str]:
    """THE column set a merge plan reads for a projection — shared by
    build_plan and the memtable-overlay path (wal/ingest.py) so hybrid
    and pure-SST scans cannot disagree on shape."""
    proj = schema.fill_required_projections(projections)
    if proj is None:
        columns = list(schema.arrow_schema.names)
    else:
        columns = [schema.arrow_schema.names[i] for i in proj]
    # __reserved__ is never read (all-null, unused); __seq__ must be
    # read for dedup even when it will be stripped from the output.
    columns = [c for c in columns if c != RESERVED_COLUMN_NAME]
    if SEQ_COLUMN_NAME not in columns:
        columns.append(SEQ_COLUMN_NAME)
    return columns


def merge_memtable_overlay(schema: StorageSchema,
                           sst_parts: list[pa.RecordBatch],
                           mem_batches: list[pa.RecordBatch],
                           predicate,
                           columns: list[str],
                           keep_builtin: bool) -> Optional[pa.RecordBatch]:
    """Host merge of ONE segment's already-merged SST rows with its
    memtable overlay — the hybrid scan's last stage (wal/ingest.py).

    Both sources carry per-row `__seq__` (sst_parts from a
    keep_builtin plan, mem_batches stamped with each entry's write
    seq), so OVERWRITE's last-value rule is one sort by (PK, __seq__)
    keeping the final row of every PK run.  The full predicate applies
    AFTER dedup, matching the pure-SST path (value-column leaves can
    interact with last-value dedup, so filtering first would resurrect
    overwritten rows); the caller therefore scans overlay segments
    without a predicate.  Ordering invariant: seqs are preserved end to
    end, so a replayed memtable row and its flushed SST twin tie on
    (PK, seq) with identical values — either winning is exactly-once.
    """
    import pyarrow.compute as pc

    from horaedb_tpu.storage.operator import LastValueOperator

    target = pa.schema([schema.arrow_schema.field(
        schema.arrow_schema.names.index(c)) for c in columns])
    parts = []
    for b in list(sst_parts) + list(mem_batches):
        if b.num_rows == 0:
            continue
        b = b.select(columns)
        if not b.schema.equals(target):
            b = b.cast(target)
        parts.append(b)
    if not parts:
        return None
    table = pa.Table.from_batches(parts, schema=target)
    sort_keys = [(n, "ascending") for n in schema.primary_key_names]
    sort_keys.append((SEQ_COLUMN_NAME, "ascending"))
    table = table.take(pc.sort_indices(table, sort_keys=sort_keys))
    batch = table.combine_chunks().to_batches()[0]
    # keep-last-of-PK-run is THE LastValue rule — reuse the operator
    # (native run-detection kernel included) so overlay and SST merges
    # cannot drift
    pk_indices = [columns.index(n) for n in schema.primary_key_names]
    batch = LastValueOperator().merge_sorted_batch(batch, pk_indices)
    if predicate is not None and batch.num_rows:
        mask = _eval_predicate_host(predicate, batch)
        batch = batch.take(np.flatnonzero(mask))
    if not keep_builtin:
        batch = batch.select([c for c in batch.schema.names
                              if not StorageSchema.is_builtin_name(c)])
    return batch


def describe_plan(plan: ScanPlan) -> str:
    """Indented plan text for golden tests (analogue of the reference's
    DisplayableExecutionPlan assertion, read.rs:575-617)."""
    lines = [f"MergeScan: mode={plan.mode.value}, keep_builtin={plan.keep_builtin}"]
    for seg in plan.segments:
        lines.append(f"  Segment[start={seg.segment_start}]: "
                     f"{'DeviceMergeDedup' if plan.mode is UpdateMode.OVERWRITE else 'HostBytesMerge'}")
        if plan.predicate is not None:
            lines.append(f"    Filter: {plan.predicate!r}")
        files = ", ".join(f"{f.id}.sst" for f in seg.ssts)
        pushed = ", pushdown=yes" if plan.pushdown is not None else ""
        lines.append(f"    ParquetScan: files=[{files}], "
                     f"columns={seg.columns}{pushed}")
    return "\n".join(lines)
