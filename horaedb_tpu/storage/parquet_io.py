"""Parquet SST encode/decode on top of the ObjectStore.

Maps WriteConfig onto pyarrow writer properties the way the reference maps
its config onto parquet-rs WriterProperties (ref: src/storage/src/
storage.rs:257-297 build_write_props): row-group size, write batch size,
global + per-column dictionary/compression/encoding, and sorting-columns
metadata recording the (pk..., seq) sort order.
"""

from __future__ import annotations

import io
from typing import Optional

import pyarrow as pa
import pyarrow.parquet as pq

from horaedb_tpu.objstore import NotFoundError, ObjectStore
from horaedb_tpu.storage.config import WriteConfig
from horaedb_tpu.storage.types import StorageSchema


def writer_options(config: WriteConfig, schema: StorageSchema) -> dict:
    """pyarrow ParquetWriter kwargs from a WriteConfig."""
    names = schema.arrow_schema.names

    def dict_enabled(n: str) -> bool:
        opt = config.column_options.get(n)
        if opt is not None and opt.enable_dict is not None:
            return opt.enable_dict
        return config.enable_dict

    per_col_dict = {n: dict_enabled(n) for n in names}
    if all(v == config.enable_dict for v in per_col_dict.values()):
        use_dictionary: object = config.enable_dict
    else:
        use_dictionary = [n for n, v in per_col_dict.items() if v]

    compression: object = config.compression.value
    per_col_comp = {
        n: config.column_options[n].compression.value
        for n in names
        if n in config.column_options and config.column_options[n].compression
    }
    if per_col_comp:
        compression = {n: per_col_comp.get(n, config.compression.value) for n in names}

    per_col_enc = {
        n: config.column_options[n].encoding
        for n in names
        if n in config.column_options and config.column_options[n].encoding
    }
    if per_col_enc:
        # per-column overrides must not drop the global default elsewhere
        column_encoding: object = (
            {n: per_col_enc.get(n, config.encoding) for n in names}
            if config.encoding else per_col_enc)
    else:
        column_encoding = config.encoding

    kwargs = dict(
        use_dictionary=use_dictionary,
        compression=compression,
        write_statistics=True,
        write_batch_size=config.write_batch_size,
    )
    if column_encoding:
        kwargs["column_encoding"] = column_encoding
    if config.enable_sorting_columns:
        kwargs["sorting_columns"] = [
            pq.SortingColumn(i) for i in range(schema.num_primary_keys)
        ] + [pq.SortingColumn(schema.seq_idx)]
    return kwargs


def encode_sst(batches: list[pa.RecordBatch], config: WriteConfig,
               schema: StorageSchema) -> bytes:
    """Serialize sorted, builtin-stamped batches into one Parquet file."""
    sink = io.BytesIO()
    writer = pq.ParquetWriter(sink, schema.arrow_schema,
                              **writer_options(config, schema))
    try:
        for batch in batches:
            writer.write_batch(batch, row_group_size=config.max_row_group_size)
    finally:
        writer.close()
    return sink.getvalue()


async def _run(runtimes, pool: str, fn, *args, **kwargs):
    """Run CPU work on a named pool (common.runtimes), falling back to
    asyncio's default thread pool when no runtimes were provided — the
    event loop itself NEVER encodes/decodes parquet (ref: dedicated
    runtimes, storage.rs:91-104)."""
    import asyncio
    import functools

    if runtimes is not None:
        return await runtimes.run(pool, fn, *args, **kwargs)
    return await asyncio.to_thread(functools.partial(fn, *args, **kwargs))


async def write_sst(store: ObjectStore, path: str,
                    batches: list[pa.RecordBatch], config: WriteConfig,
                    schema: StorageSchema, runtimes=None,
                    pool: str = "sst") -> int:
    """Encode + put; returns the file size in bytes."""
    data = await _run(runtimes, pool, encode_sst, batches, config, schema)
    await store.put(path, data)
    return len(data)


class _DrainableSink(io.RawIOBase):
    """File-like sink the ParquetWriter writes into; drain() hands the
    bytes accumulated since the last drain to the store stream, so the
    encoded SST never exists in one buffer."""

    def __init__(self) -> None:
        self._chunks: list[bytes] = []
        self._pos = 0

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        data = bytes(b)
        self._chunks.append(data)
        self._pos += len(data)
        return len(data)

    def tell(self) -> int:
        return self._pos

    def drain(self) -> bytes:
        out = b"".join(self._chunks)
        self._chunks.clear()
        return out


async def write_sst_streaming(store: ObjectStore, path: str, batches,
                              config: WriteConfig, schema: StorageSchema,
                              runtimes=None, pool: str = "compact"
                              ) -> tuple[int, int]:
    """Stream an async iterator of sorted batches through the parquet
    encoder INTO the store: each flushed row group is handed to
    store.put_stream as it encodes (S3 uploads it as a multipart part;
    the local store appends to the temp file), so peak RSS for an
    arbitrarily large SST is ~one row group + one part buffer — the
    reference's AsyncArrowWriter -> ParquetObjectWriter pipeline
    (ref: src/storage/src/storage.rs:192-212, executor.rs:155-222).

    A mid-stream failure propagates out of put_stream's iterator, which
    aborts the multipart upload / unlinks the temp file — no readable
    object and no orphaned parts.  Returns (size, num_rows)."""
    sink = _DrainableSink()
    writer = pq.ParquetWriter(sink, schema.arrow_schema,
                              **writer_options(config, schema))
    rows = 0

    async def chunks():
        nonlocal rows
        closed = False
        try:
            async for batch in batches:
                rows += batch.num_rows
                # slice to row-group size so every flushed group drains
                # to the store before the next encodes — a large merged
                # batch must not accumulate in the sink
                step = max(1, config.max_row_group_size)
                for off in range(0, batch.num_rows, step):
                    await _run(runtimes, pool, writer.write_batch,
                               batch.slice(off, step),
                               row_group_size=step)
                    data = sink.drain()
                    if data:
                        yield data
            await _run(runtimes, pool, writer.close)
            closed = True
            tail = sink.drain()
            if tail:
                yield tail
        finally:
            if not closed:
                writer.close()

    size = await store.put_stream(path, chunks())
    return size, rows


def merge_value_counts(pairs: list) -> tuple:
    """Fold (values, counts) pairs into one sorted pair.  Dtype-
    preserving: the first non-empty pair fixes the value dtype (uint64
    tsids must never pass through a float64 concat)."""
    import numpy as np

    values = counts = None
    for v, c in pairs:
        if not len(v):
            continue
        if values is None:
            values, counts = v, np.asarray(c, dtype=np.int64)
            continue
        allv = np.concatenate([values, v])
        allc = np.concatenate([counts, c])
        values, inv = np.unique(allv, return_inverse=True)
        counts = np.bincount(inv, weights=allc).astype(np.int64)
    if values is None:
        return np.asarray([]), np.asarray([], dtype=np.int64)
    return values, counts


class SstSource:
    """One SST opened for several reads (the streamed segment read does
    one pass-1 column scan plus one pass-2 filtered read PER WINDOW).
    Local stores serve every read from the mmap'd file; other stores
    fetch the object bytes ONCE and serve all reads from that buffer —
    never one download per window.  Methods are synchronous; call them
    via asyncio.to_thread from async code."""

    def __init__(self, path: Optional[str] = None,
                 data: Optional[bytes] = None):
        self._path = path
        self._data = data

    def _source(self):
        # a fresh reader per call: BufferReader is stateful and parquet
        # readers seek it
        return self._path if self._path is not None \
            else pa.BufferReader(self._data)

    def read(self, columns: Optional[list[str]] = None,
             filters=None) -> pa.Table:
        try:
            return pq.read_table(self._source(), columns=columns,
                                 memory_map=self._path is not None,
                                 filters=filters)
        except FileNotFoundError as e:
            # local-path sources re-open per call; a compaction may have
            # deleted the file — surface the store contract's error so
            # callers can re-resolve/retry
            raise NotFoundError(f"object not found: {self._path}") from e

    def value_counts(self, column: str) -> tuple:
        """(values, counts) of one column, streamed row-group-wise so
        host memory is bounded by row-group size + distinct values."""
        import numpy as np

        try:
            pf = pq.ParquetFile(self._source(),
                                memory_map=self._path is not None)
        except FileNotFoundError as e:
            raise NotFoundError(f"object not found: {self._path}") from e
        acc = (np.asarray([]), np.asarray([], dtype=np.int64))
        try:
            for batch in pf.iter_batches(columns=[column]):
                col = batch.column(0).to_numpy(zero_copy_only=False)
                v, c = np.unique(col, return_counts=True)
                acc = merge_value_counts([acc, (v, c)])
        finally:
            pf.close()
        return acc


async def open_sst_source(store: ObjectStore, path: str) -> SstSource:
    local_path = getattr(store, "local_path", None)
    if local_path is not None:
        return SstSource(path=local_path(path))
    return SstSource(data=await store.get(path))


async def read_sst(store: ObjectStore, path: str,
                   columns: Optional[list[str]] = None,
                   filters=None, runtimes=None,
                   pool: str = "sst") -> pa.Table:
    """Read an SST, optionally a column subset and a pyarrow filter
    expression (row-group pruning via parquet statistics + row filtering
    — the reference's ParquetExec pruning predicate, read.rs:442-465).

    Local stores expose a filesystem path for mmap'd reads; other stores
    go through a bytes buffer.  Decode always runs on a worker pool.
    """
    local_path = getattr(store, "local_path", None)
    if local_path is not None:
        try:
            return await _run(runtimes, pool, pq.read_table,
                              local_path(path), columns=columns,
                              memory_map=True, filters=filters)
        except FileNotFoundError as e:
            # a compaction deleted the SST between plan and read: map to
            # the store contract's error so scan retries replan (the
            # non-local branch gets this from store.get)
            raise NotFoundError(f"object not found: {path}") from e
    data = await store.get(path)
    return await _run(runtimes, pool, pq.read_table, pa.BufferReader(data),
                      columns=columns, filters=filters)
