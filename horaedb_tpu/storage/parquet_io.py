"""Parquet SST encode/decode on top of the ObjectStore.

Maps WriteConfig onto pyarrow writer properties the way the reference maps
its config onto parquet-rs WriterProperties (ref: src/storage/src/
storage.rs:257-297 build_write_props): row-group size, write batch size,
global + per-column dictionary/compression/encoding, and sorting-columns
metadata recording the (pk..., seq) sort order.
"""

from __future__ import annotations

import io
from typing import Optional

import pyarrow as pa
import pyarrow.parquet as pq

from horaedb_tpu.objstore import NotFoundError, ObjectStore
from horaedb_tpu.storage.config import WriteConfig
from horaedb_tpu.storage.types import StorageSchema


def writer_options(config: WriteConfig, schema: StorageSchema) -> dict:
    """pyarrow ParquetWriter kwargs from a WriteConfig."""
    names = schema.arrow_schema.names

    def dict_enabled(n: str) -> bool:
        opt = config.column_options.get(n)
        if opt is not None and opt.enable_dict is not None:
            return opt.enable_dict
        return config.enable_dict

    per_col_dict = {n: dict_enabled(n) for n in names}
    if all(v == config.enable_dict for v in per_col_dict.values()):
        use_dictionary: object = config.enable_dict
    else:
        use_dictionary = [n for n, v in per_col_dict.items() if v]

    compression: object = config.compression.value
    per_col_comp = {
        n: config.column_options[n].compression.value
        for n in names
        if n in config.column_options and config.column_options[n].compression
    }
    if per_col_comp:
        compression = {n: per_col_comp.get(n, config.compression.value) for n in names}

    per_col_enc = {
        n: config.column_options[n].encoding
        for n in names
        if n in config.column_options and config.column_options[n].encoding
    }
    if per_col_enc:
        # per-column overrides must not drop the global default elsewhere
        column_encoding: object = (
            {n: per_col_enc.get(n, config.encoding) for n in names}
            if config.encoding else per_col_enc)
    else:
        column_encoding = config.encoding

    kwargs = dict(
        use_dictionary=use_dictionary,
        compression=compression,
        write_statistics=True,
        write_batch_size=config.write_batch_size,
    )
    if column_encoding:
        kwargs["column_encoding"] = column_encoding
    if config.enable_sorting_columns:
        kwargs["sorting_columns"] = [
            pq.SortingColumn(i) for i in range(schema.num_primary_keys)
        ] + [pq.SortingColumn(schema.seq_idx)]
    return kwargs


def encode_sst(batches: list[pa.RecordBatch], config: WriteConfig,
               schema: StorageSchema) -> bytes:
    """Serialize sorted, builtin-stamped batches into one Parquet file."""
    sink = io.BytesIO()
    writer = pq.ParquetWriter(sink, schema.arrow_schema,
                              **writer_options(config, schema))
    try:
        for batch in batches:
            writer.write_batch(batch, row_group_size=config.max_row_group_size)
    finally:
        writer.close()
    return sink.getvalue()


async def _run(runtimes, pool: str, fn, *args, **kwargs):
    """Run CPU work on a named pool (common.runtimes), falling back to
    asyncio's default thread pool when no runtimes were provided — the
    event loop itself NEVER encodes/decodes parquet (ref: dedicated
    runtimes, storage.rs:91-104)."""
    import asyncio
    import functools

    if runtimes is not None:
        return await runtimes.run(pool, fn, *args, **kwargs)
    return await asyncio.to_thread(functools.partial(fn, *args, **kwargs))


async def write_sst(store: ObjectStore, path: str,
                    batches: list[pa.RecordBatch], config: WriteConfig,
                    schema: StorageSchema, runtimes=None,
                    pool: str = "sst") -> int:
    """Encode + put; returns the file size in bytes."""
    data = await _run(runtimes, pool, encode_sst, batches, config, schema)
    await store.put(path, data)
    return len(data)


class _DrainableSink(io.RawIOBase):
    """File-like sink the ParquetWriter writes into; drain() hands the
    bytes accumulated since the last drain to the store stream, so the
    encoded SST never exists in one buffer."""

    def __init__(self) -> None:
        self._chunks: list[bytes] = []
        self._pos = 0

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        data = bytes(b)
        self._chunks.append(data)
        self._pos += len(data)
        return len(data)

    def tell(self) -> int:
        return self._pos

    def drain(self) -> bytes:
        out = b"".join(self._chunks)
        self._chunks.clear()
        return out


async def write_sst_streaming(store: ObjectStore, path: str, batches,
                              config: WriteConfig, schema: StorageSchema,
                              runtimes=None, pool: str = "compact"
                              ) -> tuple[int, int]:
    """Stream an async iterator of sorted batches through the parquet
    encoder INTO the store: each flushed row group is handed to
    store.put_stream as it encodes (S3 uploads it as a multipart part;
    the local store appends to the temp file), so peak RSS for an
    arbitrarily large SST is ~one row group + one part buffer — the
    reference's AsyncArrowWriter -> ParquetObjectWriter pipeline
    (ref: src/storage/src/storage.rs:192-212, executor.rs:155-222).

    A mid-stream failure propagates out of put_stream's iterator, which
    aborts the multipart upload / unlinks the temp file — no readable
    object and no orphaned parts.  Returns (size, num_rows)."""
    sink = _DrainableSink()
    writer = pq.ParquetWriter(sink, schema.arrow_schema,
                              **writer_options(config, schema))
    rows = 0

    async def chunks():
        nonlocal rows
        closed = False
        pending = None  # the in-flight pool job using `writer`

        async def run_writer(fn, *args, **kwargs):
            # shielded so a CANCELLED caller leaves `pending` visible:
            # the pool job keeps executing after cancellation, and the
            # finally below must wait it out before touching the writer
            # — ParquetWriter is not thread-safe, and closing it while
            # write_batch runs on a pool thread corrupts the heap
            # (observed as intermittent SIGSEGV/SIGABRT under the
            # concurrency stress when scheduler.stop() cancels a
            # compaction mid-row-group).
            nonlocal pending
            import asyncio

            pending = asyncio.ensure_future(
                _run(runtimes, pool, fn, *args, **kwargs))
            try:
                return await asyncio.shield(pending)
            finally:
                if pending.done():
                    pending = None

        try:
            async for batch in batches:
                rows += batch.num_rows
                # slice to row-group size so every flushed group drains
                # to the store before the next encodes — a large merged
                # batch must not accumulate in the sink
                step = max(1, config.max_row_group_size)
                for off in range(0, batch.num_rows, step):
                    await run_writer(writer.write_batch,
                                     batch.slice(off, step),
                                     row_group_size=step)
                    data = sink.drain()
                    if data:
                        yield data
            await run_writer(writer.close)
            closed = True
            tail = sink.drain()
            if tail:
                yield tail
        finally:
            if pending is not None and not pending.done():
                import asyncio

                await asyncio.gather(pending, return_exceptions=True)
            if not closed:
                writer.close()

    size = await store.put_stream(path, chunks())
    return size, rows


def merge_value_counts(pairs: list) -> tuple:
    """Fold (values, counts) pairs into one sorted pair.  Dtype-
    preserving: the first non-empty pair fixes the value dtype (uint64
    tsids must never pass through a float64 concat)."""
    import numpy as np

    values = counts = None
    for v, c in pairs:
        if not len(v):
            continue
        if values is None:
            values, counts = v, np.asarray(c, dtype=np.int64)
            continue
        allv = np.concatenate([values, v])
        allc = np.concatenate([counts, c])
        values, inv = np.unique(allv, return_inverse=True)
        counts = np.bincount(inv, weights=allc).astype(np.int64)
    if values is None:
        return np.asarray([]), np.asarray([], dtype=np.int64)
    return values, counts


# ---------------------------------------------------------------------------
# Stats-pruned structured reads.
#
# pq.read_table(filters=...) routes through the dataset scanner, whose
# per-call overhead and row-level expression evaluation cost ~3-6x a
# plain decode on the segment-read shapes the engine issues (measured:
# 5.6ms vs 1.7ms on a 72k-row SST).  The scan predicate is a small
# conjunctive tree over PK columns, so we prune row groups against
# parquet statistics ourselves (the reference's pruning predicate,
# read.rs:442-465), decode with ParquetFile.read_row_groups, and apply
# residual filters as numpy masks only on boundary groups.  Columns
# pinned by an Eq leaf whose stats prove min==max==value everywhere are
# not decoded at all — they are reconstructed as constants.
# ---------------------------------------------------------------------------


def conjunct_leaves(pred, allowed: set) -> Optional[list]:
    """Flatten an And-tree of stats-checkable leaves over `allowed`
    columns.  Returns None when the tree contains Or/Not/unsupported
    leaves or columns outside `allowed` — callers then fall back to the
    expression path (exactly the rows the pushdown would keep must be
    kept, so anything not provably equivalent opts out)."""
    return conjunct_leaves_ex(pred, allowed)[0]


def conjunct_leaves_ex(pred, allowed: set) -> tuple[Optional[list], bool]:
    """conjunct_leaves plus a `complete` flag: True iff EVERY leaf of
    the predicate was collected (And-of-leaves shape, all columns in
    `allowed`) — i.e. the pushed conjunction IS the whole predicate.
    One walker decides both so the leaf-type list cannot drift."""
    from horaedb_tpu.ops import filter as F

    leaves: list = []
    complete = True

    def walk(p) -> bool:
        nonlocal complete
        if isinstance(p, F.And):
            return all(walk(c) for c in p.children)
        if isinstance(p, (F.Eq, F.Lt, F.Le, F.Gt, F.Ge, F.In,
                          F.TimeRangePred)):
            if p.column not in allowed:
                # the arrow pushdown DROPS non-allowed leaves (they are
                # applied post-merge); mirror that by skipping the leaf
                complete = False
                return True
            leaves.append(p)
            return True
        if isinstance(p, (F.Or, F.Not, F.Ne)):
            return False
        return False

    if pred is None:
        return None, False
    if not walk(pred) or not leaves:
        # no constraint survives: unfiltered reads stay on pq.read_table
        # (multithreaded column decode), pruning would add nothing
        return None, False
    return leaves, complete


def _leaf_vs_stats(leaf, stats) -> str:
    """Classify one row group against one leaf: 'empty' (no row can
    match), 'full' (every row matches), or 'partial'."""
    from horaedb_tpu.ops import filter as F

    if stats is None or not stats.has_min_max:
        return "partial"
    lo, hi = stats.min, stats.max
    if isinstance(lo, float):
        # parquet min/max statistics IGNORE NaN (a [1.0, NaN] group
        # reports min=max=1.0, null_count=0), and NaN fails every
        # comparison — so a float group can never be proven 'full'.
        # 'empty' survives: NaN rows can't match either, so a group
        # with no possible non-NaN match stays empty.
        verdict = _leaf_vs_minmax(leaf, lo, hi, F)
        return "partial" if verdict == "full" else verdict
    return _leaf_vs_minmax(leaf, lo, hi, F)


def _leaf_vs_minmax(leaf, lo, hi, F) -> str:
    try:
        if isinstance(leaf, F.Eq):
            if leaf.value < lo or leaf.value > hi:
                return "empty"
            return "full" if lo == hi == leaf.value else "partial"
        if isinstance(leaf, F.TimeRangePred):
            if hi < leaf.start or lo >= leaf.end:
                return "empty"
            return ("full" if lo >= leaf.start and hi < leaf.end
                    else "partial")
        if isinstance(leaf, F.Lt):
            if lo >= leaf.value:
                return "empty"
            return "full" if hi < leaf.value else "partial"
        if isinstance(leaf, F.Le):
            if lo > leaf.value:
                return "empty"
            return "full" if hi <= leaf.value else "partial"
        if isinstance(leaf, F.Gt):
            if hi <= leaf.value:
                return "empty"
            return "full" if lo > leaf.value else "partial"
        if isinstance(leaf, F.Ge):
            if hi < leaf.value:
                return "empty"
            return "full" if lo >= leaf.value else "partial"
        if isinstance(leaf, F.In):
            vals = [v for v in leaf.values if lo <= v <= hi]
            if not vals:
                return "empty"
            if lo == hi and lo in leaf.values:
                return "full"
            return "partial"
    except TypeError:
        # stats/value type mismatch (e.g. bytes vs int): never prune
        return "partial"
    return "partial"


def _residual_mask(leaves: list, tbl: pa.Table):
    """numpy row mask for the leaves not proven full on this run."""
    import numpy as np

    from horaedb_tpu.ops.filter import leaf_mask_host

    mask = np.ones(tbl.num_rows, dtype=bool)
    for leaf in leaves:
        col = tbl.column(leaf.column).to_numpy(zero_copy_only=False)
        mask &= leaf_mask_host(leaf, col)
    return mask


def _stats_constant(md, col_i: int, groups: list):
    """The single value column `col_i` provably holds across `groups`
    (min==max everywhere, no nulls), or None."""
    value = None
    for g in groups:
        st = md.row_group(g).column(col_i).statistics
        if (st is None or not st.has_min_max
                or not getattr(st, "has_null_count", False)
                or st.null_count or st.min != st.max):
            return None
        if value is None:
            value = st.min
        elif value != st.min:
            return None
    return value


def read_pruned(pf: pq.ParquetFile, columns: Optional[list[str]],
                leaves: list) -> pa.Table:
    """Decode `columns` of the row groups that can match the conjunction
    `leaves`, filtering boundary groups row-level.  Row-level equivalent
    to pq.read_table(filters=<AND of leaves>) on non-null data."""
    import numpy as np

    from horaedb_tpu.ops import filter as F

    md = pf.metadata
    names = [md.schema.column(i).name for i in range(md.num_columns)]
    col_idx = {n: i for i, n in enumerate(names)}
    out_cols = list(columns) if columns is not None else names

    # per-group classification
    selected: list[tuple[int, tuple]] = []  # (group, residual leaves)
    full_eq: dict[str, object] = {}  # col -> pinned value, candidate
    for leaf in leaves:
        if isinstance(leaf, F.Eq) and leaf.column in col_idx:
            full_eq.setdefault(leaf.column, leaf.value)
    for g in range(md.num_row_groups):
        rg = md.row_group(g)
        residual = []
        empty = False
        for leaf in leaves:
            i = col_idx.get(leaf.column)
            if i is None:
                residual.append(leaf)  # missing column: be conservative
                continue
            st = rg.column(i).statistics
            verdict = _leaf_vs_stats(leaf, st)
            # any nulls in the group break both 'full' proofs and numpy
            # residual compares — never trust stats without a null count.
            # ('empty' survives: null rows fail every comparison under
            # SQL semantics, so a group with no possible match stays
            # empty regardless of nulls.)
            if verdict != "empty" and (
                    st is None or not getattr(st, "has_null_count", False)
                    or st.null_count):
                raise _PruneUnsupported()
            if verdict == "empty":
                empty = True
                break
            if verdict == "partial":
                residual.append(leaf)
        if empty:
            continue
        # a pinned-Eq candidate must be proven 'full' in EVERY selected
        # group — a group where it is merely residual disqualifies it
        for col in list(full_eq):
            lf = next(l for l in leaves
                      if isinstance(l, F.Eq) and l.column == col)
            if lf in residual or col not in col_idx:
                full_eq.pop(col, None)
        selected.append((g, tuple(residual)))

    schema = pf.schema_arrow
    if not selected:
        arrays = [pa.array([], type=schema.field(n).type) for n in out_cols]
        return pa.Table.from_arrays(arrays, names=out_cols)

    # columns provably constant across every selected group are not
    # decoded; rebuild them as constants afterwards (plain types only —
    # the reconstruction goes through np.full)
    def _elidable(c: str) -> bool:
        # floats are NOT elidable: parquet stats ignore NaN, so
        # min==max with null_count=0 does not prove a float column
        # constant ([1.0, NaN, 1.0] reports min=max=1.0) and np.full
        # reconstruction would silently drop the NaNs
        t = schema.field(c).type
        return pa.types.is_integer(t) or pa.types.is_string(t)

    elide = {c: v for c, v in full_eq.items()
             if c in out_cols and _elidable(c)}
    # beyond predicate-pinned columns, ANY projected column whose stats
    # prove one constant value across every selected group skips decode
    # (__seq__ is constant in every un-compacted SST; a single-metric
    # table's ids too even without a predicate)
    residual_cols = {l.column for _, res in selected for l in res}
    for c in out_cols:
        if c in elide or not _elidable(c) or c in residual_cols \
                or c not in col_idx:
            continue
        const = _stats_constant(md, col_idx[c], [g for g, _ in selected])
        if const is not None:
            elide[c] = const
    decode_cols = [c for c in out_cols if c not in elide]
    # residual evaluation may need a column the projection dropped
    extra = sorted({l.column for _, res in selected for l in res}
                   - set(decode_cols))
    read_cols = decode_cols + extra

    if not decode_cols and not any(res for _, res in selected):
        # every projected column is an elided constant and no residual
        # filter remains: nothing needs decoding — build the constants
        # at the selected groups' total row count directly
        # (pa.concat_tables over zero-column tables would drop the count)
        n = sum(md.row_group(g).num_rows for g, _ in selected)
        arrays = []
        for c in out_cols:
            t = schema.field(c).type
            arrays.append(pa.array(
                np.full(n, elide[c], dtype=t.to_pandas_dtype()), type=t))
        return pa.Table.from_arrays(arrays, names=out_cols)

    # consecutive groups with the same residual decode as one run
    runs: list[tuple[list[int], tuple]] = []
    for g, residual in selected:
        if runs and runs[-1][1] == residual and runs[-1][0][-1] == g - 1:
            runs[-1][0].append(g)
        else:
            runs.append(([g], residual))
    parts = []
    for groups, residual in runs:
        tbl = pf.read_row_groups(groups, columns=read_cols,
                                 use_threads=False)
        if residual:
            mask = _residual_mask(list(residual), tbl)
            if not mask.all():
                tbl = tbl.filter(pa.array(mask))
        # with an empty projection the residual columns must stay in the
        # part — a zero-column table loses its row count in concat
        parts.append(tbl.select(decode_cols)
                     if extra and decode_cols else tbl)
    out = pa.concat_tables(parts)
    for c in elide:
        t = schema.field(c).type
        arr = pa.array(np.full(out.num_rows, elide[c],
                               dtype=t.to_pandas_dtype()), type=t)
        out = out.append_column(pa.field(c, t), arr)
    return out.select(out_cols)


class _PruneUnsupported(Exception):
    """Internal: this file/predicate cannot be pruned safely; callers
    fall back to the expression path."""


class SstSource:
    """One SST opened for several reads (the streamed segment read does
    one pass-1 column scan plus one pass-2 filtered read PER WINDOW).
    Local stores serve every read from the mmap'd file; other stores
    fetch the object bytes ONCE and serve all reads from that buffer —
    never one download per window.  Methods are synchronous; call them
    via asyncio.to_thread from async code."""

    def __init__(self, path: Optional[str] = None,
                 data: Optional[bytes] = None):
        self._path = path
        self._data = data

    def _source(self):
        # a fresh reader per call: BufferReader is stateful and parquet
        # readers seek it
        return self._path if self._path is not None \
            else pa.BufferReader(self._data)

    def read(self, columns: Optional[list[str]] = None,
             filters=None) -> pa.Table:
        try:
            return pq.read_table(self._source(), columns=columns,
                                 memory_map=self._path is not None,
                                 filters=filters)
        except FileNotFoundError as e:
            # local-path sources re-open per call; a compaction may have
            # deleted the file — surface the store contract's error so
            # callers can re-resolve/retry
            raise NotFoundError(f"object not found: {self._path}") from e

    def value_counts(self, column: str) -> tuple:
        """(values, counts) of one column, streamed row-group-wise so
        host memory is bounded by row-group size + distinct values."""
        import numpy as np

        try:
            pf = pq.ParquetFile(self._source(),
                                memory_map=self._path is not None)
        except FileNotFoundError as e:
            raise NotFoundError(f"object not found: {self._path}") from e
        acc = (np.asarray([]), np.asarray([], dtype=np.int64))
        try:
            for batch in pf.iter_batches(columns=[column]):
                col = batch.column(0).to_numpy(zero_copy_only=False)
                v, c = np.unique(col, return_counts=True)
                acc = merge_value_counts([acc, (v, c)])
        finally:
            pf.close()
        return acc


async def open_sst_source(store: ObjectStore, path: str) -> SstSource:
    local_path = getattr(store, "local_path", None)
    if local_path is not None:
        return SstSource(path=local_path(path))
    return SstSource(data=await store.get(path))


def _read_pruned_source(source, columns, leaves, memory_map) -> pa.Table:
    pf = pq.ParquetFile(source, memory_map=memory_map)
    try:
        return read_pruned(pf, columns, leaves)
    finally:
        pf.close()


# whole-SST fetches at/above this size stream (ObjectStore.get_stream)
# into an anonymous temp file and decode from a file-backed mmap —
# peak anonymous RSS is one stream chunk, and the kernel page cache
# owns (and can evict) the object bytes.  Below it, one get() into a
# bytes buffer stays cheaper (no filesystem round trip).  The near-data
# fallback path depends on this bound: a dead-agent fallback on a large
# covered segment must not balloon the coordinator's RSS by the
# segment it suddenly has to read itself (docs/robustness.md).
STREAM_FETCH_MIN_BYTES = 64 << 20

# memory plane (common/memledger.py): live streamed-SST mappings.
# These bytes are page-cache-backed (the kernel can evict them under
# pressure, unlike heap), but they still count against RSS while hot
# and must be attributable — a dead-agent fallback streaming a dozen
# 100 MB SSTs shows up HERE, not as a leak.  Charged at map time,
# credited by a weakref finalizer when the last buffer reference
# drops (the mapping's lifetime IS the buffer's).
from horaedb_tpu.common.memledger import ledger as _memledger  # noqa: E402

_STREAM_MMAP_ACCOUNT = _memledger.flow(
    "streamed_mmap", kind="streamed_mmap", owner="storage/parquet_io")


async def _fetch_mapped(store: ObjectStore, path: str, runtimes,
                        pool: str) -> pa.Buffer:
    """Stream an object into an unlinked temp file and return a
    pa.Buffer over its read-only mmap — drop-in for the bytes that
    store.get would have returned, without the resident copy."""
    import mmap
    import tempfile
    import weakref

    f = tempfile.TemporaryFile(prefix="sst-stream-")
    try:
        stream = store.get_stream(path)
        try:
            async for chunk in stream:
                # writes on the decode pool: the event loop never
                # blocks on disk
                await _run(runtimes, pool, f.write, chunk)
        finally:
            await stream.aclose()
        f.flush()
        size = f.tell()
        if size == 0:
            return pa.py_buffer(b"")
        # the mapping (and the unlinked file behind it) lives exactly
        # as long as the returned buffer
        mapped = mmap.mmap(f.fileno(), size, access=mmap.ACCESS_READ)
        _STREAM_MMAP_ACCOUNT.charge(size)
        weakref.finalize(mapped, _STREAM_MMAP_ACCOUNT.credit, size)
        return pa.py_buffer(mapped)
    finally:
        f.close()


async def read_sst(store: ObjectStore, path: str,
                   columns: Optional[list[str]] = None,
                   filters=None, runtimes=None,
                   pool: str = "sst", leaves: Optional[list] = None,
                   size_hint: Optional[int] = None) -> pa.Table:
    """Read an SST, optionally a column subset and a pushed-down
    predicate (row-group pruning via parquet statistics + row filtering
    — the reference's ParquetExec pruning predicate, read.rs:442-465).

    `leaves` (a conjunct_leaves result) selects the fast stats-pruned
    decode; `filters` (a pyarrow expression) is the fallback for
    predicate shapes the pruner refuses.  Both keep exactly the same
    rows.  Local stores expose a filesystem path for mmap'd reads; other
    stores go through a bytes buffer — except objects whose `size_hint`
    (the manifest's SST size) reaches STREAM_FETCH_MIN_BYTES, which
    stream chunk-wise into a file-backed mmap instead of buffering the
    whole object in RSS.  Decode always runs on a worker pool.
    """
    local_path = getattr(store, "local_path", None)
    if local_path is not None:
        try:
            if leaves is not None:
                try:
                    return await _run(runtimes, pool, _read_pruned_source,
                                      local_path(path), columns, leaves,
                                      True)
                except _PruneUnsupported:
                    pass  # nulls in a predicate column: expression path
            return await _run(runtimes, pool, pq.read_table,
                              local_path(path), columns=columns,
                              memory_map=True, filters=filters)
        except FileNotFoundError as e:
            # a compaction deleted the SST between plan and read: map to
            # the store contract's error so scan retries replan (the
            # non-local branch gets this from store.get)
            raise NotFoundError(f"object not found: {path}") from e
    if size_hint is not None and size_hint >= STREAM_FETCH_MIN_BYTES:
        data = await _fetch_mapped(store, path, runtimes, pool)
    else:
        data = await store.get(path)  # fetched ONCE, shared by both paths
    if leaves is not None:
        try:
            return await _run(runtimes, pool, _read_pruned_source,
                              pa.BufferReader(data), columns, leaves, False)
        except _PruneUnsupported:
            pass
    return await _run(runtimes, pool, pq.read_table, pa.BufferReader(data),
                      columns=columns, filters=filters)
