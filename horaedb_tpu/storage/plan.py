"""Composable logical query plan over the merge-scan.

The reference plugs its per-segment MergeExec into arbitrary DataFusion
ExecutionPlan trees (/root/reference/src/storage/src/read.rs:429-494,
storage.rs:359-368).  This engine's query surface is three shapes —
row scan (+filter/project), downsample aggregate, top-k — which used to
be hardwired in their entry points.  `QueryPlan` is the single internal
currency instead: every entry point builds one, the storage facade
executes it, and `describe()` renders the plan text the golden tests
pin (the analogue of the reference's DisplayableExecutionPlan tests,
read.rs:575-617).

Deliberately NOT a DataFusion clone: the operator set is the closed set
the TPU execution actually supports (compiled merge + grid aggregation
+ top-k), so there is no generic optimizer — building a plan IS the
optimization (pushdown/pruning happen in build_plan, aggregation fuses
in the reader).
"""

from __future__ import annotations

import textwrap
from dataclasses import dataclass
from typing import Optional

import numpy as np

from horaedb_tpu.common.error import ensure
from horaedb_tpu.storage.read import (
    AggregateSpec,
    ScanPlan,
    ScanRequest,
    describe_plan,
)


@dataclass(frozen=True)
class TopKSpec:
    """Rank groups by one aggregate grid and keep the best k.

    `by` names a grid in the aggregate output (it must be in the
    spec's `which`); a group's score is that grid's best cell across
    buckets with data (max for largest=True, min otherwise)."""

    k: int
    by: str = "max"
    largest: bool = True


@dataclass
class QueryPlan:
    """scan -> filter (inside scan) -> aggregate? -> top_k?

    `scan` is the physical merge-scan plan captured at build time: it
    renders in describe() and serves as the FIRST attempt's plan in
    execute_plan (one manifest lookup per query); compaction races make
    it stale, in which case execution replans exactly like any raced
    scan."""

    scan: ScanPlan
    request: ScanRequest
    aggregate: Optional[AggregateSpec] = None
    top_k: Optional[TopKSpec] = None

    def describe(self) -> str:
        text = describe_plan(self.scan)
        if self.aggregate is not None:
            spec = self.aggregate
            text = (f"Aggregate: group={spec.group_col}, "
                    f"ts={spec.ts_col}, value={spec.value_col}, "
                    f"bucket={spec.bucket_ms}ms, "
                    f"buckets={spec.num_buckets}, "
                    f"which={tuple(spec.which)}\n"
                    + textwrap.indent(text, "  "))
        if self.top_k is not None:
            tk = self.top_k
            text = (f"TopK: k={tk.k}, by={tk.by}, largest={tk.largest}\n"
                    + textwrap.indent(text, "  "))
        return text


def apply_top_k(group_values: np.ndarray, grids: dict,
                tk: TopKSpec) -> tuple[np.ndarray, dict]:
    """Host top-k over finalized grids: by the time grids exist the
    group axis is small (one row per series), so ranking is a numpy
    argsort — the device's job was reducing rows to grids, not sorting
    k scores.  Returns (values, grids) sliced to the k best groups,
    best first."""
    ensure(tk.by in grids,
           f"top-k by {tk.by!r} needs that aggregate in the spec's "
           f"`which`; have {sorted(grids)}")
    if not len(group_values):
        return group_values, grids
    by = np.asarray(grids[tk.by], dtype=np.float64)
    count = np.asarray(grids["count"])
    if tk.largest:
        score = np.where(count > 0, by, -np.inf).max(axis=1)
        order = np.argsort(-score, kind="stable")
    else:
        score = np.where(count > 0, by, np.inf).min(axis=1)
        order = np.argsort(score, kind="stable")
    idx = order[:tk.k]
    return (np.asarray(group_values)[idx],
            {name: np.asarray(g)[idx] for name, g in grids.items()})
