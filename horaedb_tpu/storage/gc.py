"""Orphan scrubber: manifest-aware object-store garbage reconciliation.

The engine's order-of-operations discipline deliberately LEAKS objects
rather than lose data: a failed write strands an SST the manifest never
saw, compaction's best-effort input deletes can fail, sidecar deletes
are silent.  Nothing reclaimed them — on object storage that garbage
accrues cost forever, and the Arrow-native-storage assumption that
`data/` holds only immutable *referenced* objects erodes.  The scrubber
closes the loop:

  1. Build the referenced id set from BOTH the live manifest cache
     (`manifest.all_ssts()`) and a store-side fold of snapshot + delta
     files.  The union is deliberate: a delta whose put landed but whose
     ack was lost is durable-but-not-cached, and its SSTs must never be
     scrubbed.
  2. List `data/`, parse `{id}.sst` / `{id}.enc` keys, and diff.
     Unparseable keys are never touched.
  3. Delete an unreferenced object only after it has been CONTINUOUSLY
     unreferenced for a grace period — tracked by a first-seen map from
     this scrubber's own observations, never by object timestamps or id
     clocks (a long-lived process's id counter can lag wall clock by
     hours).  The grace window is what makes the in-flight write race
     (SST put before manifest add) safe: a live write closes that gap
     in milliseconds, while a true orphan stays orphaned across passes.

Delta files are NOT scrub targets: the manifest merger already deletes
folded deltas (oldest-first, stop-on-first-failure — see manifest), and
recovery's first_run fold self-heals leftovers.  The scrubber only
reads them for the referenced set and reports the count.

Wiring: a background loop in the compaction scheduler (config
`scrub.interval`), and `POST /admin/scrub` in the server for on-demand
passes.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from horaedb_tpu.objstore import NotFoundError, ObjectStore
from horaedb_tpu.storage.manifest import (
    DELTA_PREFIX,
    Manifest,
    PREFIX_PATH,
    SNAPSHOT_FILENAME,
    _read_snapshot,
)
from horaedb_tpu.storage.manifest.encoding import decode_manifest_update
from horaedb_tpu.storage.sidecar import SIDECAR_SUFFIX
from horaedb_tpu.storage.sst import DATA_PREFIX
from horaedb_tpu.utils import op_trace, registry

logger = logging.getLogger(__name__)

_SCRUB_PASSES = registry.counter(
    "storage_scrub_passes_total", "orphan scrub passes completed")
_SCRUB_DELETED = registry.counter(
    "storage_scrub_orphans_deleted_total",
    "unreferenced data objects deleted by the scrubber")
_SCRUB_BYTES = registry.counter(
    "storage_scrub_orphan_bytes_total",
    "bytes of unreferenced data objects deleted by the scrubber")


@dataclass
class ScrubReport:
    """One scrub pass, in numbers (the /admin/scrub response body)."""

    data_objects: int = 0       # objects listed under data/
    referenced: int = 0         # distinct referenced sst ids
    orphans_seen: int = 0       # unreferenced data objects observed
    orphans_deleted: int = 0    # past grace -> deleted
    orphans_in_grace: int = 0   # observed but younger than grace
    orphan_bytes_deleted: int = 0
    unparseable: int = 0        # unknown keys under data/ (never touched)
    delta_files: int = 0        # delta log files present (informational)
    errors: int = 0             # delete failures (retried next pass)

    def as_dict(self) -> dict:
        return dict(self.__dict__)


@dataclass
class Scrubber:
    """Reconciles `{root}/data/` against the manifest.

    One instance per storage; `first_seen` persists across passes (it IS
    the grace clock).  A restart resets it — conservative: orphans then
    wait one extra grace period, never less."""

    root_path: str
    store: ObjectStore
    manifest: Optional[Manifest]
    grace_period_s: float
    first_seen: dict[str, float] = field(default_factory=dict)

    def _now(self) -> float:
        return time.monotonic()

    async def referenced_ids(self) -> tuple[set[int], int]:
        """Union of the live manifest cache and a store-side fold of
        snapshot + deltas (add-all-then-delete-all, the merger's own
        order).  Either view alone can be momentarily behind the other;
        an id referenced by EITHER is protected.  Returns
        (referenced ids, delta files seen)."""
        refs: set[int] = set()
        if self.manifest is not None:
            refs.update(f.id for f in await self.manifest.all_ssts())

        base = self.root_path.rstrip("/")
        snapshot_path = f"{base}/{PREFIX_PATH}/{SNAPSHOT_FILENAME}"
        delta_dir = f"{base}/{PREFIX_PATH}/{DELTA_PREFIX}/"
        snapshot = await _read_snapshot(self.store, snapshot_path)
        delta_metas = await self.store.list(delta_dir)
        ids = set(snapshot.ids)
        to_deletes: list[int] = []
        bufs = await asyncio.gather(
            *(self.store.get(m.path) for m in delta_metas),
            return_exceptions=True)
        for buf in bufs:
            if isinstance(buf, NotFoundError):
                continue  # folded and deleted mid-scrub
            if isinstance(buf, BaseException):
                raise buf
            update = decode_manifest_update(buf)
            ids.update(f.id for f in update.to_adds)
            to_deletes.extend(update.to_deletes)
        ids.difference_update(to_deletes)
        refs.update(ids)
        return refs, len(delta_metas)

    async def scrub(self, grace_override_s: Optional[float] = None
                    ) -> ScrubReport:
        """One reconcile pass.  Never raises on per-object failures —
        a failed delete is an orphan for the next pass.  Each pass is
        its own op trace (the store list/get/delete traffic attributes
        to it) whether the scrub loop or POST /admin/scrub ran it."""
        with op_trace("scrub", slow_s=120.0, root=self.root_path):
            return await self._scrub_traced(grace_override_s)

    async def _scrub_traced(self, grace_override_s: Optional[float]
                            ) -> ScrubReport:
        grace = (self.grace_period_s if grace_override_s is None
                 else grace_override_s)
        report = ScrubReport()
        now = self._now()

        refs, delta_files = await self.referenced_ids()
        report.referenced = len(refs)
        report.delta_files = delta_files

        data_dir = f"{self.root_path.rstrip('/')}/{DATA_PREFIX}/"
        listed = await self.store.list(data_dir)
        report.data_objects = len(listed)

        live: set[str] = set()
        for meta in listed:
            name = meta.path[len(data_dir):]
            stem, _, suffix = name.partition(".")
            if not stem.isdigit() or ("." + suffix) not in (
                    ".sst", SIDECAR_SUFFIX):
                report.unparseable += 1
                continue
            if int(stem) in refs:
                continue
            report.orphans_seen += 1
            live.add(meta.path)
            seen = self.first_seen.setdefault(meta.path, now)
            if now - seen < grace:
                report.orphans_in_grace += 1
                continue
            try:
                await self.store.delete(meta.path)
            except NotFoundError:
                pass  # already gone (raced a compaction's own delete)
            except Exception as e:  # noqa: BLE001 — next pass retries
                logger.warning("scrub failed to delete %s: %s",
                               meta.path, e)
                report.errors += 1
                continue
            logger.info("scrubbed orphan object %s (%d bytes)",
                        meta.path, meta.size)
            report.orphans_deleted += 1
            report.orphan_bytes_deleted += meta.size
            live.discard(meta.path)
            self.first_seen.pop(meta.path, None)

        # paths that vanished or became referenced must restart their
        # grace clock if they ever reappear unreferenced
        for path in list(self.first_seen):
            if path not in live:
                del self.first_seen[path]

        _SCRUB_PASSES.inc()
        if report.orphans_deleted:
            _SCRUB_DELETED.inc(report.orphans_deleted)
            _SCRUB_BYTES.inc(report.orphan_bytes_deleted)
        return report
