"""Time-window compaction: picker, executor, scheduler
(ref: src/storage/src/compaction/).

- Picker: TimeWindowCompactionStrategy — group non-in-compaction SSTs by
  segment, newest segment first, require >= input_sst_min_num files, pack
  smallest-first up to input_sst_max_num while total size stays within
  1.1 x new_sst_max_size (ref: picker.rs:62-188).  TTL-expired files are
  split out and deleted alongside.  Intentional divergence: the
  reference drops expireds when no segment qualifies (picker.rs:96's
  early return), so TTL'd files linger until a rewrite fires; here an
  expireds-only GC task deletes them without a rewrite.
  TTL math stays in milliseconds (the reference subtracts micros from a
  millis clock — a unit bug SURVEY.md flags; not replicated).
- Executor: memory-gated rewrite (ref: executor.rs:93-114) running THE
  SAME device merge pipeline as scan with keep_builtin=True, streaming
  into one new SST; manifest update {add new, delete inputs+expireds}
  precedes best-effort object deletes (ref: executor.rs:155-222).
- Scheduler: a picker loop (interval or trigger signal) feeding a bounded
  task queue consumed by the executor (ref: scheduler.rs:49-159).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

import pyarrow as pa

from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.loops import loops
from horaedb_tpu.common.tasks import cancel_and_wait
from horaedb_tpu.common.time_ext import now_ms
from horaedb_tpu.storage import parquet_io, sidecar
from horaedb_tpu.storage.manifest import ManifestUpdate
from horaedb_tpu.storage.read import ScanRequest
from horaedb_tpu.storage.sst import FileMeta, SstFile, sst_path, segment_of
from horaedb_tpu.storage.types import (
    RESERVED_COLUMN_NAME,
    Timestamp,
    TimeRange,
)

if TYPE_CHECKING:
    from horaedb_tpu.storage.storage import CloudObjectStorage

from horaedb_tpu.utils import WIDE_BUCKETS, op_trace, registry, span

logger = logging.getLogger(__name__)

_COMPACTIONS = registry.counter(
    "compaction_completed_total", "compaction tasks completed")
_COMPACTION_ROWS = registry.counter(
    "compaction_rows_rewritten_total", "rows rewritten by compaction")
_TTL_GC_FILES = registry.counter(
    "ttl_gc_files_total", "expired ssts removed by TTL garbage collection")


@dataclass
class Task:
    """(ref: compaction/mod.rs:26-36)"""

    inputs: list[SstFile]
    expireds: list[SstFile] = field(default_factory=list)

    @property
    def input_size(self) -> int:
        return sum(f.size for f in self.inputs)


class TimeWindowCompactionStrategy:
    def __init__(self, segment_duration_ms: int, new_sst_max_size: int,
                 input_sst_max_num: int, input_sst_min_num: int):
        self.segment_duration_ms = segment_duration_ms
        self.new_sst_max_size = new_sst_max_size
        self.input_sst_max_num = input_sst_max_num
        self.input_sst_min_num = input_sst_min_num

    def pick_candidate(self, ssts: list[SstFile],
                       expire_time: Optional[Timestamp]) -> Optional[Task]:
        uncompacted = [f for f in ssts
                       if not f.in_compaction and not f.is_expired(expire_time)]
        expireds = [f for f in ssts
                    if not f.in_compaction and f.is_expired(expire_time)]

        by_segment: dict[int, list[SstFile]] = {}
        for f in uncompacted:
            seg = segment_of(f, self.segment_duration_ms)
            by_segment.setdefault(seg, []).append(f)

        inputs = self._pick_files(by_segment)
        if inputs is None:
            # The reference drops expireds here (picker.rs:96's early
            # return), so TTL'd files linger until a rewrite also fires.
            # We instead emit an expireds-only GC task — pure deletes,
            # no rewrite (executor.gc_expired).
            if not expireds:
                return None
            for f in expireds:
                f.mark_compaction()
            return Task(inputs=[], expireds=expireds)
        for f in inputs:
            f.mark_compaction()
        for f in expireds:
            f.mark_compaction()
        return Task(inputs=inputs, expireds=expireds)

    def _pick_files(self, by_segment: dict[int, list[SstFile]]) -> Optional[list[SstFile]]:
        # newest segment first; compacting fresh data keeps read amp low
        for seg in sorted(by_segment, reverse=True):
            files = by_segment[seg]
            if len(files) < self.input_sst_min_num:
                continue
            files = sorted(files, key=lambda f: f.size)
            picked: list[SstFile] = []
            total = 0
            # assume ~10% shrink from dedup, so allow 1.1x the target size
            budget = int(self.new_sst_max_size * 1.1)
            for f in files[: self.input_sst_max_num]:
                total += f.size
                if total > budget:
                    break
                picked.append(f)
            if len(picked) >= self.input_sst_min_num:
                return picked
        return None


class Picker:
    """Serial-only candidate picker (ref: picker.rs:25-60)."""

    def __init__(self, storage: "CloudObjectStorage"):
        cfg = storage.config.scheduler
        self.storage = storage
        self.ttl_ms = cfg.ttl.millis if cfg.ttl else None
        self.strategy = TimeWindowCompactionStrategy(
            segment_duration_ms=storage.segment_duration_ms,
            new_sst_max_size=cfg.new_sst_max_size.bytes,
            input_sst_max_num=cfg.input_sst_max_num,
            input_sst_min_num=cfg.input_sst_min_num,
        )

    async def pick_candidate(self) -> Optional[Task]:
        ssts = await self.storage.manifest.all_ssts()
        expire_time = (Timestamp(now_ms() - self.ttl_ms)
                       if self.ttl_ms is not None else None)
        return self.strategy.pick_candidate(ssts, expire_time)


class Executor:
    """Memory-gated compaction rewrite (ref: executor.rs)."""

    def __init__(self, storage: "CloudObjectStorage", trigger: asyncio.Queue):
        self.storage = storage
        self.mem_limit = storage.config.scheduler.memory_limit.bytes
        self.inused_memory = 0
        self._trigger = trigger

    def _pre_check(self, task: Task) -> None:
        """Reserve task memory; raises WITHOUT reserving when over limit."""
        ensure(task.inputs, "compaction task with no inputs")
        task_size = task.input_size
        ensure(self.inused_memory + task_size <= self.mem_limit,
               f"Compaction memory usage too high, inused:{self.inused_memory}, "
               f"task_size:{task_size}, limit:{self.mem_limit}")
        self.inused_memory += task_size

    @staticmethod
    def _unmark(task: Task) -> None:
        """Failed tasks are unmarked so the picker can retry them
        (ref: executor.rs:123-137)."""
        for f in task.inputs:
            f.unmark_compaction()
        for f in task.expireds:
            f.unmark_compaction()

    def _trigger_more(self) -> None:
        try:
            self._trigger.put_nowait(None)
        except asyncio.QueueFull:
            pass

    async def execute(self, task: Task) -> None:
        if not task.inputs:
            await self.gc_expired(task)
            return
        try:
            self._pre_check(task)
        except Error:
            # nothing was reserved — only unmark for re-pick
            self._unmark(task)
            raise
        ok = False
        try:
            await self._do_compaction(task)
            ok = True
        finally:
            self.inused_memory -= task.input_size
            if not ok:
                self._unmark(task)

    async def _delete_objects(self, file_ids: list[int]) -> None:
        """Best-effort parallel SST object deletes (manifest already
        updated, so errors are logged, never raised —
        ref: executor.rs:224-253)."""
        # tier-2 entries for deleted ids go first: the SSTs will never
        # be read again, and per-SST invalidation is the WHOLE eviction
        # story — every surviving SST's part stays resident
        self.storage.reader.encoded_cache.invalidate(file_ids)
        results = await asyncio.gather(
            *(self.storage.store.delete(
                sst_path(self.storage.root_path, fid))
              for fid in file_ids),
            return_exceptions=True)
        for fid, res in zip(file_ids, results):
            if isinstance(res, BaseException):
                logger.error("failed to delete sst %s: %s", fid, res)
        # sidecars ride along, fully silent: most SSTs predating the
        # sidecar (or Append tables) simply have none
        await asyncio.gather(
            *(self.storage.store.delete(
                sidecar.sidecar_path(self.storage.root_path, fid))
              for fid in file_ids),
            return_exceptions=True)

    async def gc_expired(self, task: Task) -> None:
        """TTL garbage collection: drop expired SSTs from the manifest,
        then best-effort delete the objects.  No rewrite, no memory gate
        (nothing is read)."""
        with op_trace("ttl_gc", slow_s=120.0,
                      expireds=len(task.expireds)):
            await self._gc_expired_traced(task)

    async def _gc_expired_traced(self, task: Task) -> None:
        ok = False
        try:
            to_deletes = [f.id for f in task.expireds]
            if not to_deletes:
                ok = True
                return
            await self.storage.manifest.update(
                ManifestUpdate(to_adds=[], to_deletes=to_deletes))
            ok = True
            _TTL_GC_FILES.inc(len(to_deletes))
            await self._delete_objects(to_deletes)
        finally:
            if not ok:
                self._unmark(task)

    async def _do_compaction(self, task: Task) -> None:
        # each rewrite is a background op with its own trace tree
        # (objstore GETs/bytes and cache admissions attribute to it);
        # "slow" for a compaction is ten minutes, not the query scale.
        # The compaction.execute span keeps its histogram: rewrites
        # routinely outlast the default 10 s bucket ceiling, so the
        # wide layout keeps it informative
        with op_trace("compaction", slow_s=600.0,
                      inputs=len(task.inputs), bytes=task.input_size):
            with span("compaction.execute", buckets=WIDE_BUCKETS,
                      inputs=len(task.inputs),
                      expireds=len(task.expireds), bytes=task.input_size):
                await self._do_compaction_traced(task)

    async def _do_compaction_traced(self, task: Task) -> None:
        self._trigger_more()
        storage = self.storage
        time_range = task.inputs[0].meta.time_range
        for f in task.inputs[1:]:
            time_range = time_range.merged(f.meta.time_range)

        # The same merge pipeline as scan, keeping builtin columns so
        # surviving rows retain their original sequences.
        # use_cache=False: the inputs are deleted right after, so caching
        # their merge would only evict hot query entries
        # pool="compact": the rewrite's CPU work queues on the dedicated
        # compaction pool, never in front of serving scans/writes
        plan = storage.reader.build_plan(
            task.inputs, ScanRequest(range=TimeRange.new(-(2**63), 2**63 - 1)),
            keep_builtin=True, use_cache=False, pool="compact")

        file_id = SstFile.allocate_id()
        path = sst_path(storage.root_path, file_id)

        # stream batches through the parquet encoder INTO the store —
        # peak memory is ~one row group (+ one multipart part on S3),
        # not the compressed output: a 1 GiB rewrite costs megabytes of
        # RSS (ref: storage.rs:192-212 AsyncArrowWriter pipeline).
        # Device-layout sidecar parts are collected alongside (encoded
        # i32/f32, ~12B/row) up to write.sidecar_max_rows — past that
        # the cap voids the sidecar to keep the rewrite's RSS bounded.
        from horaedb_tpu.storage.config import UpdateMode

        sc_parts: Optional[list] = (
            [] if (storage.schema().update_mode is UpdateMode.OVERWRITE
                   and storage.config.write.enable_sidecar) else None)
        sc_rows = 0

        async def restored():
            # one sidecar encode stays in flight while the SAME batch's
            # parquet encode runs (the pool has >1 compact thread), so
            # the sidecar costs overlap the rewrite instead of adding to
            # it; RSS holds at most one extra batch's encoded columns
            nonlocal sc_parts, sc_rows
            in_flight: Optional[asyncio.Task] = None

            async def settle():
                nonlocal sc_parts, in_flight
                if in_flight is None:
                    return
                task, in_flight = in_flight, None
                part = await task
                if sc_parts is not None:
                    if part is None:
                        sc_parts = None
                    else:
                        sc_parts.append(part)

            try:
                async for batch in storage.reader.execute(plan):
                    await settle()
                    if sc_parts is not None:
                        sc_rows += batch.num_rows
                        if sc_rows > storage.config.write.sidecar_max_rows:
                            sc_parts = None
                        else:
                            in_flight = asyncio.ensure_future(
                                storage.runtimes.run(
                                    "compact", sidecar.encode_columns,
                                    batch))
                    yield _restore_reserved_column(batch, storage.schema())
                await settle()
            finally:
                if in_flight is not None:
                    in_flight.cancel()

        size, num_rows = await parquet_io.write_sst_streaming(
            storage.store, path, restored(), storage.config.write,
            storage.schema(), runtimes=storage.runtimes, pool="compact")
        if sc_parts:
            try:
                merged = await storage.runtimes.run(
                    "compact", sidecar.merge_parts, sc_parts)
                if merged is not None:
                    cols, n_enc = merged
                    # write-through admission: the compactor holds the
                    # output's encoded columns in hand — insert them
                    # into tier-2 now, so the first post-compaction
                    # query rebuilds from host RAM, not the store
                    storage.reader.encoded_cache.admit(file_id, cols,
                                                       n_enc)
                    data = await storage.runtimes.run(
                        "compact", sidecar.serialize, cols, n_enc)
                    if data is not None:
                        await storage.store.put(
                            sidecar.sidecar_path(storage.root_path,
                                                 file_id),
                            data)
            except Exception as exc:  # noqa: BLE001 — cache write only
                logger.warning("sidecar write failed for compacted sst "
                               "%s: %s", file_id, exc)
        sc_parts = None
        meta = FileMeta(max_sequence=file_id, num_rows=num_rows, size=size,
                        time_range=time_range)
        logger.debug("compaction output sst id=%s rows=%s size=%s",
                     file_id, num_rows, size)

        # 1. new SST into the manifest, THEN 2. delete inputs+expireds —
        # a crash in between leaves garbage objects, never data loss.
        to_deletes = [f.id for f in task.expireds] + [f.id for f in task.inputs]
        await storage.manifest.update(ManifestUpdate(
            to_adds=[SstFile(file_id, meta)], to_deletes=to_deletes))

        _COMPACTIONS.inc()
        _COMPACTION_ROWS.inc(num_rows)

        # From here on, errors must not propagate (manifest already updated).
        await self._delete_objects(to_deletes)


def _restore_reserved_column(batch: pa.RecordBatch, schema) -> pa.RecordBatch:
    """Scan output omits the all-null __reserved__ column; the SST schema
    requires it, so stamp it back before writing."""
    if RESERVED_COLUMN_NAME in batch.schema.names:
        return batch
    arrays = [batch.column(i) for i in range(batch.num_columns)]
    arrays.append(pa.nulls(batch.num_rows, type=pa.uint64()))
    names = list(batch.schema.names) + [RESERVED_COLUMN_NAME]
    out = pa.RecordBatch.from_arrays(arrays, names=names)
    # reorder to the full storage schema
    return out.select(schema.arrow_schema.names).cast(schema.arrow_schema)


class Scheduler:
    """Background picker + executor loops (ref: scheduler.rs:49-159)."""

    def __init__(self, storage: "CloudObjectStorage"):
        cfg = storage.config.scheduler
        self.storage = storage
        self.interval_s = cfg.schedule_interval.seconds
        self._trigger: asyncio.Queue = asyncio.Queue(maxsize=4)
        self._tasks: asyncio.Queue = asyncio.Queue(
            maxsize=cfg.max_pending_compaction_tasks)
        self.picker = Picker(storage)
        self.executor = Executor(storage, self._trigger)
        self._loops: list[asyncio.Task] = []
        # loops check this at every turn: a cancel delivered exactly as
        # a trigger token completes the wait_for is SWALLOWED
        # (bpo-37658), so cancellation alone cannot be the only exit
        self._stopping = False

    async def start(self) -> None:
        self._stopping = False
        root = self.storage.root_path
        # the spawn helper registers every loop with the watchdog
        # (common/loops.py): names are per-table (root path), the
        # metric label is the stable kind.  The executor's threshold
        # is sized to a worst-case rewrite — flag wedged, not busy.
        self._loops = [
            loops.spawn(self._generate_task_loop,
                        name=f"compact-picker:{root}",
                        kind="compact-picker", owner="compaction",
                        period_s=self.interval_s,
                        backlog=self._backlog),
            loops.spawn(self._recv_task_loop,
                        name=f"compact-executor:{root}",
                        kind="compact-executor", owner="compaction",
                        stall_threshold_s=900.0,
                        backlog=self._backlog),
        ]
        # the orphan scrubber rides the compaction scheduler's lifecycle:
        # same background-loop ownership, stopped by the same stop()
        scrub_cfg = self.storage.config.scrub
        if scrub_cfg.enabled:
            self._loops.append(loops.spawn(
                lambda hb: self._scrub_loop(hb, scrub_cfg.interval.seconds),
                name=f"orphan-scrubber:{root}", kind="orphan-scrubber",
                owner="compaction",
                period_s=scrub_cfg.interval.seconds,
                stall_threshold_s=300.0))

    def _backlog(self) -> dict:
        """/debug/tasks hint: pending compaction work (the "scores"
        signal — queued tasks and reserved rewrite memory)."""
        return {"pending_tasks": self._tasks.qsize(),
                "pending_triggers": self._trigger.qsize(),
                "inused_memory": self.executor.inused_memory}

    async def stop(self) -> None:
        # flag + cancel_and_wait, not cancel+await: trigger tokens race
        # stop() by design (a failing execute's trigger_more vs close),
        # and with a dead store the pick→execute→trigger cycle produces
        # tokens continuously, so EVERY cancel can land on a completed
        # wait_for and be swallowed (bpo-37658) — the flag guarantees
        # the loop exits at its next turn regardless (the torture
        # harness reproduces the hang in a few hundred schedules)
        self._stopping = True
        for t in self._loops:
            await cancel_and_wait(t)
        self._loops = []

    async def trigger(self) -> None:
        """Manual compaction entry (HTTP /compact, ref: scheduler.rs:106-112)."""
        try:
            self._trigger.put_nowait(None)
        except asyncio.QueueFull:
            pass

    async def _generate_task_loop(self, hb) -> None:
        while not self._stopping:
            try:
                await asyncio.wait_for(self._trigger.get(),
                                       timeout=self.interval_s)
            except (TimeoutError, asyncio.TimeoutError):
                pass
            hb.beat()
            if self._stopping:
                return
            # picker must run serially (in_compaction marking is the lock);
            # transient store errors must not kill the loop
            try:
                task = await self.picker.pick_candidate()
                hb.ok()
            except Exception as exc:  # noqa: BLE001 — retried next tick
                hb.error(exc)
                logger.exception("compaction pick failed; will retry")
                continue
            if task is not None:
                try:
                    self._tasks.put_nowait(task)
                except asyncio.QueueFull:
                    # never ran pre_check, so only unmark (no memory to return)
                    logger.warning("compaction task queue full, dropping pick")
                    for f in task.inputs + task.expireds:
                        f.unmark_compaction()

    async def _recv_task_loop(self, hb) -> None:
        failure_streak = 0
        while not self._stopping:
            hb.idle()  # parked on the task queue (healthy silence)
            task = await self._tasks.get()
            hb.beat()
            try:
                await self.executor.execute(task)
                hb.ok()
                failure_streak = 0
            except Exception as exc:  # noqa: BLE001 — backoff + retry
                hb.error(exc)
                logger.exception("compaction task failed")
                # back off on repeated failure: a dead store otherwise
                # spins the pick→execute→trigger cycle at full speed (a
                # retry storm against a struggling backend, and a
                # shutdown that can never land a cancellation)
                failure_streak += 1
                await asyncio.sleep(min(5.0, 0.05 * 2 ** failure_streak))

    async def _scrub_loop(self, hb, interval_s: float) -> None:
        while not self._stopping:
            hb.idle()  # the inter-pass sleep (often minutes) is healthy
            await asyncio.sleep(interval_s)
            hb.beat()
            try:
                report = await self.storage.scrubber.scrub()
                hb.ok()
                if report.orphans_deleted or report.errors:
                    logger.info("scrub pass: %s", report.as_dict())
            except Exception as exc:  # noqa: BLE001 — retried next pass
                hb.error(exc)
                logger.exception("orphan scrub pass failed; will retry")
