"""Core storage types: Timestamp, TimeRange, StorageSchema.

Mirrors src/storage/src/types.rs: the schema layout is
  pk1..pkN, value1..valueM, __seq__, __reserved__
with the two builtin UInt64 columns appended by the engine
(ref: types.rs:35-41, 160-196).  The per-file sequence stamped into
__seq__ is load-bearing for cross-file dedup: the merge path keeps the
row with the highest sequence among equal primary keys.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np
import pyarrow as pa

from horaedb_tpu.common.error import ensure
from horaedb_tpu.storage.config import UpdateMode

BUILTIN_COLUMN_NUM = 2
SEQ_COLUMN_NAME = "__seq__"
RESERVED_COLUMN_NAME = "__reserved__"

_I64_MIN = -(2**63)
_I64_MAX = 2**63 - 1


def _div_trunc(a: int, b: int) -> int:
    """Integer division truncating toward zero (Rust `/` on i64)."""
    q = a // b
    if a % b != 0 and (a < 0) != (b < 0):
        q += 1
    return q


class Timestamp(int):
    """Millisecond timestamp (ref: types.rs:45-86)."""

    MIN: "Timestamp"
    MAX: "Timestamp"

    def truncate_by(self, duration_ms: int) -> "Timestamp":
        """Align down toward zero to a duration boundary (ref: types.rs:82-85).

        Matches Rust i64 division semantics (truncation, not floor) so
        segment assignment of pre-epoch timestamps is bit-identical.
        """
        ensure(duration_ms > 0, "truncate_by needs a positive duration")
        return Timestamp(_div_trunc(int(self), duration_ms) * duration_ms)

    def __repr__(self) -> str:
        return f"Timestamp({int(self)})"


Timestamp.MIN = Timestamp(_I64_MIN)
Timestamp.MAX = Timestamp(_I64_MAX)


@dataclass(frozen=True, order=True)
class TimeRange:
    """Half-open range [start, end) (ref: types.rs:88-133)."""

    start: Timestamp
    end: Timestamp

    @classmethod
    def new(cls, start: int, end: int) -> "TimeRange":
        return cls(Timestamp(start), Timestamp(end))

    def overlaps(self, other: "TimeRange") -> bool:
        return self.start < other.end and other.start < self.end

    def contains(self, ts: int) -> bool:
        return self.start <= ts < self.end

    def merged(self, other: "TimeRange") -> "TimeRange":
        return TimeRange(
            Timestamp(min(self.start, other.start)),
            Timestamp(max(self.end, other.end)),
        )

    def __repr__(self) -> str:
        return f"[{int(self.start)}, {int(self.end)})"


@dataclass
class StorageSchema:
    """User schema + engine builtin columns (ref: types.rs:149-240).

    Layout: num_primary_keys PK columns first, then >=1 value columns,
    then __seq__ and __reserved__ (both UInt64, nullable) appended by us.
    """

    arrow_schema: pa.Schema
    num_primary_keys: int
    seq_idx: int
    reserved_idx: int
    value_idxes: list[int]
    update_mode: UpdateMode

    @classmethod
    def try_new(
        cls,
        user_schema: pa.Schema,
        num_primary_keys: int,
        update_mode: UpdateMode,
    ) -> "StorageSchema":
        ensure(num_primary_keys > 0, "num_primary_keys should be larger than 0")
        names = set(user_schema.names)
        ensure(
            SEQ_COLUMN_NAME not in names and RESERVED_COLUMN_NAME not in names,
            "schema should not use builtin column names",
        )
        num_fields = len(user_schema)
        value_idxes = list(range(num_primary_keys, num_fields))
        ensure(value_idxes, "no value column found")

        full = user_schema.append(pa.field(SEQ_COLUMN_NAME, pa.uint64())) \
                          .append(pa.field(RESERVED_COLUMN_NAME, pa.uint64()))
        return cls(
            arrow_schema=full,
            num_primary_keys=num_primary_keys,
            seq_idx=num_fields,
            reserved_idx=num_fields + 1,
            value_idxes=value_idxes,
            update_mode=update_mode,
        )

    @property
    def user_schema(self) -> pa.Schema:
        return pa.schema(
            [self.arrow_schema.field(i) for i in range(self.seq_idx)],
            metadata=self.arrow_schema.metadata,
        )

    @property
    def primary_key_names(self) -> list[str]:
        return self.arrow_schema.names[: self.num_primary_keys]

    @staticmethod
    def is_builtin_name(name: str) -> bool:
        return name in (SEQ_COLUMN_NAME, RESERVED_COLUMN_NAME)

    def fill_required_projections(self, projection: Optional[list[int]]) -> Optional[list[int]]:
        """PKs and __seq__ are always needed by the merge path
        (ref: types.rs:202-215).  Returns the augmented projection."""
        if projection is None:
            return None
        proj = list(projection)
        for i in range(self.num_primary_keys):
            if i not in proj:
                proj.append(i)
        if self.seq_idx not in proj:
            proj.append(self.seq_idx)
        return proj

    def fill_builtin_columns(self, batch: pa.RecordBatch, sequence: int) -> pa.RecordBatch:
        """Stamp the per-file sequence on every row (ref: types.rs:219-239)."""
        n = batch.num_rows
        if n == 0:
            return batch
        seq = pa.array(np.full(n, sequence, dtype=np.uint64))
        reserved = pa.nulls(n, type=pa.uint64())
        cols = list(batch.columns) + [seq, reserved]
        return pa.RecordBatch.from_arrays(cols, schema=self.arrow_schema)
