"""Cross-part aggregate combine — THE module that may allocate output
grids.

Every aggregation path ends here: per-window partial grids (each
covering LOCAL buckets [lo, lo + width) of the query's bucket range)
fold into the user-facing (groups, num_buckets) aggregate grids.  Three
coordinated pieces kill the output-grid cliff the scale ladder measured
(bench_results/scale_r5.md: combine/finalize materializing hosts x
buckets float64 cells went 4.4x superlinear at 200M rows):

  sparse combine   parts fold straight into the FINAL output buffers as
                   per-series bucket runs — full-group parts (the common
                   shape: every window of the headline scan carries all
                   series) paste as in-place column-slice ops with ZERO
                   gather/scatter temporaries, and finalize converts in
                   place instead of np.where-ing whole fresh grids.  The
                   dense fold (one f64 accumulator set + a separate
                   output set, fancy-indexed read-modify-write per part)
                   is kept behind [scan.combine] mode = "dense" and the
                   chaos suite proves the two bit-identical.

  top-k pushdown   a TopKSpec folds each group's runs into a SPAN-sized
                   transient, scores it, and materializes only the k
                   winners — peak materialized output is O(k x buckets)
                   no matter the series cardinality (the north-star 1B
                   top-k never builds the hosts x buckets grid).

  delta summation  a byte-bounded per-segment partial memo (PartsMemo,
                   keyed by the segment's exact SST set + the
                   range-independent aggregate fingerprint) serves
                   narrowed/refined dashboard ranges from prior
                   partials, recomputing only delta segments ("An
                   improved method of delta summation…", PAPERS.md).

Grid-allocation discipline: tools/lint.py rejects dense
(groups, num_buckets) numpy allocations outside this module, so future
aggregation code goes through this API instead of growing new cliffs.

Bit-identity contract (asserted by tests/test_combine.py seeded chaos):
for the same parts, sparse and dense produce byte-equal grids — f64
folds run in the same part order with the same casts, and empty-cell
conventions (count 0, sum 0, min +inf, max -inf, avg/last/last_ts NaN)
match cell for cell.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from horaedb_tpu.common.error import ensure
from horaedb_tpu.ops.downsample import ALL_AGGS
from horaedb_tpu.storage.scan_cache import ByteLRU
from horaedb_tpu.utils import registry, trace_add

COMBINE_MODES = ("sparse", "dense")

_I64_MIN = np.iinfo(np.int64).min

# combine economics: touched cells (sum of part run cells) vs the dense
# output-grid cells — the operator's evidence for whether a workload is
# run-bound (healthy) or grid-bound (the cliff).  materialized counts
# the output cells actually allocated, which the top-k pushdown keeps at
# O(k x buckets) independent of group cardinality.
_TOUCHED = registry.counter(
    "scan_combine_touched_cells_total",
    "aggregate part cells folded by combine (groups x run width, "
    "summed over parts)")
_GRID = registry.counter(
    "scan_combine_grid_cells_total",
    "dense output-grid cells (groups x buckets) per combine call")
_MATERIALIZED = registry.counter(
    "scan_combine_materialized_cells_total",
    "output cells actually allocated by combine/finalize (top-k "
    "pushdown bounds this at k x buckets x aggs)")
_MEMO_HITS = registry.counter(
    "scan_combine_memo_hits_total",
    "delta-summation memo hits (a segment's partials served without "
    "re-scanning)")
_MEMO_MISSES = registry.counter(
    "scan_combine_memo_misses_total",
    "delta-summation memo misses")
_MEMO_UNCOVERED = registry.counter(
    "scan_combine_memo_uncovered_total",
    "memo entries present but unusable: the new query's grid reaches "
    "buckets the stored partials were clipped away from (range WIDENED "
    "past the recorded grid)")
_MEMO_PARTS = registry.counter(
    "scan_combine_memo_parts_served_total",
    "aggregate parts served from the delta-summation memo")


def expand_which(which) -> set:
    """Requested aggregates plus their computation dependencies: avg
    needs sum, last carries last_ts, count always rides along (combine
    and finalize key on it)."""
    want = set(which) | {"count"}
    if "avg" in want:
        want.add("sum")
    return want


def emitted_aggs(which) -> list[str]:
    """Output grid keys for a request, in the canonical emit order."""
    requested = set(which) | {"count"}
    return [k for k in ("count", "sum", "min", "max", "avg", "last",
                        "last_ts")
            if k in requested or (k == "last_ts" and "last" in requested)]


def _empty_result(num_buckets: int, which) -> tuple[np.ndarray, dict]:
    empty = np.zeros((0, num_buckets), dtype=np.float32)
    return np.asarray([]), {k: empty.copy() for k in emitted_aggs(which)}


def _identity_grids(g: int, num_buckets: int, want: set) -> dict:
    """f64 accumulator grids with combine-identity fills, matching
    ops.downsample's partial conventions."""
    acc: dict = {"count": np.zeros((g, num_buckets), dtype=np.float64)}
    if "sum" in want:
        acc["sum"] = np.zeros((g, num_buckets), dtype=np.float64)
    if "min" in want:
        acc["min"] = np.full((g, num_buckets), np.inf, dtype=np.float64)
    if "max" in want:
        acc["max"] = np.full((g, num_buckets), -np.inf, dtype=np.float64)
    if "last" in want:
        acc["last"] = np.zeros((g, num_buckets), dtype=np.float64)
        acc["last_ts"] = np.full((g, num_buckets), _I64_MIN,
                                 dtype=np.int64)
    return acc


def _union_values(parts: list) -> np.ndarray:
    return np.unique(np.concatenate([v for v, _, _ in parts]))


def combine_aggregate_parts(parts: list[tuple[np.ndarray, int, dict]],
                            num_buckets: int,
                            which: tuple = ALL_AGGS
                            ) -> tuple[np.ndarray, dict]:
    """The DENSE fold ([scan.combine] mode = "dense"): one f64
    accumulator set, per-part fancy-indexed read-modify-write, then a
    separate output set built with np.where passes.  Kept as the
    bit-identity control for the sparse path; each part is
    (group_values, bucket_lo, grids) with grids covering LOCAL buckets
    [bucket_lo, bucket_lo + width).  `last` combines by latest
    (range-relative) timestamp, later part winning ties (parts arrive
    in segment/window order)."""
    requested = set(which) | {"count"}
    want = expand_which(requested)
    if not parts:
        return _empty_result(num_buckets, which)
    all_values = _union_values(parts)
    g = len(all_values)
    _GRID.inc(g * num_buckets)
    acc = _identity_grids(g, num_buckets, want)
    for values, lo, p in parts:
        _TOUCHED.inc(len(values) * p["count"].shape[1])
        rows = np.searchsorted(all_values, values)
        width = p["count"].shape[1]
        sl = slice(lo, lo + width)
        acc["count"][rows, sl] += p["count"]
        if "sum" in acc:
            acc["sum"][rows, sl] += p["sum"]
        if "min" in acc:
            acc["min"][rows, sl] = np.minimum(acc["min"][rows, sl],
                                              p["min"])
        if "max" in acc:
            acc["max"][rows, sl] = np.maximum(acc["max"][rows, sl],
                                              p["max"])
        if "last" in acc:
            newer = p["last_ts"].astype(np.int64) >= acc["last_ts"][rows,
                                                                    sl]
            has_data = p["count"] > 0
            take = newer & has_data
            last_rows = acc["last"][rows, sl]
            last_rows[take] = p["last"][take]
            acc["last"][rows, sl] = last_rows
            lt_rows = acc["last_ts"][rows, sl]
            lt_rows[take] = p["last_ts"].astype(np.int64)[take]
            acc["last_ts"][rows, sl] = lt_rows
    empty = acc["count"] == 0
    out = {"count": acc["count"]}
    # expose sum only when EXPLICITLY requested — it may be present in
    # acc merely as avg's dependency
    if "sum" in acc and "sum" in requested:
        out["sum"] = acc["sum"]
    if "sum" in acc and "avg" in want:
        with np.errstate(invalid="ignore", divide="ignore"):
            out["avg"] = np.where(empty, np.nan,
                                  acc["sum"] / np.maximum(acc["count"], 1))
    # count-0 cells read the documented +/-inf identities REGARDLESS
    # of part coverage: a part whose span merely touched the cell left
    # the device kernel's F32_MAX fill behind, which made empty-cell
    # bytes depend on round/part composition (host windows vs device
    # decode vs mesh runs carry different group unions).  The fused
    # path always masked (_fused_finalize_jit); the parts path now
    # matches it — and the module contract above.
    if "min" in acc:
        out["min"] = np.where(empty, np.inf, acc["min"])
    if "max" in acc:
        out["max"] = np.where(empty, -np.inf, acc["max"])
    if "last" in acc:
        out["last"] = np.where(empty, np.nan, acc["last"])
        # exposed (as float, NaN for empty) so cross-region merges can
        # pick `last` by actual sample time instead of region order
        out["last_ts"] = np.where(empty, np.nan,
                                  acc["last_ts"].astype(np.float64))
    _MATERIALIZED.inc(g * num_buckets * len(out))
    return all_values, out


def _fold_part(acc: dict, rows, sl: slice, p: dict) -> None:
    """Fold one part into the output buffers.  `rows` is None for a
    FULL part (its group set == the union): the fold is then pure
    in-place column-slice arithmetic — no gather/scatter temporaries —
    which is the headline scan's common shape (every window carries all
    series).  Subset parts take the same fancy-indexed path as the
    dense fold, so cell values cannot differ between the branches."""
    if rows is None:
        acc["count"][:, sl] += p["count"]
        if "sum" in acc:
            acc["sum"][:, sl] += p["sum"]
        if "min" in acc:
            mv = acc["min"][:, sl]
            np.minimum(mv, p["min"], out=mv)
        if "max" in acc:
            xv = acc["max"][:, sl]
            np.maximum(xv, p["max"], out=xv)
        if "last" in acc:
            lt_view = acc["last_ts"][:, sl]
            newer = p["last_ts"].astype(np.int64) >= lt_view
            take = newer & (p["count"] > 0)
            np.copyto(acc["last"][:, sl], p["last"], where=take,
                      casting="same_kind")
            np.copyto(lt_view, p["last_ts"].astype(np.int64), where=take)
        return
    acc["count"][rows, sl] += p["count"]
    if "sum" in acc:
        acc["sum"][rows, sl] += p["sum"]
    if "min" in acc:
        acc["min"][rows, sl] = np.minimum(acc["min"][rows, sl], p["min"])
    if "max" in acc:
        acc["max"][rows, sl] = np.maximum(acc["max"][rows, sl], p["max"])
    if "last" in acc:
        newer = p["last_ts"].astype(np.int64) >= acc["last_ts"][rows, sl]
        take = newer & (p["count"] > 0)
        last_rows = acc["last"][rows, sl]
        last_rows[take] = p["last"][take]
        acc["last"][rows, sl] = last_rows
        lt_rows = acc["last_ts"][rows, sl]
        lt_rows[take] = p["last_ts"].astype(np.int64)[take]
        acc["last_ts"][rows, sl] = lt_rows


def _finalize_in_place(acc: dict, requested: set, want: set) -> dict:
    """Turn fold buffers into the output dict with the dense path's
    cell conventions, mutating in place instead of allocating fresh
    np.where grids.  avg divides only where count > 0 (identical values
    to sum / max(count, 1) there) and NaNs the rest."""
    out = {"count": acc["count"]}
    empty = None
    if "avg" in want or "last" in acc or "min" in acc or "max" in acc:
        empty = acc["count"] == 0
    if "sum" in acc and "sum" in requested:
        out["sum"] = acc["sum"]
    if "sum" in acc and "avg" in want:
        avg = np.empty_like(acc["sum"])
        np.divide(acc["sum"], acc["count"], out=avg, where=~empty)
        avg[empty] = np.nan
        out["avg"] = avg
    # count-0 min/max cells read the +/-inf identities regardless of
    # part coverage (see combine_aggregate_parts — the dense control
    # applies the same mask, so the two stay byte-identical)
    if "min" in acc:
        mv = acc["min"]
        mv[empty] = np.inf
        out["min"] = mv
    if "max" in acc:
        xv = acc["max"]
        xv[empty] = -np.inf
        out["max"] = xv
    if "last" in acc:
        last = acc["last"]
        last[empty] = np.nan
        out["last"] = last
        lt = acc["last_ts"].astype(np.float64)
        lt[empty] = np.nan
        out["last_ts"] = lt
    return out


def sparse_combine_parts(parts: list[tuple[np.ndarray, int, dict]],
                         num_buckets: int,
                         which: tuple = ALL_AGGS
                         ) -> tuple[np.ndarray, dict]:
    """The sparse fold ([scan.combine] mode = "sparse", the default):
    parts paste straight into the FINAL output buffers — full-group
    parts as in-place column-slice runs, finalize in place — so combine
    allocates exactly ONE grid set (the requested aggs) and touches
    only run cells beyond the identity fills.  Bit-identical to
    combine_aggregate_parts (seeded chaos asserts byte equality)."""
    requested = set(which) | {"count"}
    want = expand_which(requested)
    if not parts:
        return _empty_result(num_buckets, which)
    all_values = _union_values(parts)
    g = len(all_values)
    _GRID.inc(g * num_buckets)
    acc = _identity_grids(g, num_buckets, want)
    touched = 0
    for values, lo, p in parts:
        width = p["count"].shape[1]
        touched += len(values) * width
        rows = None if len(values) == g else np.searchsorted(all_values,
                                                             values)
        _fold_part(acc, rows, slice(lo, lo + width), p)
    _TOUCHED.inc(touched)
    trace_add("scan_combine_touched_cells", touched)
    trace_add("scan_combine_grid_cells", g * num_buckets)
    out = _finalize_in_place(acc, requested, want)
    _MATERIALIZED.inc(g * num_buckets * len(out))
    trace_add("scan_combine_materialized_cells",
              g * num_buckets * len(out))
    return all_values, out


def combine_parts(parts: list, num_buckets: int, which: tuple = ALL_AGGS,
                  mode: str = "sparse") -> tuple[np.ndarray, dict]:
    """Mode-dispatched combine — the one entry point the reader uses."""
    ensure(mode in COMBINE_MODES,
           f"unknown [scan.combine] mode {mode!r}; expected one of "
           f"{COMBINE_MODES}")
    if mode == "dense":
        return combine_aggregate_parts(parts, num_buckets, which=which)
    return sparse_combine_parts(parts, num_buckets, which=which)


# ---- top-k pushdown --------------------------------------------------------


def _group_membership(parts: list, all_values: np.ndarray
                      ) -> tuple[list[int], list[list]]:
    """Part membership split by shape: full-group parts (every union
    group belongs, local row == union row — the headline scan's common
    shape) as ONE index list, per-group entry lists only for subset
    parts.  Bookkeeping is O(parts + subset cells); expanding full
    parts per group would make it O(groups x parts) — scaling with the
    very cardinality the pushdown exists to bound."""
    g = len(all_values)
    full: list[int] = []
    subset: list[list] = [[] for _ in range(g)]
    for pi, (values, _lo, _p) in enumerate(parts):
        if len(values) == g:
            full.append(pi)
        else:
            for r_local, r in enumerate(
                    np.searchsorted(all_values, values)):
                subset[r].append((pi, int(r_local)))
    return full, subset


def _merged_entries(full: list[int], sub: list, r: int):
    """(part_idx, local_row) pairs for group r in ascending part index
    order — the fold/tie-break order — merged from the full-part
    indices and the group's subset entries."""
    i = j = 0
    while i < len(full) or j < len(sub):
        if j >= len(sub) or (i < len(full) and full[i] < sub[j][0]):
            yield full[i], r
            i += 1
        else:
            yield sub[j]
            j += 1


def _fold_group_span(parts: list, entries,
                     span_lo: int, span_w: int, bufs: dict) -> None:
    """Fold ONE group's runs into span-sized f64 buffers (identity
    -refilled views of reusable full-width scratch), same arithmetic
    and part order as the grid folds (`entries` iterates (part_idx,
    local_row) in ascending part order).  Which aggregates fold is
    encoded by which buffers exist in `bufs`."""
    for name, buf in bufs.items():
        if name == "count" or name == "sum":
            buf[:span_w] = 0.0
        elif name == "min":
            buf[:span_w] = np.inf
        elif name == "max":
            buf[:span_w] = -np.inf
        elif name == "last":
            buf[:span_w] = 0.0
        elif name == "last_ts":
            buf[:span_w] = _I64_MIN
    for pi, r in entries:
        _values, lo, p = parts[pi]
        width = p["count"].shape[1]
        sl = slice(lo - span_lo, lo - span_lo + width)
        bufs["count"][sl] += p["count"][r]
        if "sum" in bufs:
            bufs["sum"][sl] += p["sum"][r]
        if "min" in bufs:
            mv = bufs["min"][sl]
            np.minimum(mv, p["min"][r], out=mv)
        if "max" in bufs:
            xv = bufs["max"][sl]
            np.maximum(xv, p["max"][r], out=xv)
        if "last" in bufs:
            lt_view = bufs["last_ts"][sl]
            newer = p["last_ts"][r].astype(np.int64) >= lt_view
            take = newer & (p["count"][r] > 0)
            np.copyto(bufs["last"][sl], p["last"][r], where=take,
                      casting="same_kind")
            np.copyto(lt_view, p["last_ts"][r].astype(np.int64),
                      where=take)


def _score_deps(by: str) -> set:
    """Buffers a ranking agg needs beyond count."""
    if by == "avg":
        return {"sum"}
    if by == "last":
        return {"last"}  # carries last_ts
    if by == "count":
        return set()
    return {by}


def _full_span(parts: list, full: list[int]) -> Optional[tuple[int, int]]:
    """[lo, hi) bucket span of the full-group parts, computed once —
    every group shares it."""
    if not full:
        return None
    lo = min(parts[pi][1] for pi in full)
    hi = max(parts[pi][1] + parts[pi][2]["count"].shape[1]
             for pi in full)
    return lo, hi


def _group_span(parts: list, fspan: Optional[tuple[int, int]],
                sub: list) -> tuple[int, int]:
    los = [parts[pi][1] for pi, _r in sub]
    his = [parts[pi][1] + parts[pi][2]["count"].shape[1]
           for pi, _r in sub]
    if fspan is not None:
        los.append(fspan[0])
        his.append(fspan[1])
    lo = min(los)
    return lo, max(his) - lo


def rank_top_k(kept_rows: list, scores, tk) -> list:
    """THE top-k ranking: stable argsort over the kept groups' scores
    in ascending group-row order (post-drop sorted order — the dense
    path's tie-break), best first, sliced to k.  Shared by
    combine_top_k and the mesh's device-scored path (read.py
    _aggregate_topk_mesh) so the two selections cannot drift."""
    score_arr = np.asarray(scores, dtype=np.float64)
    if tk.largest:
        order = np.argsort(-score_arr, kind="stable")
    else:
        order = np.argsort(score_arr, kind="stable")
    return [kept_rows[i] for i in order[:tk.k]]


def _score_buf(bufs: dict, by: str, span_w: int,
               count: np.ndarray) -> np.ndarray:
    """Per-cell ranking values over a group's span, matching the dense
    path's finalized grid cell for cell (only count>0 cells are ever
    read by the score, so avg can divide plainly)."""
    if by == "count":
        return count
    if by == "avg":
        with np.errstate(invalid="ignore", divide="ignore"):
            return bufs["sum"][:span_w] / np.maximum(count, 1)
    return bufs[by][:span_w]


def combine_top_k(parts: list, num_buckets: int, which: tuple,
                  tk) -> tuple[np.ndarray, dict]:
    """Top-k pushdown combine: scores fold per group into a SPAN-sized
    transient, and only the k winners' rows are ever materialized —
    peak output is O(k x buckets x aggs) independent of group
    cardinality.  Bit-identical to dense combine + empty-group drop +
    plan.apply_top_k: same f64 fold order, same score formula
    (best count>0 cell of the ranking grid), same stable tie-break on
    the post-drop sorted group order, rows returned best first."""
    requested = set(which) | {"count"}
    want = expand_which(requested)
    ensure(tk.by in requested or tk.by == "count",
           f"top-k by {tk.by!r} needs that aggregate in the spec's "
           f"`which`; have {sorted(requested)}")
    if not parts:
        return _empty_result(num_buckets, which)
    all_values = _union_values(parts)
    g = len(all_values)
    _GRID.inc(g * num_buckets)
    full, subset = _group_membership(parts, all_values)
    fspan = _full_span(parts, full)
    touched = sum(len(v) * p["count"].shape[1] for v, _lo, p in parts)
    _TOUCHED.inc(touched)
    trace_add("scan_combine_touched_cells", touched)
    trace_add("scan_combine_grid_cells", g * num_buckets)

    # score pass: one reusable full-width scratch per needed buffer
    deps = _score_deps(tk.by)
    score_names = {"count"} | deps | ({"last_ts"} if "last" in deps
                                     else set())
    scratch = {name: np.empty(num_buckets,
                              dtype=np.int64 if name == "last_ts"
                              else np.float64)
               for name in score_names}
    kept_rows: list[int] = []
    scores: list[float] = []
    for r in range(g):
        if not full and not subset[r]:
            continue
        span_lo, span_w = _group_span(parts, fspan, subset[r])
        _fold_group_span(parts, _merged_entries(full, subset[r], r),
                         span_lo, span_w, scratch)
        count = scratch["count"][:span_w]
        has = count > 0
        if not has.any():
            continue  # all-empty group: dropped before ranking,
            # exactly like finalize_aggregate's empty-group cut
        by_vals = _score_buf(scratch, tk.by, span_w, count)
        if tk.largest:
            s = float(np.max(np.where(has, by_vals, -np.inf)))
        else:
            s = float(np.min(np.where(has, by_vals, np.inf)))
        kept_rows.append(r)
        scores.append(s)
    winners = rank_top_k(kept_rows, scores, tk)

    # materialize ONLY the winners, best first.  An all-empty-group
    # result still goes through the identity/finalize pair so dtypes
    # match the dense path's dropped-to-zero-rows grids exactly.
    k_out = len(winners)
    acc = _identity_grids(k_out, num_buckets, want)
    for out_row, r in enumerate(winners):
        for pi, r_local in _merged_entries(full, subset[r], r):
            _values, lo, p = parts[pi]
            row_part = {name: grid[r_local:r_local + 1]
                        for name, grid in p.items()}
            row_acc = {name: grid[out_row:out_row + 1]
                       for name, grid in acc.items()}
            _fold_part(row_acc, None,
                       slice(lo, lo + row_part["count"].shape[1]),
                       row_part)
    out = _finalize_in_place(acc, requested, want)
    _MATERIALIZED.inc(k_out * num_buckets * len(out))
    trace_add("scan_combine_materialized_cells",
              k_out * num_buckets * len(out))
    return all_values[winners], out


# ---- delta summation: the per-segment partial memo -------------------------


class PartsMemo:
    """Byte-bounded per-segment aggregate-partial memo (the delta
    -summation tier).

    Key: the segment's scan-cache identity (segment start + exact SST
    id set + columns + pushdown) plus the RANGE-INDEPENDENT aggregate
    fingerprint (group/ts/value columns, bucket width, bucket PHASE =
    range_start % bucket_ms, requested aggs, canonical predicate).  Any
    write, flush, or compaction changes the SST set and misses
    structurally — the same discipline as the scan cache, no explicit
    invalidation (docs/robustness.md lists the failure domain).

    Value: the segment's combined parts in the recording query's grid
    coordinates, plus that grid's (range_start, num_buckets).  A later
    query with the same phase REBASES: shift each part's bucket_lo by
    the whole-bucket range delta, clip to the new grid, and re-relative
    last_ts — pure slicing, so served parts are bit-identical to a
    recompute.  Serving requires the segment's overlap with the NEW
    grid to lie inside the RECORDED grid (a widened range reaches
    buckets the stored parts were clipped away from and must
    recompute); narrowing/refining a dashboard range — the common
    zoom/pan shape — always qualifies.

    Event-loop owned, like the scan cache: probe/store only run between
    awaits on the reader's aggregate path."""

    def __init__(self, max_bytes: int):
        self.lru = ByteLRU(max_bytes, hits=_MEMO_HITS,
                           misses=_MEMO_MISSES, trace_tier="parts_memo")

    @property
    def enabled(self) -> bool:
        return self.lru.max_bytes > 0

    @staticmethod
    def key(seg_key: tuple, spec, pred_key: str) -> tuple:
        phase = spec.range_start % spec.bucket_ms
        return (seg_key, spec.group_col, spec.ts_col, spec.value_col,
                spec.bucket_ms, phase, spec.which, pred_key)

    def probe(self, seg_key: tuple, seg_start: int, segment_ms: int,
              spec, pred_key: str) -> Optional[list]:
        """Rebased parts for one segment, or None (miss / uncovered)."""
        if not self.enabled:
            return None
        key = self.key(seg_key, spec, pred_key)
        # peek first: an entry that fails the coverage check below must
        # NOT count as a hit (hits back refine_memo_fraction and the
        # operator's serve-rate story), so hit/miss is recorded only
        # after coverage is known
        entry = self.lru.peek_entry(key)
        if entry is None:
            self.lru.record_miss()
            return None
        old_start = entry["range_start"]
        old_nb = entry["num_buckets"]
        b = spec.bucket_ms
        # same phase (it's in the key), so the range delta is whole
        # buckets and rebasing is exact integer arithmetic
        shift = (old_start - spec.range_start) // b
        b_lo = (seg_start - old_start) // b
        b_hi = (seg_start + segment_ms - 1 - old_start) // b
        lo_i = max(b_lo, -shift)
        hi_i = min(b_hi, -shift + spec.num_buckets - 1)
        if lo_i <= hi_i and (lo_i < 0 or hi_i > old_nb - 1):
            # the new grid reaches buckets outside the recorded grid:
            # stored parts were clipped there — recompute
            _MEMO_UNCOVERED.inc()
            self.lru.record_miss()
            return None
        self.lru.record_hit(key)
        out = []
        delta = old_start - spec.range_start
        for values, lo, p in entry["parts"]:
            nl = lo + shift
            cut = max(0, -nl)
            width = p["count"].shape[1]
            w_eff = min(width - cut, spec.num_buckets - (nl + cut))
            if w_eff <= 0:
                continue
            sl = slice(cut, cut + w_eff)
            grids = {k: v[:, sl] for k, v in p.items() if k != "last_ts"}
            if "last_ts" in p:
                lt = p["last_ts"][:, sl]
                # stored relative to the recording range; re-relative
                # where there is data, keep the sentinel elsewhere
                grids["last_ts"] = np.where(grids["count"] > 0,
                                            lt + delta, lt)
            out.append((values, nl + cut, grids))
        _MEMO_PARTS.inc(len(out))
        trace_add("scan_combine_memo_parts", len(out))
        return out

    def store(self, seg_key: tuple, spec, pred_key: str,
              parts: list) -> None:
        """Record one segment's COMPLETE parts (aggregate_segments
        yields a segment only once all its windows folded).  Parts are
        deep-copied: the originals are often views into per-window
        memo grids, and storing views would pin their full-span bases
        while the byte accounting only saw the slice."""
        if not self.enabled:
            return
        copied = []
        nbytes = 0
        for values, lo, p in parts:
            # .copy(), NOT ascontiguousarray: a contiguous slice of a
            # per-round/per-window grid stack is returned AS-IS by
            # ascontiguousarray, which would pin the whole base alive
            # while nbytes counted only the slice
            grids = {k: v.copy() for k, v in p.items()}
            values = values.copy()
            nbytes += values.nbytes + sum(v.nbytes
                                          for v in grids.values())
            copied.append((values, lo, grids))
        entry = {"range_start": spec.range_start,
                 "num_buckets": spec.num_buckets, "parts": copied}
        self.lru.put(self.key(seg_key, spec, pred_key), entry,
                     nbytes + 256)

    def clear(self) -> None:
        self.lru.clear()

    def stats(self) -> dict:
        return {"entries": len(self.lru), "bytes": self.lru.total_bytes,
                "max_bytes": self.lru.max_bytes, "hits": self.lru.hits,
                "misses": self.lru.misses}


# ---- cross-region downsample merge (cluster tier) --------------------------


def merge_downsample_results(results: list[dict], num_buckets: int,
                             which: Optional[tuple] = None) -> dict:
    """Merge per-region downsample grids by tsid (the cluster's strict
    and degraded gather paths).  Regions are series-disjoint in steady
    state; during a split's TTL window an overlapping tsid combines
    additively (sum/count/min/max; avg recomputed; `last` takes the
    later sample time).  Allocates only the requested aggs and their
    dependencies — a subset query no longer pays six full grids.

    `which=None` infers the aggregate set from the grids the regions
    actually returned, so the merge follows whatever the fan-out
    requested without a second plumbing path.  When avg must be
    recombined across an overlapping tsid but a region omitted `sum`,
    its sum contribution is reconstructed as avg*count (exact division
    inverse up to one f64 rounding; regions only overlap during a
    split's TTL window)."""
    results = [r for r in results if r["tsids"]]
    if not results:
        return {"tsids": [], "num_buckets": num_buckets, "aggs": {}}
    if which is None:
        which = tuple(sorted({k for r in results for k in r["aggs"]
                              if k in ALL_AGGS}))
    requested = set(which) | {"count"}
    want = expand_which(requested)

    all_tsids = sorted({t for r in results for t in r["tsids"]})
    idx = {t: i for i, t in enumerate(all_tsids)}
    g = len(all_tsids)
    agg: dict = {"count": np.zeros((g, num_buckets))}
    if "sum" in want:
        agg["sum"] = np.zeros((g, num_buckets))
    if "min" in want:
        agg["min"] = np.full((g, num_buckets), np.inf)
    if "max" in want:
        agg["max"] = np.full((g, num_buckets), -np.inf)
    if "last" in want:
        agg["last"] = np.full((g, num_buckets), np.nan)
        agg["last_ts"] = np.full((g, num_buckets), -np.inf)
    for r in results:
        rows = np.asarray([idx[t] for t in r["tsids"]])
        a = r["aggs"]
        counts = np.nan_to_num(np.asarray(a["count"]))
        agg["count"][rows] += counts
        if "sum" in agg:
            if "sum" in a:
                part_sum = np.nan_to_num(np.asarray(a["sum"]))
            else:  # avg-only region: invert the division
                part_sum = np.nan_to_num(np.asarray(a["avg"])) * counts
            agg["sum"][rows] += part_sum
        if "min" in agg and "min" in a:
            agg["min"][rows] = np.fmin(agg["min"][rows],
                                       np.asarray(a["min"]))
        if "max" in agg and "max" in a:
            agg["max"][rows] = np.fmax(agg["max"][rows],
                                       np.asarray(a["max"]))
        if "last" in agg and "last" in a:
            has = counts > 0
            # winner by actual sample time (regions expose last_ts);
            # ties break toward the later region in route order
            cand_ts = np.nan_to_num(
                np.asarray(a["last_ts"], dtype=np.float64), nan=-np.inf)
            take = has & (cand_ts >= agg["last_ts"][rows])
            last_rows = agg["last"][rows]
            last_rows[take] = np.asarray(a["last"])[take]
            agg["last"][rows] = last_rows
            lt_rows = agg["last_ts"][rows]
            lt_rows[take] = cand_ts[take]
            agg["last_ts"][rows] = lt_rows
    empty = agg["count"] == 0
    if "avg" in requested and "sum" in agg:
        with np.errstate(invalid="ignore"):
            agg["avg"] = np.where(empty, np.nan,
                                  agg["sum"] / np.maximum(agg["count"],
                                                          1))
    if "min" in agg:
        agg["min"] = np.where(empty, np.inf, agg["min"])
    if "max" in agg:
        agg["max"] = np.where(empty, -np.inf, agg["max"])
    if "sum" in agg and "sum" not in requested:
        del agg["sum"]  # avg's dependency only — not requested
    return {"tsids": all_tsids, "num_buckets": num_buckets, "aggs": agg}
