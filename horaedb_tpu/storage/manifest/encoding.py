"""Manifest wire formats (ref: src/storage/src/manifest/encoding.rs).

Two formats, kept byte-compatible with the reference since they are a
compatibility surface and a bench target (SURVEY.md section 2.1):

- Delta files: proto3 `ManifestUpdate` (sst.proto:24-47) — encoded with
  our minimal prost-compatible wire codec.
- Snapshot: custom little-endian binary — 14-byte header
  `{magic u32 = 0xCAFE_1234, version u8, flag u8, length u64}`
  (encoding.rs:90-153) followed by fixed 32-byte records
  `{id u64, time_range 2x i64, size u32, num_rows u32}` (encoding.rs:161-238).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np

from horaedb_tpu import native
from horaedb_tpu.common import protowire as pw
from horaedb_tpu.common.error import ensure
from horaedb_tpu.storage.sst import FileId, FileMeta, SstFile
from horaedb_tpu.storage.types import TimeRange

# ---------------------------------------------------------------------------
# Delta: proto3 ManifestUpdate
# ---------------------------------------------------------------------------


@dataclass
class ManifestUpdate:
    """One delta-log entry (ref: encoding.rs:31-76)."""

    to_adds: list[SstFile] = field(default_factory=list)
    to_deletes: list[FileId] = field(default_factory=list)


def _encode_time_range(tr: TimeRange) -> bytes:
    out = bytearray()
    pw.encode_i64_field(1, int(tr.start), out)
    pw.encode_i64_field(2, int(tr.end), out)
    return bytes(out)


def _decode_time_range(buf: bytes) -> TimeRange:
    start = end = 0
    pos = 0
    while pos < len(buf):
        fnum, wtype, pos = pw.decode_tag(buf, pos)
        if fnum == 1 and wtype == pw.WIRE_VARINT:
            v, pos = pw.decode_varint(buf, pos)
            start = pw.decode_i64(v)
        elif fnum == 2 and wtype == pw.WIRE_VARINT:
            v, pos = pw.decode_varint(buf, pos)
            end = pw.decode_i64(v)
        else:
            pos = pw.skip_field(buf, pos, wtype)
    return TimeRange.new(start, end)


def _encode_sst_meta(meta: FileMeta) -> bytes:
    out = bytearray()
    pw.encode_u64_field(1, meta.max_sequence, out)
    pw.encode_u64_field(2, meta.num_rows, out)
    pw.encode_u64_field(3, meta.size, out)
    # prost models time_range as Some(msg) and always emits the field, even
    # zero-length for a default value — match that for byte compatibility.
    pw.encode_len_field(4, _encode_time_range(meta.time_range), out)
    return bytes(out)


def _decode_sst_meta(buf: bytes) -> FileMeta:
    max_sequence = num_rows = size = 0
    time_range = TimeRange.new(0, 0)
    pos = 0
    while pos < len(buf):
        fnum, wtype, pos = pw.decode_tag(buf, pos)
        if fnum == 1 and wtype == pw.WIRE_VARINT:
            max_sequence, pos = pw.decode_varint(buf, pos)
        elif fnum == 2 and wtype == pw.WIRE_VARINT:
            num_rows, pos = pw.decode_varint(buf, pos)
        elif fnum == 3 and wtype == pw.WIRE_VARINT:
            size, pos = pw.decode_varint(buf, pos)
        elif fnum == 4 and wtype == pw.WIRE_LEN:
            payload, pos = pw.read_len_payload(buf, pos)
            time_range = _decode_time_range(payload)
        else:
            pos = pw.skip_field(buf, pos, wtype)
    return FileMeta(max_sequence=max_sequence, num_rows=num_rows, size=size,
                    time_range=time_range)


def _encode_sst_file(f: SstFile) -> bytes:
    out = bytearray()
    pw.encode_u64_field(1, f.id, out)
    pw.encode_len_field(2, _encode_sst_meta(f.meta), out)
    return bytes(out)


def _decode_sst_file(buf: bytes) -> SstFile:
    file_id = 0
    meta: FileMeta | None = None
    pos = 0
    while pos < len(buf):
        fnum, wtype, pos = pw.decode_tag(buf, pos)
        if fnum == 1 and wtype == pw.WIRE_VARINT:
            file_id, pos = pw.decode_varint(buf, pos)
        elif fnum == 2 and wtype == pw.WIRE_LEN:
            payload, pos = pw.read_len_payload(buf, pos)
            meta = _decode_sst_meta(payload)
        else:
            pos = pw.skip_field(buf, pos, wtype)
    ensure(meta is not None, "file meta is missing")
    return SstFile(file_id, meta)


def encode_manifest_update(update: ManifestUpdate) -> bytes:
    out = bytearray()
    for f in update.to_adds:
        pw.encode_len_field(1, _encode_sst_file(f), out)
    pw.encode_packed_u64_field(2, update.to_deletes, out)
    return bytes(out)


def decode_manifest_update(buf: bytes) -> ManifestUpdate:
    update = ManifestUpdate()
    pos = 0
    while pos < len(buf):
        fnum, wtype, pos = pw.decode_tag(buf, pos)
        if fnum == 1 and wtype == pw.WIRE_LEN:
            payload, pos = pw.read_len_payload(buf, pos)
            update.to_adds.append(_decode_sst_file(payload))
        elif fnum == 2 and wtype == pw.WIRE_LEN:  # packed
            payload, pos = pw.read_len_payload(buf, pos)
            p = 0
            while p < len(payload):
                v, p = pw.decode_varint(payload, p)
                update.to_deletes.append(v)
        elif fnum == 2 and wtype == pw.WIRE_VARINT:  # unpacked fallback
            v, pos = pw.decode_varint(buf, pos)
            update.to_deletes.append(v)
        else:
            pos = pw.skip_field(buf, pos, wtype)
    return update


# ---------------------------------------------------------------------------
# Snapshot: custom binary
# ---------------------------------------------------------------------------

_HEADER_STRUCT = struct.Struct("<IBBQ")
_RECORD_STRUCT = struct.Struct("<QqqII")

# wire constants are single-sourced in horaedb_tpu.native
SNAPSHOT_MAGIC = native.SNAPSHOT_MAGIC
SNAPSHOT_VERSION = native.SNAPSHOT_VERSION
HEADER_LENGTH = _HEADER_STRUCT.size  # 14
RECORD_LENGTH = _RECORD_STRUCT.size  # 32
assert RECORD_LENGTH == native.RECORD_DTYPE.itemsize


@dataclass
class SnapshotHeader:
    """14-byte snapshot header (ref: encoding.rs:90-153).

    Spec twin: SnapshotHeader/SnapshotRecord are the independent Python
    statement of the wire format, used by tests to cross-check the native
    codec; production encode/decode goes through horaedb_tpu.native."""

    magic: int = SNAPSHOT_MAGIC
    version: int = SNAPSHOT_VERSION
    flag: int = 0
    length: int = 0

    def to_bytes(self) -> bytes:
        return _HEADER_STRUCT.pack(self.magic, self.version, self.flag, self.length)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "SnapshotHeader":
        ensure(len(buf) >= HEADER_LENGTH, "snapshot header truncated")
        magic, version, flag, length = _HEADER_STRUCT.unpack_from(buf)
        ensure(magic == SNAPSHOT_MAGIC, "invalid bytes to convert to header")
        return cls(magic=magic, version=version, flag=flag, length=length)


@dataclass(frozen=True)
class SnapshotRecord:
    """Fixed 32-byte record (ref: encoding.rs:161-238)."""

    id: int
    time_range: TimeRange
    size: int
    num_rows: int

    def to_bytes(self) -> bytes:
        return _RECORD_STRUCT.pack(
            self.id, int(self.time_range.start), int(self.time_range.end),
            self.size, self.num_rows,
        )

    @classmethod
    def from_bytes(cls, buf: bytes, offset: int = 0) -> "SnapshotRecord":
        fid, start, end, size, num_rows = _RECORD_STRUCT.unpack_from(buf, offset)
        return cls(id=fid, time_range=TimeRange.new(start, end),
                   size=size, num_rows=num_rows)


class Snapshot:
    """Full SST listing: header + record array (ref: encoding.rs:283-344).

    Array-backed: records live in a numpy structured array whose memory
    layout IS the wire layout, so encode/decode are a header plus one
    memcpy (through the C++ codec in native/ when built, numpy otherwise)
    instead of per-record Python packing — this codec is the reference's
    own benchmark target (src/benchmarks/benches/bench.rs).
    """

    def __init__(self, records: "np.ndarray | None" = None):
        self.records = (records if records is not None
                        else np.empty(0, dtype=native.RECORD_DTYPE))

    @classmethod
    def from_bytes(cls, buf: bytes) -> "Snapshot":
        return cls(native.snapshot_decode(buf))

    def into_bytes(self) -> bytes:
        return native.snapshot_encode(self.records)

    def __len__(self) -> int:
        return len(self.records)

    @property
    def ids(self) -> list[int]:
        return self.records["id"].tolist()

    def add_records(self, files: list[SstFile]) -> None:
        """Add files, replacing any record with the same id.

        Replacement (not append) keeps the delta fold idempotent: a crash
        between snapshot-put and delta-deletion replays deltas on the next
        merge, and replayed adds must not duplicate records.
        """
        if not files:
            return
        incoming = np.array(
            [(f.id, int(f.meta.time_range.start), int(f.meta.time_range.end),
              f.meta.size, f.meta.num_rows) for f in files],
            dtype=native.RECORD_DTYPE)
        keep = ~np.isin(self.records["id"], incoming["id"])
        self.records = np.concatenate([self.records[keep], incoming])

    def delete_records(self, to_deletes: list[FileId]) -> None:
        """Delete by id; ids already absent are ignored (replay tolerance —
        the reference only debug-asserts here, encoding.rs:313-321)."""
        if not to_deletes:
            return
        dels = np.asarray(to_deletes, dtype=np.uint64)
        self.records = self.records[~np.isin(self.records["id"], dels)]

    def into_ssts(self) -> list[SstFile]:
        # max_sequence == file id by construction (ref: encoding.rs:243-252)
        return [
            SstFile(int(r["id"]), FileMeta(
                max_sequence=int(r["id"]), num_rows=int(r["num_rows"]),
                size=int(r["size"]),
                time_range=TimeRange.new(int(r["start"]), int(r["end"]))))
            for r in self.records
        ]
