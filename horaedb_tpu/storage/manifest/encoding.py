"""Manifest wire formats (ref: src/storage/src/manifest/encoding.rs).

Two formats, kept byte-compatible with the reference since they are a
compatibility surface and a bench target (SURVEY.md section 2.1):

- Delta files: proto3 `ManifestUpdate` (sst.proto:24-47) — encoded with
  our minimal prost-compatible wire codec.
- Snapshot: custom little-endian binary — 14-byte header
  `{magic u32 = 0xCAFE_1234, version u8, flag u8, length u64}`
  (encoding.rs:90-153) followed by fixed 32-byte records
  `{id u64, time_range 2x i64, size u32, num_rows u32}` (encoding.rs:161-238).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from horaedb_tpu.common import protowire as pw
from horaedb_tpu.common.error import ensure
from horaedb_tpu.storage.sst import FileId, FileMeta, SstFile
from horaedb_tpu.storage.types import TimeRange

# ---------------------------------------------------------------------------
# Delta: proto3 ManifestUpdate
# ---------------------------------------------------------------------------


@dataclass
class ManifestUpdate:
    """One delta-log entry (ref: encoding.rs:31-76)."""

    to_adds: list[SstFile] = field(default_factory=list)
    to_deletes: list[FileId] = field(default_factory=list)


def _encode_time_range(tr: TimeRange) -> bytes:
    out = bytearray()
    pw.encode_i64_field(1, int(tr.start), out)
    pw.encode_i64_field(2, int(tr.end), out)
    return bytes(out)


def _decode_time_range(buf: bytes) -> TimeRange:
    start = end = 0
    pos = 0
    while pos < len(buf):
        fnum, wtype, pos = pw.decode_tag(buf, pos)
        if fnum == 1 and wtype == pw.WIRE_VARINT:
            v, pos = pw.decode_varint(buf, pos)
            start = pw.decode_i64(v)
        elif fnum == 2 and wtype == pw.WIRE_VARINT:
            v, pos = pw.decode_varint(buf, pos)
            end = pw.decode_i64(v)
        else:
            pos = pw.skip_field(buf, pos, wtype)
    return TimeRange.new(start, end)


def _encode_sst_meta(meta: FileMeta) -> bytes:
    out = bytearray()
    pw.encode_u64_field(1, meta.max_sequence, out)
    pw.encode_u64_field(2, meta.num_rows, out)
    pw.encode_u64_field(3, meta.size, out)
    # prost models time_range as Some(msg) and always emits the field, even
    # zero-length for a default value — match that for byte compatibility.
    pw.encode_len_field(4, _encode_time_range(meta.time_range), out)
    return bytes(out)


def _decode_sst_meta(buf: bytes) -> FileMeta:
    max_sequence = num_rows = size = 0
    time_range = TimeRange.new(0, 0)
    pos = 0
    while pos < len(buf):
        fnum, wtype, pos = pw.decode_tag(buf, pos)
        if fnum == 1 and wtype == pw.WIRE_VARINT:
            max_sequence, pos = pw.decode_varint(buf, pos)
        elif fnum == 2 and wtype == pw.WIRE_VARINT:
            num_rows, pos = pw.decode_varint(buf, pos)
        elif fnum == 3 and wtype == pw.WIRE_VARINT:
            size, pos = pw.decode_varint(buf, pos)
        elif fnum == 4 and wtype == pw.WIRE_LEN:
            payload, pos = pw.read_len_payload(buf, pos)
            time_range = _decode_time_range(payload)
        else:
            pos = pw.skip_field(buf, pos, wtype)
    return FileMeta(max_sequence=max_sequence, num_rows=num_rows, size=size,
                    time_range=time_range)


def _encode_sst_file(f: SstFile) -> bytes:
    out = bytearray()
    pw.encode_u64_field(1, f.id, out)
    pw.encode_len_field(2, _encode_sst_meta(f.meta), out)
    return bytes(out)


def _decode_sst_file(buf: bytes) -> SstFile:
    file_id = 0
    meta: FileMeta | None = None
    pos = 0
    while pos < len(buf):
        fnum, wtype, pos = pw.decode_tag(buf, pos)
        if fnum == 1 and wtype == pw.WIRE_VARINT:
            file_id, pos = pw.decode_varint(buf, pos)
        elif fnum == 2 and wtype == pw.WIRE_LEN:
            payload, pos = pw.read_len_payload(buf, pos)
            meta = _decode_sst_meta(payload)
        else:
            pos = pw.skip_field(buf, pos, wtype)
    ensure(meta is not None, "file meta is missing")
    return SstFile(file_id, meta)


def encode_manifest_update(update: ManifestUpdate) -> bytes:
    out = bytearray()
    for f in update.to_adds:
        pw.encode_len_field(1, _encode_sst_file(f), out)
    pw.encode_packed_u64_field(2, update.to_deletes, out)
    return bytes(out)


def decode_manifest_update(buf: bytes) -> ManifestUpdate:
    update = ManifestUpdate()
    pos = 0
    while pos < len(buf):
        fnum, wtype, pos = pw.decode_tag(buf, pos)
        if fnum == 1 and wtype == pw.WIRE_LEN:
            payload, pos = pw.read_len_payload(buf, pos)
            update.to_adds.append(_decode_sst_file(payload))
        elif fnum == 2 and wtype == pw.WIRE_LEN:  # packed
            payload, pos = pw.read_len_payload(buf, pos)
            p = 0
            while p < len(payload):
                v, p = pw.decode_varint(payload, p)
                update.to_deletes.append(v)
        elif fnum == 2 and wtype == pw.WIRE_VARINT:  # unpacked fallback
            v, pos = pw.decode_varint(buf, pos)
            update.to_deletes.append(v)
        else:
            pos = pw.skip_field(buf, pos, wtype)
    return update


# ---------------------------------------------------------------------------
# Snapshot: custom binary
# ---------------------------------------------------------------------------

_HEADER_STRUCT = struct.Struct("<IBBQ")
_RECORD_STRUCT = struct.Struct("<QqqII")

SNAPSHOT_MAGIC = 0xCAFE_1234
SNAPSHOT_VERSION = 1
HEADER_LENGTH = _HEADER_STRUCT.size  # 14
RECORD_LENGTH = _RECORD_STRUCT.size  # 32


@dataclass
class SnapshotHeader:
    """14-byte snapshot header (ref: encoding.rs:90-153)."""

    magic: int = SNAPSHOT_MAGIC
    version: int = SNAPSHOT_VERSION
    flag: int = 0
    length: int = 0

    def to_bytes(self) -> bytes:
        return _HEADER_STRUCT.pack(self.magic, self.version, self.flag, self.length)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "SnapshotHeader":
        ensure(len(buf) >= HEADER_LENGTH, "snapshot header truncated")
        magic, version, flag, length = _HEADER_STRUCT.unpack_from(buf)
        ensure(magic == SNAPSHOT_MAGIC, "invalid bytes to convert to header")
        return cls(magic=magic, version=version, flag=flag, length=length)


@dataclass(frozen=True)
class SnapshotRecord:
    """Fixed 32-byte record (ref: encoding.rs:161-238)."""

    id: int
    time_range: TimeRange
    size: int
    num_rows: int

    def to_bytes(self) -> bytes:
        return _RECORD_STRUCT.pack(
            self.id, int(self.time_range.start), int(self.time_range.end),
            self.size, self.num_rows,
        )

    @classmethod
    def from_bytes(cls, buf: bytes, offset: int = 0) -> "SnapshotRecord":
        fid, start, end, size, num_rows = _RECORD_STRUCT.unpack_from(buf, offset)
        return cls(id=fid, time_range=TimeRange.new(start, end),
                   size=size, num_rows=num_rows)

    @classmethod
    def from_sst(cls, f: SstFile) -> "SnapshotRecord":
        return cls(id=f.id, time_range=f.meta.time_range,
                   size=f.meta.size, num_rows=f.meta.num_rows)

    def to_sst(self) -> SstFile:
        # max_sequence == file id by construction (ref: encoding.rs:243-252)
        return SstFile(self.id, FileMeta(
            max_sequence=self.id, num_rows=self.num_rows, size=self.size,
            time_range=self.time_range,
        ))


class Snapshot:
    """Full SST listing: header + record array (ref: encoding.rs:283-344)."""

    def __init__(self, records: list[SnapshotRecord] | None = None):
        self.records: list[SnapshotRecord] = records or []

    @classmethod
    def from_bytes(cls, buf: bytes) -> "Snapshot":
        if not buf:
            return cls()
        header = SnapshotHeader.from_bytes(buf)
        body = buf[HEADER_LENGTH:]
        ensure(
            header.length == len(body) and header.length % RECORD_LENGTH == 0,
            f"snapshot length mismatch: header={header.length}, body={len(body)}",
        )
        records = [
            SnapshotRecord.from_bytes(body, off)
            for off in range(0, len(body), RECORD_LENGTH)
        ]
        return cls(records)

    def into_bytes(self) -> bytes:
        header = SnapshotHeader(length=len(self.records) * RECORD_LENGTH)
        out = bytearray(header.to_bytes())
        for r in self.records:
            out.extend(r.to_bytes())
        return bytes(out)

    def add_records(self, files: list[SstFile]) -> None:
        """Add files, replacing any record with the same id.

        Replacement (not append) keeps the delta fold idempotent: a crash
        between snapshot-put and delta-deletion replays deltas on the next
        merge, and replayed adds must not duplicate records.
        """
        if not files:
            return
        incoming = {f.id for f in files}
        self.records = [r for r in self.records if r.id not in incoming]
        self.records.extend(SnapshotRecord.from_sst(f) for f in files)

    def delete_records(self, to_deletes: list[FileId]) -> None:
        """Delete by id; ids already absent are ignored (replay tolerance —
        the reference only debug-asserts here, encoding.rs:313-321)."""
        if not to_deletes:
            return
        dels = set(to_deletes)
        self.records = [r for r in self.records if r.id not in dels]

    def into_ssts(self) -> list[SstFile]:
        return [r.to_sst() for r in self.records]
