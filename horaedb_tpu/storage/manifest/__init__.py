"""Manifest: SST metadata store with snapshot + delta log on object storage
(ref: src/storage/src/manifest/mod.rs).

Design (identical to the reference):
- Every update = one delta file put, THEN the in-memory cache mutation
  (crash between the two loses nothing: recovery folds deltas).
- A background merger folds deltas into the snapshot every
  `merge_interval` (or on signal) once more than `min_merge_threshold`
  deltas exist; crossing `soft_merge_threshold` nudges it, crossing
  `hard_merge_threshold` FAILS the write — that is the engine's write
  backpressure (ref: manifest/mod.rs:248-262).
- Startup recovery = read snapshot, fold ALL deltas, rewrite snapshot
  (`first_run`, ref: manifest/mod.rs:212-214, 274-333).
"""

from __future__ import annotations

import asyncio
import logging

from horaedb_tpu.common.error import Error
from horaedb_tpu.common.id_alloc import MonotonicIdAllocator
from horaedb_tpu.common.tasks import cancel_and_wait
from horaedb_tpu.objstore import NotFoundError, ObjectStore
from horaedb_tpu.storage.config import ManifestConfig
from horaedb_tpu.storage.manifest.encoding import (
    ManifestUpdate,
    Snapshot,
    decode_manifest_update,
    encode_manifest_update,
)
from horaedb_tpu.storage.sst import FileId, FileMeta, SstFile
from horaedb_tpu.storage.types import TimeRange
from horaedb_tpu.utils import span

logger = logging.getLogger(__name__)

PREFIX_PATH = "manifest"
SNAPSHOT_FILENAME = "snapshot"
DELTA_PREFIX = "delta"

_DELTA_IDS = MonotonicIdAllocator()


def _delta_order(path: str) -> int:
    """Numeric delta-file ordering (lexicographic order breaks when id
    digit counts differ)."""
    name = path.rsplit("/", 1)[-1]
    return int(name) if name.isdigit() else -1


async def _read_snapshot_bytes(store: ObjectStore, path: str) -> bytes:
    """A missing snapshot reads as empty bytes (the single home for the
    snapshot-missing rule)."""
    try:
        return await store.get(path)
    except NotFoundError:
        return b""


async def _read_snapshot(store: ObjectStore, path: str) -> Snapshot:
    return Snapshot.from_bytes(await _read_snapshot_bytes(store, path))


class _Merger:
    """Background delta→snapshot folder (ref: ManifestMerger, mod.rs:184-333)."""

    def __init__(self, snapshot_path: str, delta_dir: str, store: ObjectStore,
                 config: ManifestConfig, runtimes=None):
        self.snapshot_path = snapshot_path
        self.delta_dir = delta_dir
        self.store = store
        self.config = config
        self.runtimes = runtimes
        self.deltas_num = 0
        self._signal: asyncio.Queue[None] = asyncio.Queue(maxsize=config.channel_size)
        self._task: asyncio.Task | None = None
        # checked each loop turn: merge signals racing stop() can make
        # wait_for swallow the cancellation (bpo-37658)
        self._stopping = False
        # Serializes folds: the reference funnels every merge through one
        # consumer task; we allow trigger_merge() alongside the background
        # loop, so an explicit lock keeps a delta from being folded twice
        # concurrently.
        self._merge_lock = asyncio.Lock()

    def start(self) -> None:
        from horaedb_tpu.common.loops import loops

        self._stopping = False
        self._task = loops.spawn(
            self._merge_loop, name=f"manifest-merger:{self.snapshot_path}",
            kind="manifest-merger", owner="manifest",
            period_s=self.config.merge_interval.seconds,
            stall_threshold_s=120.0,
            backlog=lambda: {"deltas_num": self.deltas_num})

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            # merge signals race stop() exactly like compaction triggers
            # do — re-deliver the cancel past the wait_for swallow race
            # (see common/tasks.py)
            await cancel_and_wait(self._task)
            self._task = None

    async def _merge_loop(self, hb) -> None:
        interval = self.config.merge_interval.seconds
        logger.info("start manifest merge background job, interval=%ss", interval)
        while not self._stopping:
            try:
                await asyncio.wait_for(self._signal.get(), timeout=interval)
            except TimeoutError:
                pass
            except asyncio.TimeoutError:  # Python < 3.11 alias
                pass
            hb.beat()
            if self._stopping:
                return
            if self.deltas_num > self.config.min_merge_threshold:
                try:
                    await self.do_merge(first_run=False)
                    hb.ok()
                except Exception as exc:  # noqa: BLE001 — retried later
                    hb.error(exc)
                    logger.exception("failed to merge manifest deltas")

    def _schedule_merge(self) -> None:
        try:
            self._signal.put_nowait(None)
        except asyncio.QueueFull:
            logger.debug("merge signal channel full, merge already pending")

    def maybe_schedule_merge(self) -> None:
        """Backpressure gate run before every update (ref: mod.rs:248-262)."""
        current = self.deltas_num
        hard = self.config.hard_merge_threshold
        if current > hard:
            self._schedule_merge()
            raise Error(
                f"Manifest has too many delta files, value:{current}, hard_limit:{hard}"
            )
        if current > self.config.soft_merge_threshold:
            self._schedule_merge()

    async def do_merge(self, first_run: bool) -> None:
        async with self._merge_lock:
            await self._do_merge_locked(first_run)

    async def _do_merge_locked(self, first_run: bool) -> None:
        with span("manifest.merge", first_run=first_run):
            await self._do_merge_inner(first_run)

    async def _do_merge_inner(self, first_run: bool) -> None:
        metas = await self.store.list(self.delta_dir + "/")
        paths = [m.path for m in metas]
        if not paths:
            return
        if first_run:
            self.deltas_num = len(paths)

        delta_bufs = await asyncio.gather(*(self.store.get(p) for p in paths))
        snapshot_buf = await _read_snapshot_bytes(self.store,
                                                  self.snapshot_path)

        def fold() -> bytes:
            # pure CPU (protowire decode + snapshot codec) — runs on the
            # manifest pool (ref: manifest_compact_runtime,
            # storage.rs:91-104) so folds never block the event loop
            updates = [decode_manifest_update(buf) for buf in delta_bufs]
            snapshot = Snapshot.from_bytes(snapshot_buf)
            # Deltas are unsorted, so add all new files first, then
            # delete (ref: mod.rs:296-300).
            to_deletes: list[FileId] = []
            for update in updates:
                snapshot.add_records(update.to_adds)
                to_deletes.extend(update.to_deletes)
            snapshot.delete_records(to_deletes)
            return snapshot.into_bytes()

        if self.runtimes is not None:
            new_snapshot = await self.runtimes.run("manifest", fold)
        else:
            new_snapshot = await asyncio.to_thread(fold)

        # 1. Persist the snapshot, 2. then delete merged deltas — OLDEST
        # FIRST, stopping at the first failure so survivors always form
        # a SUFFIX of the folded batch.  Ids are never reused, so the
        # delta deleting file X always has a larger id than the delta
        # that added X; suffix survival therefore keeps every add with
        # its matching delete, and recovery's re-fold stays a no-op.  A
        # parallel best-effort delete could reap the delete-delta while
        # its add-delta survived — the re-fold would then RESURRECT a
        # manifest entry whose object is long gone (a permanent ghost
        # every scan trips over).
        await self.store.put(self.snapshot_path, new_snapshot)
        for path in sorted(paths, key=_delta_order):
            try:
                await self.store.delete(path)
            except NotFoundError:
                pass  # already reaped (e.g. by a prior partial pass)
            except Exception as e:  # noqa: BLE001 — next fold retries
                logger.error(
                    "failed to delete delta %s: %s (stopping; remaining "
                    "deltas re-fold on the next merge)", path, e)
                break
            self.deltas_num -= 1


class Manifest:
    """SST metadata store (ref: Manifest, mod.rs:67-176)."""

    def __init__(self, root_dir: str, store: ObjectStore,
                 config: ManifestConfig, runtimes=None):
        base = root_dir.rstrip("/")
        self.snapshot_path = f"{base}/{PREFIX_PATH}/{SNAPSHOT_FILENAME}"
        self.delta_dir = f"{base}/{PREFIX_PATH}/{DELTA_PREFIX}"
        self.store = store
        self._merger = _Merger(self.snapshot_path, self.delta_dir, store,
                               config, runtimes=runtimes)
        self._ssts: list[SstFile] = []
        self._cache_lock = asyncio.Lock()

    @classmethod
    async def open(cls, root_dir: str, store: ObjectStore,
                   config: ManifestConfig | None = None,
                   runtimes=None) -> "Manifest":
        m = cls(root_dir, store, config or ManifestConfig(),
                runtimes=runtimes)
        # Recovery: fold all deltas into the snapshot before serving.
        await m._merger.do_merge(first_run=True)
        snapshot = await _read_snapshot(store, m.snapshot_path)
        m._ssts = snapshot.into_ssts()
        logger.debug("loaded manifest snapshot at startup, ssts=%d", len(m._ssts))
        m._merger.start()
        return m

    async def close(self) -> None:
        await self._merger.stop()

    async def add_file(self, file_id: FileId, meta: FileMeta) -> None:
        await self.update(ManifestUpdate(to_adds=[SstFile(file_id, meta)]))

    async def update(self, update: ManifestUpdate) -> None:
        self._merger.maybe_schedule_merge()
        if self._merger.deltas_num > self._merger.config.soft_merge_threshold:
            # Soft backpressure: THROTTLE the writer (bounded) until the
            # background fold drains below the soft threshold.  With an
            # in-memory/local store no await in the write path truly
            # suspends, so a tight writer loop would otherwise starve
            # the merger until the hard limit failed every write (the
            # reference runs its merger on separate tokio threads; a
            # single asyncio loop needs an explicit pause).  The wait is
            # bounded so a wedged store degrades to the hard-limit error
            # instead of hanging writers.
            deadline = (asyncio.get_running_loop().time()
                        + self._merger.config.soft_merge_max_wait.seconds)
            while (self._merger.deltas_num
                   > self._merger.config.soft_merge_threshold
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.001)
        self._merger.deltas_num += 1
        try:
            await self._update_inner(update)
        except BaseException:
            self._merger.deltas_num -= 1
            raise

    async def _update_inner(self, update: ManifestUpdate) -> None:
        path = f"{self.delta_dir}/{_DELTA_IDS.allocate()}"
        # 1. Persist the delta, 2. then mutate the cache (ref: mod.rs:139-156).
        await self.store.put(path, encode_manifest_update(update))
        async with self._cache_lock:
            self._ssts.extend(update.to_adds)
            if update.to_deletes:
                dels = set(update.to_deletes)
                self._ssts = [f for f in self._ssts if f.id not in dels]

    async def all_ssts(self) -> list[SstFile]:
        async with self._cache_lock:
            return list(self._ssts)

    async def find_ssts(self, time_range: TimeRange) -> list[SstFile]:
        async with self._cache_lock:
            return [f for f in self._ssts if f.meta.time_range.overlaps(time_range)]

    # test/introspection hooks
    @property
    def deltas_num(self) -> int:
        return self._merger.deltas_num

    async def trigger_merge(self) -> None:
        """Force a synchronous fold (tests and shutdown)."""
        await self._merger.do_merge(first_run=False)
