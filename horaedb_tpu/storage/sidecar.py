"""Device-layout sidecars: the SST's columns persisted in the exact
fixed-width encoding the device scan consumes.

The cold scan path is structurally bound by Arrow/parquet decode plus
per-scan re-encode (dictionary np.unique, int64->int32 offset shifts,
f64->f32 casts) — the same bottleneck the reference acknowledges on its
CPU path (/root/reference/src/storage/src/read.rs:477-478 "TODO: fetch
using multiple threads").  Instead of adding decode threads, each SST
write/compaction also persists a sidecar object (`{id}.enc` next to
`{id}.sst`) holding the post-encode layout of ops/encode.py: dict codes
with their sorted dictionaries, epoch-relative int32 offsets, float32
values.  A cold scan then reconstructs device batches with
np.frombuffer — no decompression, no np.unique, no casts.

The sidecar is strictly a CACHE:
- parquet stays the durable/compatibility format; the manifest never
  references sidecars;
- the loader validates magic + version and falls back to the parquet
  path on ANY mismatch or absence — correctness never depends on it;
- SST objects are immutable and ids never reused, so a sidecar can
  never be stale; deletes ride along with SST deletes, best-effort.

Binary layout (version 1, little-endian):

    [8s magic "HDTPENC1"] [u32 header_len] [header JSON]
    [pad to 16] [section 0] [pad to 16] [section 1] ...

The header lists per-column metadata with section offsets relative to
the (aligned) data start.  String dictionaries are stored as an int32
offsets section plus a UTF-8 blob section; numeric dictionaries as raw
int64.
"""

from __future__ import annotations

import asyncio
import json
import struct
from dataclasses import dataclass
from typing import Optional

import numpy as np
import pyarrow as pa

from horaedb_tpu.common.error import Error
from horaedb_tpu.objstore import NotFoundError
from horaedb_tpu.ops import encode
from horaedb_tpu.storage.types import RESERVED_COLUMN_NAME

_MAGIC = b"HDTPENC1"
_VERSION = 1
_ALIGN = 16

# per-column block statistics granularity: each int32 column records
# min/max per block of this many rows, enabling the loader to fetch
# only candidate byte ranges for selective (point-query) leaf sets —
# the sidecar's analogue of parquet row-group pruning
BLOCK_ROWS = 65536

SIDECAR_SUFFIX = ".enc"

# arrow types the sidecar can carry (str(pa_type) -> type); anything
# else makes the whole file non-encodable (the writer skips it)
_ARROW_TYPES = {
    str(t): t for t in (
        pa.int8(), pa.int16(), pa.int32(), pa.int64(),
        pa.uint8(), pa.uint16(), pa.uint32(), pa.uint64(),
        pa.float32(), pa.float64(),
        pa.string(), pa.large_string(), pa.binary(),
    )
}

_NP_DTYPES = {"int32": np.int32, "float32": np.float32}


def sidecar_path(prefix: str, file_id: int) -> str:
    return f"{prefix}/data/{file_id}{SIDECAR_SUFFIX}"


# ---------------------------------------------------------------------------
# encode / serialize
# ---------------------------------------------------------------------------


def encode_columns(batch: pa.RecordBatch) -> Optional[dict]:
    """Encode every storable column of a PK-sorted stamped batch into
    the device layout: {name: (unpadded np array, ColumnEncoding)}.
    Returns None when any column can't be represented (unknown type,
    nulls) — except __reserved__, which is all-null by design and never
    read (build_plan drops it), so it is simply omitted."""
    out: dict = {}
    for name, col in zip(batch.schema.names, batch.columns):
        if name == RESERVED_COLUMN_NAME:
            continue
        if str(col.type) not in _ARROW_TYPES or col.null_count:
            return None
        try:
            arr, enc = encode.encode_column(col, name)
        except Exception:
            return None
        out[name] = (arr, enc)
    return out or None


# largest storable blob-dictionary payload: offsets are int32 on disk,
# so a dictionary whose concatenated bytes reach 2^31 cannot be
# represented — the writer must refuse (silent int32 cumsum wraparound
# would serve WRONG VALUES on read)
_DICT_BLOB_MAX = 2**31


def _dict_sections(dictionary: np.ndarray) -> Optional[tuple[dict, list]]:
    """(meta, sections) for one dictionary: numeric dicts as one raw
    int64 section, string/bytes dicts as int32 offsets + blob."""
    if dictionary.dtype == np.int64:
        return {"dict_kind": "i64", "dict_len": len(dictionary)}, \
            [dictionary.tobytes()]
    if dictionary.dtype == object:
        blobs = []
        for v in dictionary:
            if isinstance(v, bytes):
                blobs.append(v)
            elif isinstance(v, str):
                blobs.append(v.encode("utf-8"))
            else:
                return None
        lens = [len(b) for b in blobs]
        if sum(lens) >= _DICT_BLOB_MAX:
            # int32 offsets would wrap: not storable (caller falls back
            # to parquet-only for this SST)
            return None
        offsets = np.zeros(len(blobs) + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        offsets = offsets.astype(np.int32)
        return ({"dict_kind": "blob", "dict_len": len(dictionary)},
                [offsets.tobytes(), b"".join(blobs)])
    return None


def serialize(columns: dict, n_rows: int) -> Optional[bytes]:
    """Pack encoded columns into one sidecar blob, or None when a
    dictionary isn't storable."""
    sections: list[bytes] = []
    col_meta = []
    for name, (arr, enc) in columns.items():
        if len(arr) != n_rows or str(arr.dtype) not in _NP_DTYPES:
            return None
        meta = {"name": name, "kind": enc.kind, "dtype": str(arr.dtype),
                "arrow": str(enc.arrow_type), "epoch": int(enc.epoch),
                "section": len(sections)}
        sections.append(np.ascontiguousarray(arr).tobytes())
        if arr.dtype == np.int32 and n_rows:
            # per-block min/max over the ENCODED values (codes/offsets
            # are order-preserving, so leaf constants translate into
            # this space); one i32 section [mins..., maxes...].
            # reduceat handles the ragged tail block exactly, with no
            # padded copy of the column
            starts = np.arange(0, n_rows, BLOCK_ROWS)
            stats = np.concatenate([
                np.minimum.reduceat(arr, starts),
                np.maximum.reduceat(arr, starts)])
            meta["bstats_section"] = len(sections)
            sections.append(stats.astype(np.int32).tobytes())
        if enc.kind == "dict":
            ds = _dict_sections(enc.dictionary)
            if ds is None:
                return None
            dmeta, dsec = ds
            meta.update(dmeta)
            meta["dict_section"] = len(sections)
            sections.extend(dsec)
        col_meta.append(meta)

    offsets = []
    pos = 0
    for s in sections:
        pos = -(-pos // _ALIGN) * _ALIGN
        offsets.append(pos)
        pos += len(s)
    header = json.dumps({
        "version": _VERSION, "n_rows": n_rows,
        "sections": offsets, "columns": col_meta,
    }).encode("utf-8")

    parts = [_MAGIC, struct.pack("<I", len(header)), header]
    head_len = sum(len(p) for p in parts)
    parts.append(b"\0" * (-(-head_len // _ALIGN) * _ALIGN - head_len))
    pos = 0
    for off, s in zip(offsets, sections):
        parts.append(b"\0" * (off - pos))
        parts.append(s)
        pos = off + len(s)
    return b"".join(parts)


def build(batch: pa.RecordBatch) -> Optional[bytes]:
    """One-call write-side helper: encode + serialize, None when the
    batch isn't representable."""
    cols = encode_columns(batch)
    if cols is None:
        return None
    return serialize(cols, batch.num_rows)


# ---------------------------------------------------------------------------
# deserialize
# ---------------------------------------------------------------------------


def _parse_header(buf) -> Optional[tuple[dict, int]]:
    """(header, data_start) or None.  `buf` must contain at least the
    whole header (magic + length + JSON)."""
    try:
        if len(buf) < 12 or buf[:8] != _MAGIC:
            return None
        (header_len,) = struct.unpack_from("<I", buf, 8)
        if len(buf) < 12 + header_len:
            return None
        header = json.loads(bytes(buf[12:12 + header_len]).decode("utf-8"))
        if header.get("version") != _VERSION:
            return None
        data_start = -(-(12 + header_len) // _ALIGN) * _ALIGN
        return header, data_start
    except (KeyError, ValueError, struct.error, UnicodeDecodeError):
        return None


def header_span(buf_head: bytes) -> Optional[int]:
    """Total header bytes (magic + length + JSON) from the blob's first
    bytes, or None when they aren't a sidecar prefix."""
    if len(buf_head) < 12 or buf_head[:8] != _MAGIC:
        return None
    (header_len,) = struct.unpack_from("<I", buf_head, 8)
    return 12 + header_len


def deserialize(buf: bytes,
                want: Optional[set] = None) -> Optional[tuple[dict, int]]:
    """Parse a sidecar blob into ({name: (np view, ColumnEncoding)},
    n_rows).  Arrays are zero-copy views into `buf`.  `want` restricts
    which columns materialize (None = all); a wanted column missing from
    the file returns None (caller falls back to parquet)."""
    try:
        parsed = _parse_header(buf)
        if parsed is None:
            return None
        header, data_start = parsed
        n_rows = int(header["n_rows"])
        offsets = header["sections"]
        by_name = {m["name"]: m for m in header["columns"]}
        names = list(by_name) if want is None else [n for n in want]
        cols: dict = {}
        for name in names:
            m = by_name.get(name)
            if m is None:
                return None
            arrow_t = _ARROW_TYPES.get(m["arrow"])
            dtype = _NP_DTYPES.get(m["dtype"])
            if arrow_t is None or dtype is None:
                return None
            arr = np.frombuffer(buf, dtype=dtype, count=n_rows,
                                offset=data_start + offsets[m["section"]])
            if m["kind"] == "dict":
                dictionary = _load_dict(buf, m, data_start, offsets)
                if dictionary is None:
                    return None
                enc = encode.ColumnEncoding("dict", arrow_t,
                                            dictionary=dictionary)
            elif m["kind"] == "offset":
                enc = encode.ColumnEncoding("offset", arrow_t,
                                            epoch=int(m["epoch"]))
            else:
                enc = encode.ColumnEncoding("numeric", arrow_t)
            cols[name] = (arr, enc)
        return cols, n_rows
    except (KeyError, ValueError, IndexError, struct.error,
            json.JSONDecodeError, UnicodeDecodeError):
        return None


def _load_dict(buf: bytes, m: dict, data_start: int,
               offsets: list) -> Optional[np.ndarray]:
    sec = m.get("dict_section")
    dlen = int(m.get("dict_len", -1))
    if sec is None or dlen < 0:
        return None
    if m.get("dict_kind") == "i64":
        return np.frombuffer(buf, dtype=np.int64, count=dlen,
                             offset=data_start + offsets[sec])
    if m.get("dict_kind") == "blob":
        offs = np.frombuffer(buf, dtype=np.int32, count=dlen + 1,
                             offset=data_start + offsets[sec])
        base = data_start + offsets[sec + 1]
        # a wrapped/corrupt offsets section must read as INVALID, not
        # slice garbage: offsets are non-decreasing from 0 and the blob
        # must actually contain the last offset (truncated objects)
        if not _blob_offsets_ok(offs, len(buf) - base):
            return None
        # zero-copy view of the blob section; decode is one C++ pass
        return _decode_blob_dict(offs, memoryview(buf)[base:],
                                 m["arrow"] == "binary")
    return None


def _blob_offsets_ok(offs: np.ndarray, blob_len: int) -> bool:
    """Validate a blob dictionary's offsets section: starts at 0,
    non-decreasing (an int32 cumsum wraparound in a pre-fix writer shows
    up as a decrease or a negative), and the final offset fits the
    available blob bytes."""
    if len(offs) == 0 or int(offs[0]) != 0:
        return False
    if bool(np.any(offs[1:] < offs[:-1])):
        return False
    return int(offs[-1]) <= blob_len


# ---------------------------------------------------------------------------
# cross-SST concat (one segment = several sorted SST runs)
# ---------------------------------------------------------------------------


def _materialize_i64(arr: np.ndarray, enc: encode.ColumnEncoding
                     ) -> np.ndarray:
    if enc.kind == "offset":
        return arr.astype(np.int64) + enc.epoch
    if enc.kind == "dict":
        return enc.dictionary[arr]
    return arr.astype(np.int64)


# max dictionary size after a cross-SST union remap, matching
# encode._dictionary_encode: the merge kernel reserves INT32_MAX as its
# padding sentinel, so the largest code must stay strictly below it —
# a sentinel-sized union would alias real codes with padding
_MAX_DICT_CODES = 2**31 - 1


def concat_encoded(parts: list[dict], names: list[str]
                   ) -> Optional[tuple[dict, dict, int]]:
    """Concatenate per-SST encoded columns (in SST/run order — the merge
    relies on runs arriving in sequence order) into one column set:
    (columns, encodings, n_rows).

    dict columns re-map onto the sorted union dictionary (codes stay
    order-preserving); offset columns re-base to the smallest epoch when
    the combined span still fits int32; mixed/overflowing int64 columns
    fall back to materializing values and re-encoding.  Returns None
    only for irreconcilable arrow types."""
    if len(parts) == 1:
        cols = {n: parts[0][n][0] for n in names}
        encs = {n: parts[0][n][1] for n in names}
        return cols, encs, len(next(iter(cols.values()))) if names else 0

    out_cols: dict = {}
    out_encs: dict = {}
    n_total = 0
    for name in names:
        arrs = [p[name][0] for p in parts]
        encs = [p[name][1] for p in parts]
        atypes = {str(e.arrow_type) for e in encs}
        if len(atypes) != 1:
            return None
        arrow_t = encs[0].arrow_type
        kinds = {e.kind for e in encs}
        if kinds == {"numeric"}:
            out = np.concatenate(arrs)
            enc = encode.ColumnEncoding("numeric", arrow_t)
        elif kinds == {"offset"}:
            epochs = [e.epoch for e in encs]
            lo = min(epochs)
            hi = max(e.epoch + (int(a.max()) if len(a) else 0)
                     for a, e in zip(arrs, encs))
            if hi - lo < 2**31 - 1:
                out = np.concatenate([
                    a + np.int32(e.epoch - lo)
                    for a, e in zip(arrs, encs)])
                enc = encode.ColumnEncoding("offset", arrow_t, epoch=lo)
            else:
                out, enc = _concat_as_dict(arrs, encs, arrow_t)
                if enc is None:
                    return None
        elif kinds <= {"dict", "offset"} and all(
                e.kind == "offset" or e.dictionary.dtype == np.int64
                for e in encs):
            if kinds == {"dict"}:
                union = np.unique(np.concatenate(
                    [e.dictionary for e in encs]))
                if len(union) >= _MAX_DICT_CODES:
                    return None  # codes would alias the pad sentinel
                out = np.concatenate([
                    np.searchsorted(union, e.dictionary).astype(
                        np.int32)[a]
                    for a, e in zip(arrs, encs)])
                enc = encode.ColumnEncoding("dict", arrow_t,
                                            dictionary=union)
            else:
                out, enc = _concat_as_dict(arrs, encs, arrow_t)
                if enc is None:
                    return None
        elif kinds == {"dict"}:
            # string/bytes dictionaries: object-dtype union keeps codes
            # order-preserving (np.unique sorts); re-check the union
            # bound after remap — per-part dictionaries each fit, their
            # union may not
            union = np.unique(np.concatenate([e.dictionary for e in encs]))
            if len(union) >= _MAX_DICT_CODES:
                return None  # codes would alias the pad sentinel
            out = np.concatenate([
                np.searchsorted(union, e.dictionary).astype(np.int32)[a]
                for a, e in zip(arrs, encs)])
            enc = encode.ColumnEncoding("dict", arrow_t, dictionary=union)
        else:
            return None
        out_cols[name] = out
        out_encs[name] = enc
        n_total = len(out)
    return out_cols, out_encs, n_total


def _concat_as_dict(arrs: list, encs: list, arrow_t) -> tuple:
    """Fallback: materialize int64 values and dictionary-encode the
    concatenation (sorted-run fast path inside _dictionary_encode).
    (None, None) when the combined dictionary would reach the merge
    kernel's pad sentinel — caller returns None → parquet fallback."""
    values = np.concatenate([
        _materialize_i64(a, e) for a, e in zip(arrs, encs)])
    try:
        codes, dictionary = encode._dictionary_encode(values)
    except Error:
        return None, None  # dictionary overflow: not representable
    if len(dictionary) >= _MAX_DICT_CODES:
        return None, None
    return codes, encode.ColumnEncoding("dict", arrow_t,
                                        dictionary=dictionary)


def merge_parts(parts: list[dict]) -> Optional[tuple[dict, int]]:
    """Concat per-batch encoded parts into ONE part ({name: (arr,
    enc)}, n_rows), or None when the parts aren't mergeable.  Streamed
    writers (compaction) serialize the result into a sidecar AND admit
    it into the tier-2 encoded cache — same columns, one concat."""
    if not parts:
        return None
    names = list(parts[0].keys())
    if any(list(p.keys()) != names for p in parts[1:]):
        return None
    cc = concat_encoded(parts, names)
    if cc is None:
        return None
    cols, encs, n = cc
    return {nm: (cols[nm], encs[nm]) for nm in names}, n


# ---------------------------------------------------------------------------
# read-side assembly
# ---------------------------------------------------------------------------


@dataclass
class EncodedSegment:
    """One segment's rows straight from sidecars — the parquet-free twin
    of the Arrow table `_read_segment_table` returns.  Columns are
    unpadded, filtered (prune leaves applied), concatenated in SST/run
    order, ready for the merge's window prep.

    `pending_leaves` is set (a list, possibly empty) when the assemble
    DEFERRED the exact leaf mask for the device-decode dispatch
    (ops/device_decode.py): the fused program evaluates the conjunction
    in encoded space on device, so the host never compacts rows.  None
    means leaves were applied at assemble (the host-decode contract);
    a host fallback for a deferred segment must apply_leaves_host
    first."""

    columns: dict
    encodings: dict
    n: int
    names: list
    pending_leaves: Optional[list] = None
    # how many sorted SST runs were concatenated (None = unknown).  A
    # single-run segment — the post-compaction steady state — is
    # (pk, seq)-sorted BY CONSTRUCTION (both write paths sort before
    # the SST put; compaction emits merge-sorted), so the fused decode
    # routes it sort-free without even the one-pass host check
    # (ops/device_decode.py, scan_decode_sort_skipped_total)
    source_runs: Optional[int] = None
    # per-run row counts in concatenation order (sum == n); carried so
    # the fused decode can k-way-merge the presorted runs on device
    # instead of paying the full lax.sort (ops/merge.kway_merge_perm).
    # None = run boundaries unknown (single-part shortcuts, legacy
    # callers) — the decode then falls back to the sort route.
    run_lengths: Optional[tuple] = None

    @property
    def num_rows(self) -> int:
        return self.n

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.columns.values())


def apply_leaves_host(es: EncodedSegment) -> EncodedSegment:
    """Resolve a deferred leaf conjunction on host — the fallback when
    a device-decode-routed segment turns out ineligible at dispatch
    (unsupported encoding/dtype/budget): the exact mask+compaction
    assemble_parts would have done, so the host window path receives
    the filtered rows it expects.  No-op for segments with nothing
    pending."""
    from horaedb_tpu.ops import filter as filter_ops

    leaves = es.pending_leaves
    if not leaves:
        if leaves is not None:
            es.pending_leaves = None
        return es
    cols = es.columns
    run_lengths = es.run_lengths
    if es.n:
        batch = encode.DeviceBatch(columns=cols, encodings=es.encodings,
                                   n_valid=es.n, capacity=es.n)
        mask = np.asarray(filter_ops.eval_predicate(
            filter_ops.And(tuple(leaves)), batch))
        if not mask.all():
            idx = np.flatnonzero(mask)
            cols = {nm: a[idx] for nm, a in cols.items()}
            if run_lengths is not None:
                # survivors per run: the run boundaries stay valid for
                # the k-way route because compaction happens per run
                counts, pos = [], 0
                for rl in run_lengths:
                    counts.append(int(mask[pos:pos + rl].sum()))
                    pos += rl
                run_lengths = tuple(counts)
    n = len(next(iter(cols.values()))) if cols else 0
    return EncodedSegment(columns=cols, encodings=es.encodings, n=n,
                          names=es.names, pending_leaves=None,
                          source_runs=es.source_runs,
                          run_lengths=run_lengths)


def assemble_segment(bufs: list[bytes], columns: list,
                     leaves: Optional[list]) -> Optional[EncodedSegment]:
    """Parse one segment's sidecar blobs and assemble (see
    assemble_parts).  None on any parse/shape problem — the caller
    falls back to parquet."""
    leaves = leaves or []
    want = set(columns) | {lf.column for lf in leaves}
    parts = []
    for buf in bufs:
        got = deserialize(buf, want)
        if got is None:
            return None
        parts.append(got)
    return assemble_parts(parts, columns, leaves)


def assemble_parts(parts: list, columns: list,
                   leaves: Optional[list]) -> Optional[EncodedSegment]:
    """Apply the pruned-read leaf conjunction per SST part (row-level
    equivalent to the parquet path's read_pruned / filters=pushdown) and
    concatenate the runs in SST order.  `parts` are (cols, n) pairs as
    returned by deserialize()/load_sst_encoded()."""
    from horaedb_tpu.ops import filter as filter_ops

    leaves = leaves or []
    out_parts = []
    run_lengths = []
    for cols, n in parts:
        if leaves and n:
            batch = encode.DeviceBatch(
                columns={nm: a for nm, (a, _) in cols.items()},
                encodings={nm: e for nm, (_, e) in cols.items()},
                n_valid=n, capacity=n)
            mask = np.asarray(filter_ops.eval_predicate(
                filter_ops.And(tuple(leaves)), batch))
            if not mask.all():
                idx = np.flatnonzero(mask)
                cols = {nm: (a[idx], e) for nm, (a, e) in cols.items()}
                n = len(idx)
        out_parts.append({nm: cols[nm] for nm in columns})
        run_lengths.append(int(n))
    cc = concat_encoded(out_parts, list(columns))
    if cc is None:
        return None
    out_cols, out_encs, n_total = cc
    return EncodedSegment(columns=out_cols, encodings=out_encs,
                          n=n_total, names=list(columns),
                          source_runs=len(parts),
                          run_lengths=tuple(run_lengths))


# ---------------------------------------------------------------------------
# selective fetch (block pruning) — the sidecar's analogue of parquet
# row-group pruning for point queries on remote stores
# ---------------------------------------------------------------------------

# below this object size a whole-object GET beats extra round trips
_PARTIAL_MIN_BYTES = 1 << 20
# the header probe: big enough for any realistic header JSON, small
# enough that the probe's byte copy is noise.  Objects smaller than
# this arrive complete in the probe (short read, one request);
# unprunable larger objects pay probe + ONE plain GET — measured
# cheaper than a probe-sized head reused via range-read + concat,
# which copied the whole object twice on host-backed stores
_HEAD_BYTES = 64 << 10
# above this surviving-row fraction the partial fetch saves too little
# (range reads cost extra round trips; at half the bytes they still
# win — a point-query run straddling a block boundary keeps 2 blocks,
# which must stay under this at the common 4-8 block SST sizes)
_PARTIAL_MAX_FRAC = 0.5


def _block_mask_for_leaf(leaf, enc, mins: np.ndarray,
                         maxs: np.ndarray) -> Optional[np.ndarray]:
    """Conservative per-block MAY-match mask for one leaf over encoded
    -space block stats; None = this leaf cannot prune.  The inequality
    forms mirror ops.filter.eval_predicate exactly (dict codes have no
    '<=' constant, hence the side-specific thresholds)."""
    from horaedb_tpu.ops import filter as F
    from horaedb_tpu.ops.filter import (
        _const_code_exact,
        _const_code_lower,
        _const_code_upper,
    )

    if isinstance(leaf, F.Eq):
        c = _const_code_exact(enc, leaf.value)
        if c is None:
            return np.zeros(len(mins), dtype=bool)
        return (mins <= c) & (c <= maxs)
    if isinstance(leaf, F.In):
        codes = sorted(c for c in (_const_code_exact(enc, v)
                                   for v in leaf.values) if c is not None)
        if not codes:
            return np.zeros(len(mins), dtype=bool)
        arr = np.asarray(codes)
        idx = np.searchsorted(arr, mins)
        ok = idx < len(arr)
        out = np.zeros(len(mins), dtype=bool)
        out[ok] = arr[np.minimum(idx[ok], len(arr) - 1)] <= maxs[ok]
        return out
    if isinstance(leaf, F.Lt):
        return mins < _const_code_lower(enc, leaf.value)
    if isinstance(leaf, F.Le):
        t = _const_code_upper(enc, leaf.value)
        return mins < t if enc.kind == "dict" else mins <= t
    if isinstance(leaf, F.Gt):
        if enc.kind == "dict":
            return maxs >= _const_code_upper(enc, leaf.value)
        return maxs > _const_code_lower(enc, leaf.value)
    if isinstance(leaf, F.Ge):
        return maxs >= _const_code_lower(enc, leaf.value)
    if isinstance(leaf, F.TimeRangePred):
        lo = _const_code_lower(enc, leaf.start)
        hi = _const_code_lower(enc, leaf.end)
        return (maxs >= lo) & (mins < hi)
    return None


class _Sections:
    """Byte-range reader over one sidecar object with a tiny per-query
    cache, so a dictionary needed by both the pruning loop and the
    column load downloads once."""

    def __init__(self, store, path: str, data_start: int):
        self.store = store
        self.path = path
        self.data_start = data_start
        self._cache: dict = {}
        # decoded ColumnEncoding per column name — a leaf column that is
        # also a wanted column builds its (possibly large) dictionary
        # exactly once per SST load
        self.enc_cache: dict = {}

    async def fetch(self, offset: int, nbytes: int,
                    cache: bool = True) -> bytes:
        key = (offset, nbytes)
        got = self._cache.get(key)
        if got is None:
            lo = self.data_start + offset
            got = await self.store.get_range(self.path, lo, lo + nbytes)
            # data-column chunks pass cache=False: a streamed session
            # reads each window's disjoint ranges exactly once, and
            # pinning them would re-materialize the whole segment —
            # the residency streaming exists to avoid
            if cache and nbytes <= (4 << 20):
                self._cache[key] = got
        return got


def _decode_blob_dict(offs: np.ndarray, blob: bytes,
                      is_binary: bool) -> np.ndarray:
    """Object dictionary from (offsets, blob) in ONE C++ pass: wrap the
    validated sections as a zero-copy Arrow binary/utf8 array and let
    Arrow materialize the objects — the per-entry Python slice+decode
    loop this replaces was decode CPU per DICTIONARY entry, which at
    high series cardinality dominated sidecar assemble on low-core
    hosts (ROADMAP item 1 residual).  Callers have already validated
    the offsets (_blob_offsets_ok shape: start 0, non-decreasing,
    final offset within the blob)."""
    n = len(offs) - 1
    offs32 = np.ascontiguousarray(offs, dtype=np.int32)
    arr = pa.Array.from_buffers(
        pa.binary() if is_binary else pa.utf8(), n,
        [None, pa.py_buffer(offs32), pa.py_buffer(blob)])
    return arr.to_numpy(zero_copy_only=False)


async def _dict_for(meta: dict, header: dict, secs: _Sections,
                    runner=None) -> Optional[np.ndarray]:
    offsets = header["sections"]
    dlen = int(meta.get("dict_len", -1))
    sec = meta.get("dict_section")
    if sec is None or dlen < 0:
        return None
    if meta.get("dict_kind") == "i64":
        raw = await secs.fetch(offsets[sec], dlen * 8)
        return np.frombuffer(raw, dtype=np.int64, count=dlen)
    if meta.get("dict_kind") == "blob":
        raw = await secs.fetch(offsets[sec], (dlen + 1) * 4)
        offs = np.frombuffer(raw, dtype=np.int32, count=dlen + 1)
        if len(offs) == 0 or int(offs[0]) != 0 \
                or bool(np.any(offs[1:] < offs[:-1])):
            return None  # wrapped/corrupt offsets: invalid, not garbage
        blob = await secs.fetch(offsets[sec + 1], int(offs[-1]))
        if len(blob) < int(offs[-1]):
            return None  # truncated object
        is_binary = meta["arrow"] == "binary"
        if runner is not None:
            # per-entry Python decode loop: CPU-bound, off the loop
            return await runner(_decode_blob_dict, offs, blob, is_binary)
        return _decode_blob_dict(offs, blob, is_binary)
    return None


async def _encoding_for(meta: dict, header: dict, secs: _Sections,
                        runner=None):
    cached = secs.enc_cache.get(meta["name"])
    if cached is not None:
        return cached
    arrow_t = _ARROW_TYPES.get(meta["arrow"])
    if arrow_t is None:
        return None
    if meta["kind"] == "offset":
        enc = encode.ColumnEncoding("offset", arrow_t,
                                    epoch=int(meta["epoch"]))
    elif meta["kind"] == "numeric":
        enc = encode.ColumnEncoding("numeric", arrow_t)
    else:
        dictionary = await _dict_for(meta, header, secs, runner)
        if dictionary is None:
            return None
        enc = encode.ColumnEncoding("dict", arrow_t,
                                    dictionary=dictionary)
    secs.enc_cache[meta["name"]] = enc
    return enc


async def load_sst_encoded(store, path: str, want: set,
                           leaves: Optional[list], runner=None):
    """Fetch one SST's sidecar columns as ({name: (arr, enc)}, n_rows).

    When the leaf conjunction is selective, per-block stats narrow the
    fetch to candidate ROW ranges via store.get_range — whole columns
    are never downloaded for a point query over a big SST.  Pruning is
    conservative (block granularity); assemble_parts' exact leaf mask
    still applies after.  Falls back to a whole-object read (reusing
    the probed head bytes) when pruning cannot help.  `runner`
    (async callable(fn, *args), e.g. a worker-pool dispatch) carries
    the CPU-bound deserialize so callers keep it off the event loop.
    None = invalid sidecar (caller falls back to parquet);
    NotFoundError propagates."""
    async def _des(buf):
        if runner is None:
            return deserialize(buf, want)
        return await runner(deserialize, buf, want)

    leaves = leaves or []
    if not leaves:
        # nothing to prune with: one whole-object GET, no header probe
        return await _des(await store.get(path))
    head = await store.get_range(path, 0, _HEAD_BYTES)
    if len(head) < _HEAD_BYTES:
        # short read = the WHOLE object is already in hand; larger
        # objects that turn out unprunable pay probe + one plain GET
        # (the deliberate trade documented at _HEAD_BYTES — a plain
        # GET is zero-copy on host-backed stores)
        return await _des(head)
    try:
        span = header_span(head)
        if span is not None and span > len(head):
            head = bytes(head) + bytes(
                await store.get_range(path, len(head), span))
        parsed = _parse_header(head)
        if parsed is None:
            # not a (readable) header: a full read preserves the
            # corrupt-blob fallback semantics
            return await _des(await store.get(path))
        header, data_start = parsed
        n_rows = int(header["n_rows"])
        by_name = {m["name"]: m for m in header["columns"]}
        if any(nm not in by_name for nm in want):
            return None
        offsets = header["sections"]
        approx_bytes = data_start + (max(offsets) if offsets else 0)
        nblocks = -(-n_rows // BLOCK_ROWS) if n_rows else 0
        # leaf columns are always in `want` (callers build it that
        # way), so their presence was vetted by the want check above
        prunable = (leaves and nblocks > 1
                    and approx_bytes >= _PARTIAL_MIN_BYTES)
        if not prunable:
            return await _des(await store.get(path))
        return await _load_pruned(store, path, want, leaves, runner,
                                  header, data_start, n_rows, nblocks,
                                  _des)
    except (KeyError, IndexError, ValueError, TypeError, struct.error):
        # a magic-valid but malformed header (bad indices, truncated
        # sections) must read as INVALID — the caller memoizes the miss
        # permanently, same as an unparseable blob.  Store/IO errors
        # propagate instead: the caller treats those as TRANSIENT (no
        # memo), so one network hiccup can't blacklist a valid sidecar
        return None


async def _gather_or_cancel(*coros):
    """gather() that never strands a sibling: when one awaitable
    raises, the rest are cancelled AND awaited before the error
    propagates — an orphaned store read must not outlive its scan into
    table/engine teardown (the deterministic-teardown discipline the
    scan pipeline enforces at every stage boundary)."""
    tasks = [asyncio.ensure_future(c) for c in coros]
    try:
        return await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise


async def _leaf_block_mask(leaves, by_name, header, secs, nblocks,
                           runner):
    """(mask, pruned_any) over blocks for a leaf conjunction, or None
    when an encoding can't be built (caller falls back).

    Each stats-bearing column's (encoding, block stats) loads ONCE and
    the columns load CONCURRENTLY — on a 25 ms-latency store the old
    leaf-serial chain paid ~2 round trips per leaf, a visible slice of
    the pipelined cold scan's per-segment floor."""
    offsets = header["sections"]
    metas, seen = [], set()
    for leaf in leaves:
        meta = by_name[leaf.column]
        if "bstats_section" not in meta or leaf.column in seen:
            continue
        seen.add(leaf.column)
        metas.append(meta)

    async def load(meta):
        enc, raw = await _gather_or_cancel(
            _encoding_for(meta, header, secs, runner),
            secs.fetch(offsets[meta["bstats_section"]], nblocks * 8))
        return meta["name"], enc, raw

    by_col = {}
    for name, enc, raw in await _gather_or_cancel(
            *(load(m) for m in metas)):
        if enc is None:
            return None
        by_col[name] = (enc, np.frombuffer(raw, dtype=np.int32,
                                           count=2 * nblocks))
    mask = np.ones(nblocks, dtype=bool)
    pruned_any = False
    for leaf in leaves:
        got = by_col.get(leaf.column)
        if got is None:
            continue  # no block stats for this column: can't prune
        enc, stats = got
        lm = _block_mask_for_leaf(leaf, enc, stats[:nblocks],
                                  stats[nblocks:])
        if lm is not None:
            mask &= lm
            pruned_any = True
    return mask, pruned_any


def _mask_to_ranges(mask: np.ndarray, n_rows: int) -> list[tuple[int, int]]:
    """Contiguous surviving-block runs -> row ranges."""
    ranges: list[tuple[int, int]] = []
    b = 0
    nblocks = len(mask)
    while b < nblocks:
        if not mask[b]:
            b += 1
            continue
        b0 = b
        while b < nblocks and mask[b]:
            b += 1
        ranges.append((b0 * BLOCK_ROWS, min(b * BLOCK_ROWS, n_rows)))
    return ranges


async def _load_columns(by_name, header, secs, want, ranges, runner):
    """Fetch each wanted column's bytes for the row ranges; ({name:
    (arr, enc)}, total_rows) or None on an unsupported column."""
    offsets = header["sections"]
    total = sum(hi - lo for lo, hi in ranges)

    async def load_col(name: str):
        meta = by_name[name]
        dtype = _NP_DTYPES.get(meta["dtype"])
        enc = await _encoding_for(meta, header, secs, runner)
        if dtype is None or enc is None:
            return name, None
        base = offsets[meta["section"]]
        isz = np.dtype(dtype).itemsize
        chunks = await asyncio.gather(*(
            secs.fetch(base + isz * lo, isz * (hi - lo), cache=False)
            for lo, hi in ranges))
        arrs = [np.frombuffer(c, dtype=dtype) for c in chunks]
        if not arrs:
            # every block pruned (key absent from this SST): a valid
            # EMPTY part, not an error — concat/assemble handle it
            return name, (np.empty(0, dtype=dtype), enc)
        return name, (np.concatenate(arrs) if len(arrs) > 1 else arrs[0],
                      enc)

    loaded = await asyncio.gather(*(load_col(nm) for nm in want))
    cols = {}
    for name, got in loaded:
        if got is None:
            return None
        cols[name] = got
    return cols, total


async def _load_pruned(store, path, want, leaves, runner, header,
                       data_start, n_rows, nblocks, _des):
    by_name = {m["name"]: m for m in header["columns"]}
    secs = _Sections(store, path, data_start)
    got = await _leaf_block_mask(leaves, by_name, header, secs, nblocks,
                                 runner)
    if got is None:
        return await _des(await store.get(path))
    mask, pruned_any = got
    kept = int(mask.sum())
    if (not pruned_any or kept == nblocks
            or kept * BLOCK_ROWS > _PARTIAL_MAX_FRAC * n_rows):
        return await _des(await store.get(path))
    ranges = _mask_to_ranges(mask, n_rows)
    return await _load_columns(by_name, header, secs, want, ranges,
                               runner)


# ---------------------------------------------------------------------------
# streamed-segment serving: PK-value-range windows from block stats
# ---------------------------------------------------------------------------


class SstStreamSession:
    """Prepared per-SST sidecar session for STREAMED segments: the
    header (and, lazily, dictionaries) probe once; each window then
    loads only the blocks intersecting its PK value range.  Small
    objects that fit the probe parse once and serve every window from
    memory."""

    @classmethod
    async def open(cls, store, path: str, want: set, runner=None):
        """None = no usable sidecar (caller falls back to the parquet
        streamer); NotFoundError propagates."""
        head = await store.get_range(path, 0, _HEAD_BYTES)
        self = cls()
        self.store, self.path, self.runner = store, path, runner
        self.want = set(want)
        self._full = None
        try:
            if len(head) < _HEAD_BYTES:
                full = deserialize(head, self.want)
                if full is None:
                    return None
                self._full = full
                return self
            span = header_span(head)
            if span is not None and span > len(head):
                head = bytes(head) + bytes(
                    await store.get_range(path, len(head), span))
            parsed = _parse_header(head)
            if parsed is None:
                return None
            self.header, self.data_start = parsed
            self.n_rows = int(self.header["n_rows"])
            self.by_name = {m["name"]: m for m in self.header["columns"]}
            if any(nm not in self.by_name for nm in self.want):
                return None
            self.nblocks = -(-self.n_rows // BLOCK_ROWS) \
                if self.n_rows else 0
            self.secs = _Sections(store, path, self.data_start)
            return self
        except NotFoundError:
            raise
        except Exception:
            return None

    async def _dict_values(self, meta, codes: np.ndarray):
        """Dictionary entries for `codes` WITHOUT downloading the whole
        dictionary: ONE ranged read spanning [min(code), max(code)] for
        i64 dicts (tsid's case — ~8 B/entry over the needed span); blob
        dicts load whole via the enc cache (tag dictionaries are
        small).  Returns an array aligned with `codes`, or None."""
        if meta.get("dict_kind") == "i64":
            lo_c, hi_c = int(codes.min()), int(codes.max())
            off = self.header["sections"][meta["dict_section"]]
            raw = await self.secs.fetch(off + 8 * lo_c,
                                        8 * (hi_c - lo_c + 1))
            span = np.frombuffer(raw, dtype=np.int64,
                                 count=hi_c - lo_c + 1)
            return span[codes.astype(np.int64) - lo_c]
        enc = await _encoding_for(meta, self.header, self.secs,
                                  self.runner)
        if enc is None or enc.dictionary is None:
            return None
        return enc.dictionary[codes.astype(np.int64)]

    async def block_value_ranges(self, column: str):
        """Per-block (min_value, max_value, rows) of `column`, or None
        when stats/encodings can't support window planning."""
        if self._full is not None:
            cols = self._full[0]
            if column not in cols:
                return None
            arr, enc = cols[column]
            n = self._full[1]
            if n == 0:
                return []
            vals = encode.decode_column(arr, enc, n).to_numpy(
                zero_copy_only=False)
            return [(vals.min(), vals.max(), n)]
        meta = self.by_name.get(column)
        if meta is None or "bstats_section" not in meta:
            return None
        raw = await self.secs.fetch(
            self.header["sections"][meta["bstats_section"]],
            self.nblocks * 8)
        stats = np.frombuffer(raw, dtype=np.int32, count=2 * self.nblocks)
        mins_c, maxs_c = stats[:self.nblocks], stats[self.nblocks:]
        if meta["kind"] == "offset":
            mins_v = mins_c.astype(np.int64) + int(meta["epoch"])
            maxs_v = maxs_c.astype(np.int64) + int(meta["epoch"])
        elif meta["kind"] == "numeric":
            mins_v, maxs_v = mins_c, maxs_c
        elif meta["kind"] == "dict":
            mins_v = await self._dict_values(meta, mins_c)
            maxs_v = await self._dict_values(meta, maxs_c)
            if mins_v is None or maxs_v is None:
                return None
        else:
            return None
        out = []
        for b in range(self.nblocks):
            rows = min(BLOCK_ROWS, self.n_rows - b * BLOCK_ROWS)
            out.append((mins_v[b], maxs_v[b], rows))
        return out

    async def load_window(self, leaves: list):
        """(cols, n) of the blocks intersecting the leaf conjunction
        (window range leaves + the plan's own pushed leaves); the exact
        mask applies later in assemble_parts.  None on malformed."""
        if self._full is not None:
            return self._full
        got = await _leaf_block_mask(leaves, self.by_name, self.header,
                                     self.secs, self.nblocks, self.runner)
        if got is None:
            return None
        mask, _pruned = got
        ranges = _mask_to_ranges(mask, self.n_rows)
        return await _load_columns(self.by_name, self.header, self.secs,
                                   self.want, ranges, self.runner)


async def plan_stream_windows(sessions: list, pk_names: list,
                              max_window_rows: int):
    """(partition_column, [(lo, hi), ...]) value-range windows over the
    first PK column whose values vary, sized so the blocks intersecting
    each range hold ~max_window_rows rows (soft bound: straddling
    blocks count toward both sides).  Ranges are [lo, hi) with None as
    -inf/+inf; equal-PK rows always land in exactly one window, which
    is what cross-SST dedup requires.  None = planning impossible
    (missing stats): fall back to the parquet streamer."""
    for col in pk_names:
        infos = await asyncio.gather(*(
            s.block_value_ranges(col) for s in sessions))
        if any(info is None for info in infos):
            return None
        blocks = [blk for info in infos for blk in info]
        if not blocks:
            return col, [(None, None)]
        lo = min(b[0] for b in blocks)
        hi = max(b[1] for b in blocks)
        if lo == hi:
            continue  # constant column cannot bound anything
        blocks.sort(key=lambda b: (b[0], b[1]))
        bounds: list = []
        acc = 0
        for bmin, _bmax, rows in blocks:
            if acc >= max_window_rows and (not bounds
                                           or bmin > bounds[-1]):
                # cut BETWEEN blocks at this block's min value: works
                # for ints and strings alike, no +1 arithmetic
                bounds.append(bmin)
                acc = 0
            acc += rows
        edges = [None] + bounds + [None]
        return col, list(zip(edges[:-1], edges[1:]))
    return None  # every PK constant: nothing to window on
